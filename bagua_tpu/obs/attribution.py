"""Device-time attribution: which bucket's collective costs what ON DEVICE.

The host spans (PR 7) honestly time only the host; the device program is
opaque to them — a bucket's ``trace/bucket_collective`` span documents the
*launch schedule* at trace time, not where device microseconds go.  The
profiler's xplane has the other half: per-occurrence device events for
every XLA op, including the collectives (``all-reduce-start`` etc.).  This
module joins the two:

* host side — the overlap scheduler's per-bucket launch spans carry
  ``bucket`` index and ``bytes``;
* device side — :func:`bagua_tpu.profiling.parse_xplane_comm_events`
  yields the communication occurrences in device-time order
  (:func:`~bagua_tpu.profiling.is_comm_op` is the wire filter).

When the trace's per-step comm occurrence count matches the bucket count,
occurrences map to buckets positionally (the launch order IS the device
issue order under XLA's in-order collective streams) and the report names
per-bucket device comm seconds — the measured signal the ROADMAP's
autotune-v2 bucket-size search scores against.  Otherwise (fused
collectives, chunked rings multiplying occurrences) the report degrades to
per-op aggregates, saying so.

On cpu-sim there is no TPU plane — the report is
``{"available": False, "rationale": ...}``, the same null-with-rationale
convention as ``trace_overlap``: a number that measures nothing real is
worse than an honest null.

The trainer runs :func:`attribute_device_comm` once when a
``BAGUA_PROFILE_DIR`` auto-capture window closes, publishes the summary
gauges (``obs/device_comm_s_per_step``, ``obs/device_overlap_fraction``)
and hands the record to :func:`bagua_tpu.obs.export.note_device_attribution`
so it rides the per-rank obs summary → health beacon → fleet snapshot.
"""

from __future__ import annotations

import logging
import re
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

#: slice-local (ICI) stages of a two-level schedule, by XLA instruction
#: name; everything else on the wire of such a schedule is the cross-slice
#: allreduce (or the scalar loss sync, which also crosses the boundary)
_ICI_STAGE = re.compile(r"(reduce-scatter|all-gather)")


def _contiguous_triples(wire: List[dict], per_step: int) -> bool:
    """Whether the per-step occurrence sequence is (reduce-scatter,
    allreduce, allgather) per bucket, contiguously — the shape the
    positional per-bucket split requires.  Families that issue the gather
    leg in a later phase (ZeRO's optimizer-update allgathers) interleave
    differently; their per-bucket split must degrade, not mis-attribute."""
    stage_rx = (re.compile(r"reduce-scatter"), re.compile(r"all-reduce"),
                re.compile(r"all-gather"))
    for i, ev in enumerate(wire):
        if not stage_rx[(i % per_step) % 3].search(ev["name"]):
            return False
    return True

__all__ = ["attribute_device_comm", "bucket_launches_from_ring",
           "UNAVAILABLE_RATIONALE"]

UNAVAILABLE_RATIONALE = (
    "trace has no TPU device plane or no communication ops — device-time "
    "attribution needs real device events (cpu-sim collectives are "
    "single-host memcpy); host spans still cover the dispatch side"
)


def bucket_launches_from_ring(spans: Optional[List[dict]] = None
                              ) -> List[dict]:
    """The newest per-bucket launch schedule from the span ring: one entry
    per ``trace/bucket_collective`` span (deduped by bucket index, last
    trace wins — a recompile re-records the schedule), sorted by launch
    order.  ``[{"bucket", "bytes", "tier", "ici_bytes", "dcn_bytes"},
    ...]``; ``tier`` is ``"two_level"`` for the hierarchical decomposition
    (three collectives per bucket: ICI reduce-scatter, DCN allreduce, ICI
    allgather) and ``"flat"`` for one fused collective.  [] when the
    overlap scheduler never ran (serialized path has one fused comm stage,
    not per-bucket launches)."""
    if spans is None:
        from . import spans as _spans

        spans = _spans.recorder.snapshot()
    by_bucket: Dict[int, dict] = {}
    for span in spans:
        if span.get("name") != "trace/bucket_collective":
            continue
        attrs = span.get("attrs") or {}
        if "bucket" not in attrs:
            continue
        by_bucket[int(attrs["bucket"])] = {
            "bucket": int(attrs["bucket"]),
            "bytes": int(attrs.get("bytes") or 0),
            "tier": str(attrs.get("tier") or "flat"),
            "ici_bytes": int(attrs.get("ici_bytes")
                             or attrs.get("bytes") or 0),
            "dcn_bytes": int(attrs.get("dcn_bytes") or 0),
            "t0": span.get("t0", 0.0),
        }
    out = sorted(by_bucket.values(), key=lambda e: e["t0"])
    for e in out:
        e.pop("t0", None)
    return out


def attribute_device_comm(log_dir: str,
                          bucket_launches: Optional[List[dict]] = None
                          ) -> dict:
    """Attribute device communication time from a profiler trace directory.

    Returns (always a dict, never raises):

    * unavailable — ``{"available": False, "rationale": ...}``;
    * available — ``{"available": True, "step_s", "comm_s_per_step",
      "compute_s_per_step", "overlap_fraction", "per_bucket": [...] |
      None, "per_bucket_rationale": ... when per_bucket is None,
      "per_op": [...]}``.

    ``per_bucket`` entries are ``{"bucket", "bytes", "device_comm_s"}``
    (mean device seconds per step for that bucket's collective).
    """
    from .. import profiling as _prof

    try:
        newest = _prof._newest_xplane(log_dir)
        if newest is None:
            return {"available": False, "rationale": UNAVAILABLE_RATIONALE}
        comm = _prof.parse_xplane_comm_events(newest)
        overlap = _prof.parse_xplane_overlap(newest)
    except Exception as e:  # noqa: BLE001 - proto availability varies
        return {"available": False,
                "rationale": f"xplane parse unavailable: {e}"}
    if not comm or not comm.get("events"):
        return {"available": False, "rationale": UNAVAILABLE_RATIONALE}

    events = comm["events"]
    n_steps = int(comm.get("n_steps") or 0)
    record: dict = {"available": True}
    if overlap:
        record.update({
            "step_s": overlap["step_s"],
            "comm_s_per_step": overlap["comm_s_per_step"],
            "compute_s_per_step": overlap["compute_s_per_step"],
            "overlap_fraction": overlap["overlap_fraction"],
        })
    # per-op aggregate: always reportable (the -start half carries wire
    # time; -done is the wait)
    per_op: Dict[str, dict] = {}
    for ev in events:
        rec = per_op.setdefault(ev["name"],
                                {"op": ev["name"], "time_s": 0.0,
                                 "occurrences": 0})
        rec["time_s"] += ev["dur_s"]
        rec["occurrences"] += 1
    for rec in per_op.values():
        rec["time_s"] = round(rec["time_s"], 9)
    record["per_op"] = sorted(per_op.values(),
                              key=lambda r: -r["time_s"])

    if bucket_launches is None:
        bucket_launches = bucket_launches_from_ring()
    record["per_bucket"] = None
    n_buckets = len(bucket_launches)
    if not n_buckets:
        record["per_bucket_rationale"] = (
            "no per-bucket launch spans in the ring (serialized comm "
            "stage is one fused launch) — per-op totals above are the "
            "attribution"
        )
        return record
    # positional match: wire-time occurrences only (the -done waits say
    # where the schedule stalled, not what the bucket cost).  XLA
    # uniquifies instruction names, so the done halves appear as
    # `all-reduce-done`, `all-reduce-done.1`, ... — match the infix, not
    # the suffix
    wire = [e for e in events if "-done" not in e["name"]]
    two_level = bool(bucket_launches) and all(
        l.get("tier") == "two_level" for l in bucket_launches)
    if two_level and n_steps:
        # tier totals by op NAME, not position: a two-level schedule's
        # slice-local stages are reduce-scatter/all-gather instructions
        # and its only cross-slice stage is the inter allreduce — name
        # classification is robust to families that issue the gather legs
        # outside the overlap window (ZeRO's optimizer-phase allgathers),
        # where positional triple-grouping would mis-tier them.  The
        # scalar loss allreduce (4 B, spans both axes) lands in the DCN
        # class — it does cross the slice boundary.
        ici_total = sum(e["dur_s"] for e in wire
                        if _ICI_STAGE.search(e["name"]))
        dcn_total = sum(e["dur_s"] for e in wire
                        if not _ICI_STAGE.search(e["name"]))
        record["comm_ici_s_per_step"] = round(ici_total / n_steps, 9)
        record["comm_dcn_s_per_step"] = round(dcn_total / n_steps, 9)
    #: device occurrences one bucket launch expands to, by tier shape:
    #: flat = one fused collective; two_level = ICI reduce-scatter, DCN
    #: allreduce, ICI allgather
    ops_per_bucket = 3 if two_level and n_buckets else 1
    if n_steps and len(wire) % n_steps == 0 \
            and len(wire) // n_steps == n_buckets * ops_per_bucket \
            and (ops_per_bucket == 1 or _contiguous_triples(wire,
                                                            len(wire)
                                                            // n_steps)):
        per_step = len(wire) // n_steps
        totals = [0.0] * per_step
        for i, ev in enumerate(wire):
            totals[i % per_step] += ev["dur_s"]
        per_bucket = []
        for pos, launch in enumerate(bucket_launches):
            row = {"bucket": launch["bucket"], "bytes": launch["bytes"],
                   "tier": launch.get("tier", "flat")}
            if ops_per_bucket == 3:
                rs, ar, ag = totals[3 * pos: 3 * pos + 3]
                row["device_ici_s"] = round((rs + ag) / n_steps, 9)
                row["device_dcn_s"] = round(ar / n_steps, 9)
                row["device_comm_s"] = round((rs + ar + ag) / n_steps, 9)
            else:
                row["device_comm_s"] = round(totals[pos] / n_steps, 9)
            per_bucket.append(row)
        record["per_bucket"] = per_bucket
    else:
        record["per_bucket_rationale"] = (
            f"{len(wire)} device comm occurrences across "
            f"{n_steps or '?'} steps do not map "
            f"{ops_per_bucket}:1 onto {n_buckets} "
            "bucket launches as contiguous per-bucket stages (fused, "
            "chunked, or phase-split collectives) — per-op totals above "
            "are the attribution"
            + (" (per-tier totals still reported: those classify by op "
               "name, not position)" if two_level and n_steps else "")
        )
    return record
