"""Metrics exporter + fleet view: the sidecar-shaped half of the obs plane.

The reference runs a Flask autotune sidecar every rank POSTs metrics to;
here the consumers are files an operator (or the ROADMAP's autotune-v2
scorer) can tail:

* :data:`METRIC_REGISTRY` — every counter/gauge name the package emits,
  declared once with kind and doc (mirror of ``env.ENV_REGISTRY``).
  ``bagua-lint``'s ``unregistered-counter`` rule rejects ``counters.incr``
  /``set_gauge`` call sites whose literal name is not declared here, so a
  typo'd metric name cannot silently fork a counter.
* :class:`MetricsExporter` — a background thread that periodically merges
  ``telemetry.counters``, the trainer's latest ``step_metrics``, and the
  ``measured_step_dt`` history into ``metrics.jsonl`` (one snapshot per
  line) and ``metrics.prom`` (a Prometheus textfile) under
  ``BAGUA_OBS_EXPORT_DIR``.
* **fleet view** — each worker's per-rank summary
  (:func:`local_obs_summary`: step, step-dt percentiles, staleness, skip
  counts) rides the worker's health beacon onto the launcher's lease
  heartbeat; the coordinator-side monitor merges every member's payload
  into one fleet snapshot (:func:`write_fleet_snapshot`,
  ``BAGUA_OBS_FLEET_OUT``).

Import-light (no jax): the launcher's monitor writes the fleet snapshot and
must not pay a jax import for it.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import re
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from .. import env as _env
from ..faults.inject import FAULT_POINTS
from ..telemetry import counters

logger = logging.getLogger(__name__)

#: goodput-ledger attribution classes (single source of truth for the
#: `obs/ledger/<cls>_s` gauge names; :mod:`bagua_tpu.obs.ledger` — a
#: ``python -m`` entry point this module must not import eagerly — reads
#: them from here)
LEDGER_CLASSES = (
    "productive_step", "compile", "state_migration", "checkpoint",
    "rendezvous", "catchup_sync", "rewind", "stall",
    # serving classes (docs/serving.md): prefill/decode are a serving
    # replica's goodput; batch-formation idle and weight loads are its
    # named badput
    "prefill", "decode", "batch_formation_idle", "weight_load",
    "idle_other",
)


def _ledger():
    # lazy: obs.ledger is a CLI entry point; importing it from package
    # import time would leave runpy executing a second module copy
    from .ledger import ledger

    return ledger

__all__ = [
    "METRIC_REGISTRY", "Metric", "LEDGER_CLASSES",
    "is_registered", "any_registered_matches",
    "MetricsExporter", "render_prometheus", "prepared_snapshot",
    "local_obs_summary",
    "note_step", "note_step_metrics", "note_anomaly",
    "note_device_attribution", "last_device_attribution",
    "note_mfu", "last_mfu", "note_hbm_footprint", "last_hbm_footprint",
    "note_hbm_live", "last_hbm_live", "note_ckpt_directory",
    "build_fleet_record", "write_fleet_snapshot", "validate_fleet_snapshot",
    "FLEET_SCHEMA",
]


# ---- metric registry ------------------------------------------------------


@dataclass(frozen=True)
class Metric:
    """One declared metric: the single source of truth for its kind and
    operator-facing documentation (the counter analog of ``env.EnvVar``)."""

    name: str
    kind: str  # "counter" (monotonic event count) | "gauge" (last value)
    doc: str


METRIC_REGISTRY: Dict[str, Metric] = {}


def _declare(name: str, kind: str, doc: str) -> None:
    assert kind in ("counter", "gauge"), kind
    METRIC_REGISTRY[name] = Metric(name, kind, doc)


# -- communication / watchdog --
_declare("comm/aborts", "counter",
         "Cooperative abort flag raises (watchdog fire, grad-guard abort, "
         "user abort()).")
_declare("comm/abort_resets", "counter",
         "reset_abort() recoveries after an abort.")
# -- gradient-health sentinel --
_declare("grad_guard/unhealthy_steps", "counter",
         "Steps whose gradients contained NaN/Inf (any policy).")
_declare("grad_guard/skipped_steps", "counter",
         "Unhealthy steps rewound by policy `skip`.")
_declare("grad_guard/aborts", "counter",
         "Guard escalations to the comm abort flag (policy `abort`, or the "
         "consecutive-skip budget).")
# -- checkpoint integrity chain --
_declare("ckpt/integrity_failures", "counter",
         "Checkpoints that failed verification at restore (unreadable step, "
         "torn sidecar, content-digest mismatch).")
_declare("ckpt/fallback_restores", "counter",
         "Restores that landed on an older step after newer checkpoint(s) "
         "failed verification.")
_declare("ckpt/verified_restores", "counter",
         "Restores whose content digest verified against the save-time "
         "record.")
_declare("ckpt/stacked_resize_restores", "counter",
         "Stacked (per-rank) checkpoints re-tiled onto a resized world.")
# -- async model averaging --
_declare("async/rounds_launched", "counter",
         "Averaging rounds launched at negotiated boundaries.")
_declare("async/rounds_applied", "counter",
         "Rounds whose delta was applied on this rank.")
_declare("async/rounds_dropped", "counter",
         "Rounds discarded without applying (rewind veto, partition, "
         "catch-up supersede, abort).")
_declare("async/missed_boundaries", "counter",
         "This-rank round drops that count as fenceable health events.")
_declare("async/catchup_syncs", "counter",
         "Forced synchronous catch-up averages (staleness cap, checkpoint "
         "sync).")
_declare("async/staleness_max", "gauge",
         "Worst rank's applied-round lag observed at the last negotiated "
         "boundary.")
_declare("async/aborts_negotiated", "counter",
         "Negotiated ABORT transitions of the averaging control loop.")
_declare("async/resumes_negotiated", "counter",
         "Negotiated RESUME transitions of the averaging control loop.")
# -- elastic membership / launcher --
_declare("elastic/rounds", "counter", "Rendezvous rounds completed.")
_declare("elastic/world_nnodes", "gauge",
         "Node count of the most recently negotiated world.")
_declare("elastic/failures", "counter", "Worker-crash stop events.")
_declare("elastic/lease_expired", "counter", "Lease-expiry stop events.")
_declare("elastic/leaves", "counter",
         "Deliberate-departure stop events (watchdog exit, ^C).")
_declare("elastic/resizes", "counter",
         "Coordinated resize stop events (standby join).")
_declare("elastic/health_fenced", "counter",
         "Members expelled by the heartbeat health fence.")
_declare("elastic/restarts", "counter", "Elastic gang restarts consumed.")
_declare("elastic/excluded", "counter",
         "Rounds this node was excluded from (waited as standby).")
_declare("elastic/lease_rearms", "counter",
         "Member leases re-armed at coordinator takeover (the promotion "
         "grace that prevents a coordinator blip from mass-expiring "
         "healthy workers).")
# -- replicated restart store / coordinator failover --
_declare("store/failovers", "counter",
         "Restart-store client failovers to another endpoint (the previous "
         "endpoint died, wedged, or answered with a write fence).")
_declare("store/op_deadline_exceeded", "counter",
         "Restart-store ops abandoned because the per-op retry deadline "
         "budget (BAGUA_RESTART_STORE_OP_DEADLINE_S) was exhausted.")
_declare("store/fenced_writes", "counter",
         "Writes refused by a demoted/standby store server (generation "
         "fence) as observed by this client.")
_declare("store/promotions", "counter",
         "Store-generation promotions this client performed (bumping a "
         "standby endpoint to primary during failover).")
_declare("coord/takeovers", "counter",
         "Standby coordinator promotions to the active coordinator role "
         "after the leadership lease went stale.")
# -- fault injection (one armed/fired/recovered triple per point) --
for _point in FAULT_POINTS:
    _declare(f"faults/{_point}/armed", "counter",
             f"`{_point}` fault specs armed.")
    _declare(f"faults/{_point}/fired", "counter",
             f"`{_point}` faults fired.")
    _declare(f"faults/{_point}/recovered", "counter",
             f"`{_point}` faults the defense path recovered from.")
# -- observability plane self-accounting --
_declare("obs/flight_dumps", "counter",
         "Flight-recorder post-mortem dumps written.")
_declare("obs/flight_dumps_pruned", "counter",
         "Flight-recorder dumps removed by the BAGUA_OBS_DUMP_MAX_FILES "
         "retention cap (oldest-first; a long run with recurring "
         "throttled faults no longer grows the dump dir without limit).")
_declare("obs/http_requests", "counter",
         "Requests served by this process's HTTP status plane "
         "(bagua_tpu.obs.http: /metrics, /healthz, /ledger, and the "
         "coordinator's /fleet and /history).")
_declare("obs/http_port", "gauge",
         "Port the HTTP status plane actually bound (differs from "
         "BAGUA_OBS_HTTP_PORT when the configured port was taken and "
         "the server fell back to an ephemeral one).")
_declare("obs/export_snapshots", "counter",
         "Metrics-exporter snapshots written (jsonl line + prom file).")
_declare("obs/spans_dropped", "gauge",
         "Spans evicted from this process's bounded span ring "
         "(BAGUA_OBS_RING) — non-zero means a merged timeline's track is "
         "a tail, not the whole run.")
# -- step-time anomaly detection (docs/observability.md) --
_declare("obs/step_anomalies", "counter",
         "Steps flagged by the rolling median/MAD step-time anomaly "
         "detector (raw host cadence far outside this rank's baseline).")
_declare("obs/perf_hints", "counter",
         "Perf hints published for the autotune service (anomaly "
         "detections and other environmental performance signals).")
# -- device-time attribution (profiler-derived, TPU only) --
_declare("obs/device_comm_s_per_step", "gauge",
         "Measured device communication seconds per step from the last "
         "closed profiler window (null-with-rationale on cpu-sim).")
_declare("obs/device_comm_ici_s_per_step", "gauge",
         "Slice-local (ICI-tier) share of the measured device comm seconds "
         "per step: the reduce-scatter + allgather stages of the "
         "hierarchical two-level decomposition (docs/hierarchical.md); "
         "present only when the per-bucket positional match held on a "
         "two-level launch schedule.")
_declare("obs/device_comm_dcn_s_per_step", "gauge",
         "Cross-slice (DCN-tier) share of the measured device comm seconds "
         "per step: the inter-slice allreduce stage riding the slow link — "
         "the number the two-level decomposition exists to shrink.")
_declare("obs/device_overlap_fraction", "gauge",
         "Fraction of device comm time hidden under compute in the last "
         "closed profiler window (parse_xplane_overlap).")
# -- efficiency plane: goodput ledger + MFU + HBM accounting --
for _cls in LEDGER_CLASSES:
    _declare(f"obs/ledger/{_cls}_s", "gauge",
             f"Cumulative wall-clock seconds the goodput ledger attributes "
             f"to the `{_cls}` class on this rank (docs/observability.md, "
             "efficiency plane).")
_declare("obs/ledger/wall_s", "gauge",
         "Total wall-clock seconds the goodput ledger has covered on this "
         "rank (the conservation denominator: classes sum to this within "
         "1%).")
_declare("obs/goodput_fraction", "gauge",
         "Fraction of this rank's ledger wall spent making forward "
         "progress — productive train steps, plus a serving replica's "
         "prefill/decode walls (the GOODPUT_CLASSES) — the fleet's "
         "headline efficiency number (everything else is badput with a "
         "named class).")
_declare("obs/mfu", "gauge",
         "Model FLOPS utilization of the current compiled step: cached "
         "cost-model flops / measured step cadence / peak silicon FLOP/s "
         "(absent on cpu-sim — the summary carries a rationale instead).")
_declare("obs/cost_analysis_unavailable", "counter",
         "step_cost_analysis calls that returned {} because the backend "
         "offered no cost model (one count per compiled program, not per "
         "call) — the formerly silent swallow-all, now visible fleet-wide.")
_declare("obs/hbm_static_footprint_bytes", "gauge",
         "Static per-device HBM footprint estimate: resident TrainState "
         "shard bytes + one set of per-bucket gradient flats "
         "(bagua_tpu.obs.memory.static_footprint; exact on cpu-sim).")
_declare("obs/hbm_peak_bytes", "gauge",
         "Live device.memory_stats() peak_bytes_in_use from the last "
         "beacon-cadence poll (real TPU only; absent on cpu-sim).")
_declare("obs/hbm_headroom_bytes", "gauge",
         "bytes_limit minus the live peak from the last memory poll — the "
         "capacity-planning margin (real TPU only).")
# -- telemetry historian trend gauges (coordinator-side; docs/observability
# -- .md): windowed derivatives over the fleet-snapshot stream, published
# -- back into each snapshot and consumed by the autopilot's trend rules
_declare("obs/goodput_slope", "gauge",
         "Fleet-worst least-squares slope of goodput_fraction per second "
         "over the historian's trend window (BAGUA_OBS_HISTORIAN_WINDOW_S)"
         " — negative and sustained means the fleet is losing efficiency, "
         "before any absolute SLO trips.")
_declare("obs/hbm_headroom_slope", "gauge",
         "Fleet-worst least-squares slope of the live HBM headroom in "
         "bytes per second over the historian's trend window — a negative "
         "slope projects exhaustion (headroom / -slope seconds out), the "
         "evidence behind the autopilot's pre-OOM resize rule.")
_declare("obs/dcn_comm_share", "gauge",
         "Fleet-worst share of the step wall spent in cross-slice DCN "
         "device seconds (windowed mean device_comm_dcn_s_per_step over "
         "windowed mean step_dt_p50) — the number the hierarchical "
         "two-level decomposition exists to shrink; sustained dominance "
         "triggers the autopilot's compression-escalation hint.")


# -- fleet autopilot (docs/autopilot.md) --
_declare("autopilot/snapshots", "counter",
         "Fleet snapshots the autopilot's policy engine evaluated.")
_declare("autopilot/stale_snapshots", "counter",
         "Fleet snapshots the policy engine REFUSED to decide on because "
         "they were older than BAGUA_AUTOPILOT_STALENESS_S — a wedged "
         "snapshot writer must not cause actions from stale evidence.")
_declare("autopilot/decisions", "counter",
         "Actions the pure decision core emitted (observe AND act mode — "
         "a decision is counted whether or not it actuates).")
_declare("autopilot/actions_actuated", "counter",
         "Decided actions actually actuated (act mode only).")
_declare("autopilot/observed_only", "counter",
         "Decided actions logged without actuation (observe mode — the "
         "dry-run rollout counter).")
_declare("autopilot/suppressed_cooldown", "counter",
         "Rule firings suppressed because their action kind was inside "
         "its cooldown window.")
_declare("autopilot/suppressed_budget", "counter",
         "Rule firings suppressed because the global action budget "
         "(BAGUA_AUTOPILOT_BUDGET) was exhausted.")
_declare("autopilot/fences", "counter",
         "Chronic-straggler fence decisions (rank health-fenced, world "
         "resized down through the elastic epoch machinery).")
_declare("autopilot/retunes", "counter",
         "Retune decisions (collective-dominant victims and the ladder's "
         "hint/retune rungs) delivered as autotune perf hints with "
         "service-side re-measure.")
_declare("autopilot/family_switches", "counter",
         "Escalation-ladder algorithm-family-switch decisions (commanded "
         "through the autotune recommendation path; the trainers' switch "
         "is a re-jit, not a restart).")
_declare("autopilot/resizes", "counter",
         "Escalation-ladder terminal resize decisions (worst-goodput "
         "node removed through the fence/epoch machinery).")
_declare("autopilot/compress_hints", "counter",
         "DCN-dominance trend-rule decisions: compression-family "
         "escalation hints (compress the slow cross-slice tier) delivered "
         "through the autotune perf-hint channel.  Fires only from "
         "historian trend windows (BAGUA_OBS_HISTORIAN=on).")
_declare("autopilot/quarantines", "counter",
         "Checkpoint storage paths quarantined after repeated integrity "
         "failures/fallback restores (saves redirect).")
_declare("autopilot/escalation_rung", "gauge",
         "Current SLO-escalation ladder rung (0 = healthy, 1 hint, "
         "2 retune, 3 family switch, 4 resize).")
_declare("autopilot/state_persists", "counter",
         "Policy-state snapshots persisted to the restart store (the "
         "coordinator-restart idempotence channel: cooldowns, rung, "
         "quarantined paths survive a relaunch).")
# -- serving plane (docs/serving.md) --
_declare("serve/requests_admitted", "counter",
         "Requests admitted from the queue into an engine batch slot "
         "(continuous batching: admission happens mid-batch, every tick).")
_declare("serve/requests_completed", "counter",
         "Requests that produced their full output and were evicted.")
_declare("serve/requests_preempted", "counter",
         "Slots preempted on page-pool exhaustion (pages reclaimed, the "
         "request re-queued for recompute — the backpressure path).")
_declare("serve/requests_rejected", "counter",
         "Submissions refused at the admission-queue depth cap "
         "(ServeQueueFull).")
_declare("serve/ticks", "counter",
         "Scheduler ticks executed (one batched decode step each, when "
         "any slot is active).")
_declare("serve/prefill_tokens", "counter",
         "Prompt tokens written into the paged KV-cache (teacher-forced "
         "tick feeds + chunked prefill).")
_declare("serve/prefill_chunks", "counter",
         "Chunked-prefill program invocations (BAGUA_SERVE_PREFILL_CHUNK "
         "tokens of one slot per call).")
_declare("serve/decode_tokens", "counter",
         "Output tokens sampled — decode ticks plus the chunked-prefill "
         "call that produces a request's first token.  Counts WORK, not "
         "delivery: a preempted request's recomputed tokens count each "
         "time they are sampled (equals delivered output tokens only "
         "when serve/requests_preempted is 0).")
_declare("serve/pool_exhausted", "counter",
         "Page-allocation attempts that found the pool empty (each one "
         "queues or preempts — never crashes).")
_declare("serve/weight_loads", "counter",
         "Integrity-verified serving weight loads "
         "(serve.loader.load_serving_params).")
_declare("serve/queue_depth", "gauge",
         "Requests currently waiting in the admission queue.")
_declare("serve/active_slots", "gauge",
         "Batch slots currently running a request.")
_declare("serve/pages_in_use", "gauge",
         "KV-cache pages currently allocated (excludes the 2 reserved "
         "pages).")
_declare("serve/ttft_last_s", "gauge",
         "Time-to-first-token of the most recently started request "
         "(submit -> first sampled token); percentiles live in "
         "BENCH_SERVE.json.")
_declare("serve/tpot_last_s", "gauge",
         "Time-per-output-token of the most recently completed request "
         "(after its first token).")


def is_registered(name: str) -> bool:
    return name in METRIC_REGISTRY


def render_metrics_md() -> str:
    """The ``docs/metrics.md`` reference table, emitted straight from
    :data:`METRIC_REGISTRY` (``scripts/gen_env_docs.py`` writes/checks it
    alongside the env-var table)."""
    lines = [
        "# Metrics",
        "",
        "Generated by `scripts/gen_env_docs.py` from "
        "`bagua_tpu.obs.export.METRIC_REGISTRY` — do not edit by hand.",
        "",
        "Every counter/gauge the package emits is declared in the registry;",
        "`bagua-lint`'s `unregistered-counter` rule fails CI on any",
        "`counters.incr`/`set_gauge` call site whose name is not declared",
        "here, so the table cannot drift from the write sites.  Names export",
        "to Prometheus as `bagua_<name>` with `/` and `.` mangled to `_`",
        "(see `prometheus_name`).",
        "",
        "| Metric | Kind | Description |",
        "| --- | --- | --- |",
    ]
    for name in sorted(METRIC_REGISTRY):
        m = METRIC_REGISTRY[name]
        doc = " ".join(m.doc.split())
        lines.append(f"| `{name}` | {m.kind} | {doc} |")
    return "\n".join(lines) + "\n"


def any_registered_matches(pattern: str) -> bool:
    """Whether some registered name fully matches ``pattern`` (a regex) —
    how the ``unregistered-counter`` lint rule validates f-string call
    sites like ``f"faults/{point}/fired"``."""
    rx = re.compile(pattern)
    return any(rx.fullmatch(name) for name in METRIC_REGISTRY)


# ---- per-rank obs summary (the fleet view's worker half) ------------------

_SUMMARY_LOCK = threading.Lock()
_STEP_DTS: deque = deque(maxlen=64)
_LAST_STEP: Optional[int] = None
_LAST_STEP_METRICS: Dict[str, Any] = {}
_LAST_ANOMALY: Optional[Dict[str, Any]] = None
_LAST_DEVICE_ATTRIBUTION: Optional[Dict[str, Any]] = None
_LAST_MFU: Optional[Dict[str, Any]] = None
_LAST_HBM_FOOTPRINT: Optional[Dict[str, Any]] = None
_LAST_HBM_LIVE: Optional[Dict[str, Any]] = None
_LAST_CKPT_DIRECTORY: Optional[str] = None


def note_step(step: int, step_dt: Optional[float]) -> None:
    """Trainer hook (host side, once per step): the latest step number and
    measured host step cadence, feeding the percentile summary."""
    global _LAST_STEP
    with _SUMMARY_LOCK:
        _LAST_STEP = int(step)
        if step_dt is not None and step_dt > 0:
            _STEP_DTS.append(float(step_dt))


def note_step_metrics(metrics: Dict[str, Any]) -> None:
    """Host-safe (already-read-back) step metrics — e.g. the grad guard's
    one-step-behind verdict.  Values must be plain Python numbers: the
    flight recorder re-publishes them from paths where touching a device
    array could hang forever."""
    with _SUMMARY_LOCK:
        _LAST_STEP_METRICS.update(metrics)


def last_step_metrics() -> Dict[str, Any]:
    with _SUMMARY_LOCK:
        return dict(_LAST_STEP_METRICS)


def note_anomaly(suspect: Dict[str, Any]) -> None:
    """The anomaly detector's fleet-view hook: the latest
    ``straggler_suspect`` rides the per-rank obs summary (beacon →
    heartbeat → coordinator snapshot)."""
    global _LAST_ANOMALY
    with _SUMMARY_LOCK:
        _LAST_ANOMALY = dict(suspect)


def note_device_attribution(record: Dict[str, Any]) -> None:
    """Publish a device-time attribution record
    (:func:`bagua_tpu.obs.attribution.attribute_device_comm`): summary
    gauges for the exporter, the full record for the obs summary.  An
    unavailable record (cpu-sim) is kept too — null-with-rationale beats
    silence."""
    global _LAST_DEVICE_ATTRIBUTION
    with _SUMMARY_LOCK:
        _LAST_DEVICE_ATTRIBUTION = dict(record)
    if record.get("available"):
        if record.get("comm_s_per_step") is not None:
            counters.set_gauge("obs/device_comm_s_per_step",
                               float(record["comm_s_per_step"]))
        if record.get("overlap_fraction") is not None:
            counters.set_gauge("obs/device_overlap_fraction",
                               float(record["overlap_fraction"]))
        # per-tier breakdown (hierarchical two-level schedules only): the
        # DCN gauge is the slow-link cost the decomposition shrinks
        if record.get("comm_ici_s_per_step") is not None:
            counters.set_gauge("obs/device_comm_ici_s_per_step",
                               float(record["comm_ici_s_per_step"]))
        if record.get("comm_dcn_s_per_step") is not None:
            counters.set_gauge("obs/device_comm_dcn_s_per_step",
                               float(record["comm_dcn_s_per_step"]))


def last_device_attribution() -> Optional[Dict[str, Any]]:
    with _SUMMARY_LOCK:
        return (dict(_LAST_DEVICE_ATTRIBUTION)
                if _LAST_DEVICE_ATTRIBUTION is not None else None)


def note_mfu(record: Dict[str, Any]) -> None:
    """Publish the trainer's per-step MFU record: the ``obs/mfu`` gauge
    when available, the null-with-rationale record either way (the fleet
    view shows WHY a rank has no MFU column on cpu-sim)."""
    global _LAST_MFU
    with _SUMMARY_LOCK:
        _LAST_MFU = dict(record)
    if record.get("available") and record.get("mfu") is not None:
        counters.set_gauge("obs/mfu", float(record["mfu"]))


def last_mfu() -> Optional[Dict[str, Any]]:
    with _SUMMARY_LOCK:
        return dict(_LAST_MFU) if _LAST_MFU is not None else None


def note_hbm_footprint(record: Dict[str, Any]) -> None:
    """Publish the one-shot static HBM footprint
    (:func:`bagua_tpu.obs.memory.static_footprint`): summary record + the
    ``obs/hbm_static_footprint_bytes`` gauge."""
    global _LAST_HBM_FOOTPRINT
    with _SUMMARY_LOCK:
        _LAST_HBM_FOOTPRINT = dict(record)
    if record.get("total_bytes") is not None:
        counters.set_gauge("obs/hbm_static_footprint_bytes",
                           int(record["total_bytes"]))


def last_hbm_footprint() -> Optional[Dict[str, Any]]:
    with _SUMMARY_LOCK:
        return (dict(_LAST_HBM_FOOTPRINT)
                if _LAST_HBM_FOOTPRINT is not None else None)


def note_hbm_live(record: Dict[str, Any]) -> None:
    """Publish a live ``device.memory_stats()`` poll
    (:func:`bagua_tpu.obs.memory.live_memory_stats`): peak/headroom gauges
    when available, the rationale record either way."""
    global _LAST_HBM_LIVE
    with _SUMMARY_LOCK:
        _LAST_HBM_LIVE = dict(record)
    if record.get("available"):
        if record.get("peak_bytes_in_use") is not None:
            counters.set_gauge("obs/hbm_peak_bytes",
                               int(record["peak_bytes_in_use"]))
        if record.get("headroom_bytes") is not None:
            counters.set_gauge("obs/hbm_headroom_bytes",
                               int(record["headroom_bytes"]))


def last_hbm_live() -> Optional[Dict[str, Any]]:
    with _SUMMARY_LOCK:
        return dict(_LAST_HBM_LIVE) if _LAST_HBM_LIVE is not None else None


def note_ckpt_directory(directory: str) -> None:
    """Checkpoint-manager hook: the storage path this rank saves to rides
    the obs summary, so the coordinator-side autopilot can name WHICH path
    to quarantine when the rank's integrity counters climb."""
    global _LAST_CKPT_DIRECTORY
    with _SUMMARY_LOCK:
        _LAST_CKPT_DIRECTORY = str(directory)


def _percentile(sorted_vals: List[float], q: float) -> float:
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def local_obs_summary() -> Optional[dict]:
    """This process's per-rank fleet-view summary: step, step-dt
    percentiles, staleness gauge, skip counts.  None before the trainer
    noted any step (launcher processes, pure-eval jobs) — the beacon then
    carries no obs payload."""
    with _SUMMARY_LOCK:
        step = _LAST_STEP
        dts = sorted(_STEP_DTS)
        anomaly = dict(_LAST_ANOMALY) if _LAST_ANOMALY else None
        attribution = (dict(_LAST_DEVICE_ATTRIBUTION)
                       if _LAST_DEVICE_ATTRIBUTION else None)
        mfu = dict(_LAST_MFU) if _LAST_MFU else None
        footprint = dict(_LAST_HBM_FOOTPRINT) if _LAST_HBM_FOOTPRINT else None
        hbm_live = dict(_LAST_HBM_LIVE) if _LAST_HBM_LIVE else None
        ckpt_dir = _LAST_CKPT_DIRECTORY
    if step is None:
        return None
    summary = {
        "rank": int(_env.get_rank()),
        "step": step,
        "staleness": counters.get("async/staleness_max"),
        "skipped_steps": counters.get("grad_guard/skipped_steps"),
    }
    # checkpoint-integrity evidence for the autopilot's quarantine rule:
    # how often this rank's restores failed verification / fell back, and
    # which storage path its manager writes (None of it costs bytes while
    # the chain is clean and no manager exists)
    ckpt_failures = counters.get("ckpt/integrity_failures")
    ckpt_fallbacks = counters.get("ckpt/fallback_restores")
    if ckpt_failures:
        summary["ckpt_integrity_failures"] = ckpt_failures
    if ckpt_fallbacks:
        summary["ckpt_fallback_restores"] = ckpt_fallbacks
    if ckpt_dir and (ckpt_failures or ckpt_fallbacks):
        summary["ckpt_directory"] = ckpt_dir
    if dts:
        summary["step_dt_p50"] = round(_percentile(dts, 0.5), 6)
        summary["step_dt_p90"] = round(_percentile(dts, 0.9), 6)
    if anomaly:
        # the fleet's straggler question, answered per rank: latest flagged
        # step, how slow, and which phase dominated the excess
        summary["straggler_suspect"] = anomaly
    if attribution:
        if attribution.get("available"):
            summary["device_comm_s_per_step"] = attribution.get(
                "comm_s_per_step")
            summary["device_overlap_fraction"] = attribution.get(
                "overlap_fraction")
            if attribution.get("comm_dcn_s_per_step") is not None:
                # per-tier split of the comm seconds (two-level schedules):
                # the coordinator's fleet view can see DCN seconds move out
                # of the step when the hierarchical path lands
                summary["device_comm_ici_s_per_step"] = attribution.get(
                    "comm_ici_s_per_step")
                summary["device_comm_dcn_s_per_step"] = attribution.get(
                    "comm_dcn_s_per_step")
        else:
            # null-with-rationale, like trace_overlap's bench records
            summary["device_comm_s_per_step"] = None
            summary["device_attribution_rationale"] = attribution.get(
                "rationale")
    # efficiency plane: goodput fraction + badput breakdown (the fleet
    # rollup names each rank's worst badput class from these), MFU, and the
    # HBM footprint/headroom — all host-side accounting
    ledger_report = _ledger().report()
    if ledger_report is not None:
        from .ledger import BADPUT_CLASSES  # lazy: ledger imports from us

        summary["goodput_fraction"] = ledger_report["goodput_fraction"]
        summary["badput"] = {
            cls: round(s, 3)
            for cls, s in ledger_report["classes"].items()
            if cls in BADPUT_CLASSES and s > 0
        }
        summary["worst_badput_class"] = ledger_report["worst_badput_class"]
    if mfu:
        if mfu.get("available"):
            summary["mfu"] = mfu.get("mfu")
        else:
            summary["mfu"] = None
            summary["mfu_rationale"] = mfu.get("rationale")
    if footprint:
        summary["hbm_static_footprint_bytes"] = footprint.get("total_bytes")
    if hbm_live:
        if hbm_live.get("available"):
            summary["hbm_peak_bytes"] = hbm_live.get("peak_bytes_in_use")
            summary["hbm_headroom_bytes"] = hbm_live.get("headroom_bytes")
        else:
            summary["hbm_live_rationale"] = hbm_live.get("rationale")
    return summary


def reset_local_summary() -> None:
    """Forget the per-rank summary (test isolation)."""
    global _LAST_STEP, _LAST_ANOMALY, _LAST_DEVICE_ATTRIBUTION
    global _LAST_MFU, _LAST_HBM_FOOTPRINT, _LAST_HBM_LIVE
    global _LAST_CKPT_DIRECTORY
    with _SUMMARY_LOCK:
        _LAST_STEP = None
        _STEP_DTS.clear()
        _LAST_STEP_METRICS.clear()
        _LAST_ANOMALY = None
        _LAST_DEVICE_ATTRIBUTION = None
        _LAST_MFU = None
        _LAST_HBM_FOOTPRINT = None
        _LAST_HBM_LIVE = None
        _LAST_CKPT_DIRECTORY = None


# ---- Prometheus / JSONL rendering -----------------------------------------

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_]")


def prometheus_name(name: str) -> str:
    """``faults/grad.poison/fired`` -> ``bagua_faults_grad_poison_fired``."""
    return "bagua_" + _PROM_NAME.sub("_", name)


def render_prometheus(snapshot: Dict[str, Any]) -> str:
    """Prometheus textfile exposition of a counters snapshot — HELP/TYPE
    from the registry; unregistered names (should not exist once the lint
    rule holds) export as untyped with a marker comment."""
    lines: List[str] = []
    for name in sorted(snapshot):
        value = snapshot[name]
        pname = prometheus_name(name)
        metric = METRIC_REGISTRY.get(name)
        if metric is not None:
            lines.append(f"# HELP {pname} {' '.join(metric.doc.split())}")
            lines.append(f"# TYPE {pname} {metric.kind}")
        else:
            lines.append(f"# HELP {pname} (unregistered metric name)")
            lines.append(f"# TYPE {pname} untyped")
        lines.append(f"{pname} {value}")
    return "\n".join(lines) + "\n"


def prepared_snapshot():
    """The ONE counters snapshot both Prometheus surfaces render: the
    exporter's ``metrics.prom`` file and the HTTP plane's ``/metrics``
    endpoint (:mod:`bagua_tpu.obs.http`).  Refreshes the derived gauges
    first — ring drop pressure (a truncated timeline must read as
    truncated, not as a quiet run) and the goodput ledger's cumulative
    class/goodput gauges — so a live scrape and the on-disk file always
    expose the same series set."""
    from . import spans as _spans

    counters.set_gauge("obs/spans_dropped", _spans.recorder.dropped)
    _ledger().publish_gauges(counters)
    return counters.snapshot()


def _maybe_rotate(path: str) -> None:
    """Size-capped rotation for the append-only ``metrics.jsonl``: once the
    file reaches ``BAGUA_OBS_EXPORT_MAX_BYTES`` it moves to ``<path>.1``
    (replacing the previous rotation) and a fresh file starts — a long run
    can no longer grow the export unboundedly, and readers (the ledger CLI)
    still see up to two generations of history."""
    max_bytes = _env.get_obs_export_max_bytes()
    if max_bytes <= 0:
        return
    try:
        if os.path.getsize(path) >= max_bytes:
            os.replace(path, path + ".1")
    except OSError:
        pass  # no file yet, or a racing rotation — the append creates it


def _atomic_write(path: str, text: str) -> None:
    # pid AND thread in the temp name: the flight recorder writes from
    # whichever thread hit the defense path (watchdog monitor, SIGTERM
    # helper, main), and two threads sharing one temp file would truncate
    # each other's in-progress write before the replace
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class MetricsExporter:
    """Background thread (the analog of the reference's Flask sidecar):
    every ``interval_s``, snapshot the telemetry counters + the per-rank
    obs summary + the latest host-safe step metrics, append one JSON line
    to ``<directory>/metrics.jsonl``, and atomically rewrite
    ``<directory>/metrics.prom``.

    One counter-lock acquisition per snapshot (``counters.snapshot()``) —
    never one per metric — and one batched self-increment
    (``counters.incr_many``)."""

    def __init__(self, directory: str, interval_s: Optional[float] = None,
                 trainer: Optional[Any] = None):
        self.directory = str(directory)
        self.interval_s = float(
            _env.get_obs_export_interval_s() if interval_s is None
            else interval_s
        )
        self._trainer = weakref.ref(trainer) if trainer is not None else None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bagua-obs-exporter", daemon=True
        )

    def attach_trainer(self, trainer: Any) -> None:
        self._trainer = weakref.ref(trainer)

    def start(self) -> "MetricsExporter":
        os.makedirs(self.directory, exist_ok=True)
        self._thread.start()
        return self

    def export_once(self) -> dict:
        """One snapshot (also the thread's body): returns the JSONL record
        for tests/round-trips."""
        snap = prepared_snapshot()
        record: Dict[str, Any] = {
            "time_unix": time.time(),
            "collected_at": snap.collected_at,
            "rank": int(_env.get_rank()),
            "counters": dict(snap),
        }
        summary = local_obs_summary()
        if summary:
            record["obs"] = summary
        metrics = last_step_metrics()
        if metrics:
            record["step_metrics"] = metrics
        attribution = last_device_attribution()
        if attribution:
            record["device_attribution"] = attribution
        trainer = self._trainer() if self._trainer is not None else None
        if trainer is not None:
            dt = getattr(trainer, "measured_step_dt", None)
            if callable(dt):
                record["measured_step_dt"] = dt()
        jsonl = os.path.join(self.directory, "metrics.jsonl")
        _maybe_rotate(jsonl)
        with open(jsonl, "a") as f:
            f.write(json.dumps(record) + "\n")
        _atomic_write(os.path.join(self.directory, "metrics.prom"),
                      render_prometheus(snap))
        counters.incr_many({"obs/export_snapshots": 1})
        return record

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.export_once()
            except Exception as e:  # noqa: BLE001 - export must not kill
                logger.warning("metrics export failed: %s", e)

    def stop(self, final_export: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        if final_export:
            try:
                self.export_once()
            except Exception as e:  # noqa: BLE001
                logger.debug("final metrics export failed: %s", e)


_GLOBAL_EXPORTER: Optional[MetricsExporter] = None
_GLOBAL_EXPORTER_LOCK = threading.Lock()


def maybe_start_global_exporter(trainer: Optional[Any] = None
                                ) -> Optional[MetricsExporter]:
    """Process-wide exporter, started once when ``BAGUA_OBS_EXPORT_DIR`` is
    set (one thread no matter how many trainers — the global-watchdog
    pattern); later trainers re-attach so the freshest one's step metrics
    export."""
    directory = _env.get_obs_export_dir()
    if not directory:
        return None
    global _GLOBAL_EXPORTER
    with _GLOBAL_EXPORTER_LOCK:
        if _GLOBAL_EXPORTER is None:
            _GLOBAL_EXPORTER = MetricsExporter(
                directory, trainer=trainer
            ).start()
            atexit.register(_GLOBAL_EXPORTER.stop)
        elif trainer is not None:
            _GLOBAL_EXPORTER.attach_trainer(trainer)
        return _GLOBAL_EXPORTER


# ---- fleet snapshot (coordinator side) ------------------------------------

FLEET_SCHEMA = "bagua-obs-fleet-v1"


def _fleet_efficiency(ranks: Dict[str, dict]) -> dict:
    """The fleet-level efficiency rollup from merged per-rank obs
    summaries: mean/min goodput fraction, and per rank the goodput plus its
    worst (dominant) badput class.  Empty ``ranks`` sub-dict when no member
    reported a ledger yet (launcher-only fleets, pre-first-step)."""
    per_rank: Dict[str, dict] = {}
    fractions: List[float] = []
    for entry in ranks.values():
        for rank_id, obs in (entry.get("obs") or {}).items():
            if not isinstance(obs, dict):
                continue
            gf = obs.get("goodput_fraction")
            if gf is None:
                continue
            fractions.append(float(gf))
            per_rank[str(rank_id)] = {
                "goodput_fraction": gf,
                "worst_badput_class": obs.get("worst_badput_class"),
            }
    out: dict = {"ranks": per_rank}
    if fractions:
        out["goodput_fraction_mean"] = round(
            sum(fractions) / len(fractions), 6)
        out["goodput_fraction_min"] = round(min(fractions), 6)
    return out


def build_fleet_record(epoch: int,
                       members: Dict[int, Optional[dict]]) -> dict:
    """Merge every member's latest heartbeat health payload
    (``LeaseTracker.health_of``) into one ``bagua-obs-fleet-v1`` record —
    per node: the fence-relevant health events plus the per-rank ``obs``
    summaries its launcher merged from the workers' beacons.  The ONE
    merge both the snapshot file and the autopilot's policy engine
    consume."""
    ranks: Dict[str, dict] = {}
    for node_id, payload in members.items():
        payload = payload or {}
        obs = payload.get("obs") or {}
        if "step" in obs:
            # a single-rank summary (the in-process heartbeat default
            # source) normalizes to the launcher's per-rank shape
            obs = {str(obs.get("rank", 0)): obs}
        ranks[str(int(node_id))] = {
            "health": {k: v for k, v in payload.items() if k != "obs"},
            "obs": obs,
        }
    return {
        "schema": FLEET_SCHEMA,
        "time_unix": time.time(),
        "epoch": int(epoch),
        "nnodes": len(members),
        "ranks": ranks,
        # efficiency rollup: aggregate goodput + each rank's worst
        # badput class, lifted from the per-rank summaries above — the
        # fleet-level answer to "where is the fleet's wall-clock going"
        "efficiency": _fleet_efficiency(ranks),
    }


def write_fleet_snapshot(path: str, epoch: int,
                         members: Optional[Dict[int, Optional[dict]]] = None,
                         record: Optional[dict] = None) -> bool:
    """Write the coordinator-side fleet snapshot atomically — from
    ``members`` (merged here) or a pre-built ``record``.  Exception-free
    (the caller is the launcher's monitor loop)."""
    try:
        if record is None:
            record = build_fleet_record(epoch, members or {})
        _atomic_write(str(path), json.dumps(record, indent=1, sort_keys=True))
        return True
    except OSError as e:
        logger.debug("fleet snapshot not written: %s", e)
        return False


def validate_fleet_snapshot(record: dict) -> List[str]:
    """Schema problems with a fleet snapshot ([] = valid) — the drill/test
    gate."""
    problems: List[str] = []
    if record.get("schema") != FLEET_SCHEMA:
        problems.append(f"schema != {FLEET_SCHEMA}")
    for key, typ in (("time_unix", (int, float)), ("epoch", int),
                     ("nnodes", int), ("ranks", dict)):
        if not isinstance(record.get(key), typ):
            problems.append(f"missing/mistyped {key}")
    for nid, entry in (record.get("ranks") or {}).items():
        if not isinstance(entry, dict) or "health" not in entry \
                or "obs" not in entry:
            problems.append(f"rank {nid}: missing health/obs")
    eff = record.get("efficiency")
    if not isinstance(eff, dict) or not isinstance(eff.get("ranks"), dict):
        problems.append("missing/mistyped efficiency rollup")
    else:
        for rid, entry in eff["ranks"].items():
            if "goodput_fraction" not in entry:
                problems.append(f"efficiency.ranks[{rid}] missing "
                                "goodput_fraction")
    return problems
