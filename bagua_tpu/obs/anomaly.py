"""Step-time anomaly detection: notice when a step gets slow, and say why.

Health fencing (PR 6) sees correctness pathology — non-finite gradients,
missed async rounds — but a rank can hurt the fleet while computing
perfectly: a thermal-throttled host, a congested link, a noisy neighbor.
This module watches the one signal every rank already measures (the raw
host step cadence) plus the per-phase host durations the trainer samples
anyway, keeps a rolling ROBUST baseline (median/MAD — a single historic
spike must not inflate the yardstick that judges the next one), and when a
step lands far outside it:

* counts the event (``obs/step_anomalies``),
* triggers a throttled flight-recorder dump of the offending window
  (trigger ``step_anomaly`` — the spans around the slow step are exactly
  the post-mortem an operator wants),
* publishes a ``straggler_suspect`` phase breakdown
  (dispatch / collective / optimizer / other) into the per-rank obs
  summary, which rides the health beacon → lease heartbeat → coordinator
  fleet snapshot (the "which rank, which phase, since when" answer), and
* feeds a bounded **perf hint** queue the autotune service consumes
  (``AutotuneClient.report_metrics(perf_hints=...)``) — the scorer's cue
  that measured step time moved for environmental reasons, not because the
  current knob config is bad.

Phase semantics (host-side, honest about what XLA hides): ``dispatch`` is
the compiled-step dispatch call — in steady state its cadence tracks
device time, so a rank whose OWN device/host is slow shows a
dispatch-dominant excess; ``collective`` is host-visible synchronization
wait (async negotiate/catch-up boundaries, and gated straggler stalls —
the wait a slow PEER inflicts); ``optimizer`` is the grad-guard verdict
readback and other host-side optimizer-adjacent work; the residual is
``other``.  Coordinator side, :func:`fleet_straggler_suspects` applies the
same logic across ranks: dispatch-dominant anomalies name the straggler,
collective-dominant ones its victims.

Rolling baselines are per-rank by construction (one detector per process).
Import-light (no jax).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from statistics import median
from typing import Any, Dict, List, Optional

from .. import env as _env
from ..telemetry import counters

logger = logging.getLogger(__name__)

__all__ = [
    "StepAnomalyDetector", "PHASES", "publish_perf_hint",
    "drain_perf_hints", "peek_perf_hints", "fleet_straggler_suspects",
]

#: the attributed phases of one host step window; anything unattributed
#: lands in "other"
PHASES = ("dispatch", "collective", "optimizer")

#: 1.4826 * MAD estimates the standard deviation for Gaussian data — the
#: usual robust-z scaling
_MAD_SIGMA = 1.4826

#: minimum step-time ratio for an anomaly to become an autotune perf HINT:
#: hints postpone a sampling window (the service re-measures instead of
#: scoring), so 1.5-3x host blips — real anomalies, worth a suspect and a
#: counter — must not stall the Bayesian loop; a genuine straggler is an
#: order of magnitude out
HINT_MIN_RATIO = 3.0


class StepAnomalyDetector:
    """Rolling median/MAD anomaly detector over raw step time.

    ``observe(step, raw_dt, phases)`` once per step (host side, after the
    cadence sample).  Returns the ``straggler_suspect`` dict when the step
    is anomalous, else None.  A step is anomalous when, against the
    rolling window of PRIOR samples (after ``warmup`` of them exist)::

        raw_dt > median + threshold * 1.4826 * MAD
        raw_dt > min_ratio * median          # MAD→0 guard on quiet hosts

    Both conditions — a near-zero MAD (perfectly steady cadence) would
    otherwise flag microsecond jitter.  The offending sample still enters
    the window afterwards: median/MAD shrug off minority contamination, so
    one spike cannot mask the next (gated in ``tests/test_anomaly.py``).
    """

    def __init__(self, window: Optional[int] = None,
                 warmup: Optional[int] = None,
                 threshold: Optional[float] = None,
                 min_ratio: float = 1.3,
                 dump_min_interval_s: float = 30.0,
                 rank: Optional[int] = None):
        self.window = int(window if window is not None
                          else _env.get_obs_anomaly_window())
        self.warmup = int(warmup if warmup is not None
                          else _env.get_obs_anomaly_warmup())
        self.threshold = float(threshold if threshold is not None
                               else _env.get_obs_anomaly_threshold())
        if self.window < 4:
            raise ValueError(f"window must be >= 4, got {self.window}")
        if self.warmup < 2:
            raise ValueError(f"warmup must be >= 2, got {self.warmup}")
        self.min_ratio = float(min_ratio)
        self.dump_min_interval_s = float(dump_min_interval_s)
        self.rank = int(_env.get_rank()) if rank is None else int(rank)
        self._dts: deque = deque(maxlen=self.window)
        self._phase_dts: Dict[str, deque] = {}
        self._last_dump_mono: Optional[float] = None
        #: bounded history of flagged suspects (newest last) — drills and
        #: operators read it; the beacon carries only the latest
        self.suspects: deque = deque(maxlen=16)

    # -- core -------------------------------------------------------------

    def observe(self, step: int, raw_dt: Optional[float],
                phases: Optional[Dict[str, float]] = None
                ) -> Optional[dict]:
        if raw_dt is None or raw_dt <= 0:
            return None
        phases = {k: float(v) for k, v in (phases or {}).items() if v > 0}
        other = max(0.0, raw_dt - sum(phases.values()))
        suspect = None
        if len(self._dts) >= self.warmup:
            base = sorted(self._dts)
            med = median(base)
            mad = median(abs(x - med) for x in base)
            cut = med + self.threshold * _MAD_SIGMA * mad
            if raw_dt > cut and raw_dt > self.min_ratio * med and med > 0:
                suspect = self._flag(step, raw_dt, med, mad, phases, other)
        self._dts.append(raw_dt)
        # EVERY known phase gets a sample each step — a phase absent this
        # window contributed 0 s.  Without the zeros, a phase only seen
        # during anomalies (a straggler's collective wait) would have an
        # anomaly-sized baseline by its second occurrence and dominance
        # attribution would flip to whatever phase was still uncontaminated
        for name in set(PHASES) | set(phases):
            self._phase_dts.setdefault(
                name, deque(maxlen=self.window)).append(
                    phases.get(name, 0.0))
        self._phase_dts.setdefault(
            "_other", deque(maxlen=self.window)).append(other)
        return suspect

    def _phase_baseline(self, name: str) -> float:
        hist = self._phase_dts.get(name)
        return median(hist) if hist else 0.0

    def _flag(self, step: int, raw_dt: float, med: float, mad: float,
              phases: Dict[str, float], other: float) -> dict:
        # phase breakdown of the EXCESS: each attributed phase's duration
        # minus its own rolling median (of PRIOR windows — this window's
        # samples enter the history only after flagging); the residual
        # host time is "other"
        breakdown: Dict[str, float] = {}
        excess: Dict[str, float] = {}
        for name in sorted(set(PHASES) | set(phases)):
            dur = phases.get(name, 0.0)
            breakdown[name] = round(dur, 6)
            excess[name] = dur - self._phase_baseline(name)
        breakdown["other"] = round(other, 6)
        excess["other"] = other - self._phase_baseline("_other")
        dominant = max(excess, key=lambda k: excess[k])
        suspect = {
            "rank": self.rank,
            "step": int(step),
            "step_dt": round(raw_dt, 6),
            "baseline_p50": round(med, 6),
            "baseline_mad": round(mad, 6),
            "ratio": round(raw_dt / med, 3) if med else None,
            "dominant_phase": dominant,
            "phases": breakdown,
            "detected_at_unix": time.time(),
        }
        self.suspects.append(suspect)
        counters.incr("obs/step_anomalies")
        logger.warning(
            "step anomaly: rank %d step %d took %.4fs (baseline p50 "
            "%.4fs, x%.1f) — dominant phase %r",
            self.rank, step, raw_dt, med, suspect["ratio"] or 0.0, dominant,
        )
        # the fleet-view half: the latest suspect rides the obs summary
        # (beacon -> heartbeat -> coordinator snapshot)
        from . import export as _export

        _export.note_anomaly(suspect)
        if suspect["ratio"] is not None \
                and suspect["ratio"] >= HINT_MIN_RATIO:
            publish_perf_hint({
                "kind": "step_time_anomaly",
                "rank": self.rank,
                "step": int(step),
                "ratio": suspect["ratio"],
                "dominant_phase": dominant,
            })
        self._maybe_dump(suspect)
        return suspect

    def _maybe_dump(self, suspect: dict) -> None:
        """Throttled flight-recorder dump of the offending window: the ring
        around the slow step is the post-mortem; per-anomaly dumps on a
        chronically slow host would turn the recorder into the I/O
        straggler it is hunting."""
        now = time.monotonic()
        if self._last_dump_mono is not None \
                and now - self._last_dump_mono < self.dump_min_interval_s:
            return
        self._last_dump_mono = now
        from . import recorder as _recorder

        _recorder.dump_flight_record(
            "step_anomaly",
            reason=(f"step {suspect['step']} took {suspect['step_dt']}s "
                    f"(baseline p50 {suspect['baseline_p50']}s)"),
            extra={"straggler_suspect": suspect},
        )


# ---- perf hint channel (consumed by the autotune service) -----------------

_HINT_LOCK = threading.Lock()
_HINTS: deque = deque(maxlen=32)


def publish_perf_hint(hint: dict) -> None:
    """Queue a perf hint for the next autotune check-in.  Bounded (oldest
    drop): hints are advisory context, never a backlog to drain at any
    cost."""
    with _HINT_LOCK:
        _HINTS.append(dict(hint))
    counters.incr("obs/perf_hints")


def drain_perf_hints() -> List[dict]:
    """Pop every queued hint (oldest first) — the trainer's autotune
    check-in attaches them to ``report_metrics``."""
    with _HINT_LOCK:
        hints = list(_HINTS)
        _HINTS.clear()
    return hints


def requeue_perf_hints(hints: List[dict]) -> None:
    """Put drained hints BACK (front of the queue, original order) after a
    failed delivery — a transient sidecar hiccup must not silently discard
    the taint signal for the window it described.  No counter increment:
    these hints were already counted when published."""
    if not hints:
        return
    with _HINT_LOCK:
        for hint in reversed(hints):
            _HINTS.appendleft(dict(hint))


def peek_perf_hints() -> List[dict]:
    with _HINT_LOCK:
        return list(_HINTS)


# ---- coordinator-side fleet analysis --------------------------------------


def fleet_straggler_suspects(fleet_record: dict) -> dict:
    """Read a ``bagua-obs-fleet-v1`` snapshot and name the straggler(s).

    A rank whose anomaly is **dispatch**-dominant (or ``other``-dominant —
    locally slow host time) is itself slow: a straggler.  A rank whose
    anomaly is **collective**-dominant is *waiting* on someone else: a
    victim.  Returns ``{"stragglers": [...], "victims": [...]}`` where
    each entry is ``{"rank", "node", "suspect"}`` sorted by excess ratio —
    the consumable answer for the coordinator (and the autotune scorer,
    which must not re-tune knobs to chase an environmental straggler)."""
    stragglers: List[dict] = []
    victims: List[dict] = []
    for node_id, entry in (fleet_record.get("ranks") or {}).items():
        for rank_id, summary in (entry.get("obs") or {}).items():
            suspect = (summary or {}).get("straggler_suspect")
            if not suspect:
                continue
            item = {"rank": int(suspect.get("rank", rank_id)),
                    "node": int(node_id), "suspect": suspect}
            if suspect.get("dominant_phase") == "collective":
                victims.append(item)
            else:
                stragglers.append(item)
    key = lambda it: -(it["suspect"].get("ratio") or 0)  # noqa: E731
    return {"stragglers": sorted(stragglers, key=key),
            "victims": sorted(victims, key=key)}
