"""HBM memory accounting: does the bucket-flat layout fit the device?

Three complementary views, cheapest first:

* **static footprint** (:func:`static_footprint`) — the resident training
  state's per-device bytes computed from host metadata alone: the live
  ``TrainState`` leaves (params / optimizer state / algorithm state —
  per-device shard sizes, so stacked-gossip axes and sharded ZeRO chunks
  count once, not world-size times) plus the transient per-bucket gradient
  flats the compiled step materializes (:func:`plan_flat_bytes` over the
  ``BucketPlan``).  Exact and testable on cpu-sim — the number an operator
  sizes a config against before ever compiling.
* **compiled-step analysis** — XLA's ``compile().memory_analysis()``
  per step-cache entry, harvested alongside the cached cost analysis in
  ``BaguaTrainer.step_cost_analysis`` when the backend provides one
  (TPU does; cpu-sim reports null-with-rationale).
* **live peaks** (:func:`live_memory_stats`) — ``device.memory_stats()``
  polled off the hot path (the trainer's ~2 s beacon cadence): real
  ``peak_bytes_in_use`` and the headroom against ``bytes_limit``.  TPU
  runtimes expose it; cpu-sim returns null-with-rationale, like
  ``trace_overlap``.

Footprint and headroom ride the per-rank obs summary → health beacon →
fleet snapshot as gauges, and land in ``EFFICIENCY.json``.  Host-side
only: nothing here touches the compiled step.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "plan_flat_bytes", "tree_device_bytes", "static_footprint",
    "compiled_memory_analysis", "live_memory_stats",
]


def plan_flat_bytes(plan) -> int:
    """Bytes of one full set of flat bucket buffers for a
    :class:`~bagua_tpu.bucket.BucketPlan` — padding included (the padded
    numel IS what the compiled step materializes per bucket)."""
    return int(sum(
        b.padded_numel * np.dtype(b.dtype).itemsize for b in plan.buckets
    ))


def tree_device_bytes(tree) -> int:
    """Per-device bytes of a pytree of arrays: each leaf counts its LOCAL
    shard (``addressable_shards[0]``), so a replicated leaf counts its
    full size, a stacked/sharded leaf its per-device slice — the HBM a
    single chip actually holds.  Host metadata only (shapes/dtypes), no
    readbacks."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "nbytes"):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += int(shards[0].data.nbytes)
        else:
            total += int(leaf.nbytes)
    return total


def static_footprint(trainer, state) -> Dict[str, Any]:
    """Per-device HBM bytes of a trainer's resident training state plus the
    step's transient gradient flats — the static fit estimate.

    Components (all per device):

    * ``params_bytes`` / ``opt_state_bytes`` / ``algo_state_bytes`` — the
      live :class:`TrainState` leaves' shard sizes.  Under the
      flat-resident layout the params/opt leaves ARE the bucket flats, so
      this matches the ``BucketPlan`` avals exactly (pinned in
      ``tests/test_ledger.py``).
    * ``grad_flats_bytes`` — one set of per-bucket gradient flats
      (:func:`plan_flat_bytes`): the dominant transient the compiled step
      materializes between backward and the collective.
    """
    plan = getattr(trainer, "_plan", None)
    record: Dict[str, Any] = {
        "params_bytes": tree_device_bytes(state.params),
        "opt_state_bytes": tree_device_bytes(state.opt_state),
        "algo_state_bytes": tree_device_bytes(
            getattr(state, "algo_state", None)),
        "grad_flats_bytes": plan_flat_bytes(plan) if plan is not None else 0,
        "bucket_count": len(plan.buckets) if plan is not None else 0,
        "flat_resident": bool(getattr(trainer, "_flat_resident", False)),
        "per_device": True,
    }
    record["total_bytes"] = (
        record["params_bytes"] + record["opt_state_bytes"]
        + record["algo_state_bytes"] + record["grad_flats_bytes"]
    )
    return record


#: attributes a jax ``CompiledExecutable.memory_analysis()`` result may
#: expose (backend-dependent; missing ones are simply absent)
_MEMORY_ANALYSIS_FIELDS = (
    "argument_size_in_bytes", "output_size_in_bytes",
    "temp_size_in_bytes", "alias_size_in_bytes",
    "generated_code_size_in_bytes", "host_argument_size_in_bytes",
    "host_output_size_in_bytes", "host_temp_size_in_bytes",
    "host_generated_code_size_in_bytes", "serialized_size_in_bytes",
)


def compiled_memory_analysis(compiled) -> Optional[Dict[str, int]]:
    """Extract the plain-int fields from a compiled executable's
    ``memory_analysis()`` (None when the backend offers none — cpu-sim's
    null-with-rationale case).  Adds ``peak_bytes`` = arguments + outputs +
    temps when all three are present: the executable's own HBM high-water
    estimate."""
    try:
        analysis = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 - backend-dependent surface
        logger.debug("memory_analysis unavailable: %s", e)
        return None
    if analysis is None:
        return None
    out: Dict[str, int] = {}
    for field in _MEMORY_ANALYSIS_FIELDS:
        value = getattr(analysis, field, None)
        if isinstance(value, (int, np.integer)):
            out[field] = int(value)
    if not out:
        return None
    if all(k in out for k in ("argument_size_in_bytes",
                              "output_size_in_bytes",
                              "temp_size_in_bytes")):
        out["peak_bytes"] = (out["argument_size_in_bytes"]
                             + out["output_size_in_bytes"]
                             + out["temp_size_in_bytes"])
    return out


def live_memory_stats(device=None) -> Dict[str, Any]:
    """One poll of ``device.memory_stats()`` (the first local device by
    default): ``{"available": True, bytes_in_use, peak_bytes_in_use,
    bytes_limit, headroom_bytes}`` on runtimes that expose it (TPU), else
    ``{"available": False, "rationale": ...}`` — null-with-rationale, so a
    fleet view can show *why* a rank has no live-memory column."""
    import jax

    if device is None:
        device = jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except Exception as e:  # noqa: BLE001 - backend-dependent surface
        # transient: a runtime hiccup, not "this backend never has HBM
        # stats" — callers should keep polling (with a budget)
        return {"available": False, "transient": True,
                "rationale": f"memory_stats raised {type(e).__name__}: {e}"}
    if not stats:
        return {"available": False,
                "rationale": f"device {device.device_kind!r} reports no "
                             "memory_stats (cpu-sim has no HBM)"}
    record: Dict[str, Any] = {"available": True,
                              "device_kind": device.device_kind}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if key in stats:
            record[key] = int(stats[key])
    limit = record.get("bytes_limit")
    peak = record.get("peak_bytes_in_use", record.get("bytes_in_use"))
    if limit is not None and peak is not None:
        record["headroom_bytes"] = int(limit - peak)
    return record
