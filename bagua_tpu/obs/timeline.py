"""Fleet timeline: merge per-rank span dumps into one Perfetto trace.

PR 7 left each rank with its own evidence — a bounded span ring, flight
dumps, counters — but a hang or a straggler is a *fleet* phenomenon: the
question is "what was rank 3 doing while rank 0 waited at the boundary",
and that needs every rank's spans on ONE time axis.  This module assembles
exactly that: rank-tagged span dumps (flight-recorder JSONs, or live ring
snapshots written by :func:`dump_span_ring`) become a single Chrome-trace /
Perfetto JSON — rank → process, thread → track — that ``chrome://tracing``
or https://ui.perfetto.dev renders directly.

The hard part is clocks.  Spans record ``time.monotonic()``, whose epoch is
arbitrary *per process* — raw t0s from two ranks can be hours apart while
the events were simultaneous.  Alignment anchors on the spans that end at a
globally synchronized instant: ``async/negotiate`` (the control gather
blocks every rank until the slowest arrives, so all ranks EXIT together),
``async/catchup``, and ``elastic/rendezvous`` (every member leaves the
round at publication).  For each non-reference rank, every anchor span
shared with the reference rank (same name, same ``step``/``epoch``) yields
one offset sample ``ref.t1 - other.t1``; the median is that rank's clock
offset.  Ranks with no shared anchor fall back to aligning their earliest
span (flagged ``aligned: false`` in the metadata — read their tracks as
shape, not as cross-rank ordering).

Schema ``bagua-obs-timeline-v1``: the standard Chrome-trace object form
(``traceEvents`` + ``metadata``), so any trace viewer opens it unmodified;
the bagua-specific provenance (per-rank offsets, anchor counts, drop
counts) lives under ``metadata``.

CLI::

    python -m bagua_tpu.obs.timeline DUMP_DIR_OR_FILES... \
        [--out timeline.json] [--check] [--no-align]

Import-light (no jax): this is an offline/post-mortem tool.
"""

from __future__ import annotations

import argparse
import glob
import json
import logging
import os
import statistics
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "TIMELINE_SCHEMA", "ANCHOR_SPAN_NAMES", "assemble_timeline",
    "validate_timeline", "load_rank_records", "dump_span_ring", "main",
]

TIMELINE_SCHEMA = "bagua-obs-timeline-v1"

#: span names whose EXIT is a globally synchronized instant (a blocking
#: cross-rank boundary): every rank leaves together, so matching spans on
#: two ranks pin their monotonic clocks to one another
ANCHOR_SPAN_NAMES = ("async/negotiate", "async/catchup",
                     "elastic/rendezvous")


# ---- input loading --------------------------------------------------------


def _is_rank_record(rec: Any) -> bool:
    return isinstance(rec, dict) and isinstance(rec.get("spans"), list) \
        and "rank" in rec


def load_rank_records(paths: Sequence[str]) -> List[dict]:
    """Read rank span dumps from files and/or directories.

    Accepts flight-recorder dumps (``flight_*.json``) and span-ring dumps
    (:func:`dump_span_ring`, ``spans_*.json``) — anything JSON with
    ``rank`` + ``spans``; directories are scanned for both filename
    patterns.  Unreadable or shape-less files are skipped with a warning
    (a post-mortem tool must salvage what it can)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for pat in ("flight_*.json", "spans_*.json"):
                files.extend(sorted(glob.glob(os.path.join(p, pat))))
        else:
            files.append(p)
    records = []
    for path in files:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("timeline: skipping unreadable %s (%s)", path, e)
            continue
        if not _is_rank_record(rec):
            logger.warning("timeline: %s has no rank/spans — skipped", path)
            continue
        rec.setdefault("_source", os.path.basename(path))
        records.append(rec)
    return records


def dump_span_ring(path: str, rank: Optional[int] = None) -> str:
    """Write this process's live span ring as a timeline-consumable rank
    record (``{"rank", "spans", "active_spans", "spans_dropped"}``) — the
    non-crash way to feed :func:`assemble_timeline`, e.g. at the end of a
    profiling window.  Returns the path written."""
    from .. import env as _env
    from . import export as _export
    from . import ledger as _ledger
    from . import spans as _spans

    record = {
        "rank": int(_env.get_rank()) if rank is None else int(rank),
        "pid": os.getpid(),
        "spans": _spans.recorder.snapshot(),
        "active_spans": _spans.recorder.active_snapshot(),
        "spans_dropped": _spans.recorder.dropped,
        # goodput ledger: the cumulative class history becomes a Perfetto
        # counter track alongside this rank's span track
        "ledger": _ledger.ledger.report(),
        "ledger_samples": _ledger.ledger.samples(),
    }
    _export._atomic_write(path, json.dumps(record, indent=1))
    return path


# ---- clock alignment ------------------------------------------------------


def _anchor_key(span: dict) -> Optional[Tuple]:
    """Identity of a boundary crossing, comparable across ranks: the span
    name plus the step (async boundaries) or epoch (rendezvous rounds)."""
    name = span.get("name")
    if name not in ANCHOR_SPAN_NAMES:
        return None
    attrs = span.get("attrs") or {}
    if name == "elastic/rendezvous":
        marker = attrs.get("epoch")
    else:
        marker = span.get("step")
    if marker is None:
        return None
    return (name, marker)


def _rank_anchors(spans: List[dict]) -> Dict[Tuple, float]:
    """anchor key -> t1 (latest occurrence wins: a re-run boundary — e.g. a
    resumed epoch — supersedes its earlier attempt)."""
    out: Dict[Tuple, float] = {}
    for span in spans:
        key = _anchor_key(span)
        if key is not None and "t1" in span:
            prev = out.get(key)
            if prev is None or span["t1"] > prev:
                out[key] = span["t1"]
    return out


def _clock_offsets(spans_by_rank: Dict[int, List[dict]],
                   align: bool = True) -> Dict[int, dict]:
    """Per-rank ``{"offset_s", "aligned", "anchors"}`` mapping every rank's
    monotonic clock onto the reference (lowest) rank's."""
    ranks = sorted(spans_by_rank)
    ref = ranks[0]
    ref_anchors = _rank_anchors(spans_by_rank[ref]) if align else {}
    out: Dict[int, dict] = {}
    for rank in ranks:
        if rank == ref:
            out[rank] = {"offset_s": 0.0, "aligned": True, "anchors": 0,
                         "reference": True}
            continue
        samples = []
        if align:
            anchors = _rank_anchors(spans_by_rank[rank])
            samples = [ref_anchors[k] - anchors[k]
                       for k in anchors.keys() & ref_anchors.keys()]
        if samples:
            out[rank] = {"offset_s": statistics.median(samples),
                         "aligned": True, "anchors": len(samples)}
        else:
            # no shared boundary span: best effort — line the earliest
            # spans up so the track is at least on screen, and say so
            ref_t0 = min((s["t0"] for s in spans_by_rank[ref]), default=0.0)
            t0 = min((s["t0"] for s in spans_by_rank[rank]), default=0.0)
            out[rank] = {"offset_s": ref_t0 - t0, "aligned": False,
                         "anchors": 0}
    return out


# ---- assembly -------------------------------------------------------------


def _span_identity(span: dict) -> Tuple:
    return (span.get("name"), span.get("t0"), span.get("t1"),
            span.get("thread"), span.get("depth"))


def assemble_timeline(rank_records: Sequence[dict],
                      align: bool = True) -> dict:
    """Merge rank span dumps into one Chrome-trace JSON (object form).

    ``rank_records``: dicts with ``rank`` + ``spans`` (finished spans as
    :mod:`bagua_tpu.obs.spans` records them), optionally ``active_spans``
    and ``spans_dropped`` — i.e. flight dumps or :func:`dump_span_ring`
    output.  Multiple records for one rank (several dumps from one run)
    merge; identical spans dedupe.  Raises ``ValueError`` on no spans at
    all — an empty timeline is an operator error, not a trace."""
    spans_by_rank: Dict[int, List[dict]] = {}
    active_by_rank: Dict[int, List[dict]] = {}
    dropped_by_rank: Dict[int, int] = {}
    sources_by_rank: Dict[int, List[str]] = {}
    ledger_by_rank: Dict[int, Dict[float, dict]] = {}
    for rec in rank_records:
        rank = int(rec["rank"])
        for sample in rec.get("ledger_samples") or []:
            if isinstance(sample, dict) and "t" in sample \
                    and isinstance(sample.get("classes"), dict):
                # keyed by t: multiple dumps of one rank dedupe naturally
                ledger_by_rank.setdefault(rank, {})[sample["t"]] = \
                    sample["classes"]
        seen = {_span_identity(s) for s in spans_by_rank.get(rank, [])}
        for span in rec.get("spans") or []:
            if not isinstance(span, dict) or "t0" not in span:
                continue
            if _span_identity(span) in seen:
                continue
            seen.add(_span_identity(span))
            spans_by_rank.setdefault(rank, []).append(span)
        for span in rec.get("active_spans") or []:
            if isinstance(span, dict) and "t0" in span:
                active_by_rank.setdefault(rank, []).append(span)
        dropped_by_rank[rank] = max(dropped_by_rank.get(rank, 0),
                                    int(rec.get("spans_dropped") or 0))
        if rec.get("_source"):
            sources_by_rank.setdefault(rank, []).append(rec["_source"])
        spans_by_rank.setdefault(rank, [])
    spans_by_rank = {r: s for r, s in spans_by_rank.items()
                     if s or active_by_rank.get(r)}
    if not spans_by_rank:
        raise ValueError("no spans in any rank record — nothing to merge "
                         "(were the dumps written with BAGUA_OBS=off?)")

    offsets = _clock_offsets(
        {r: s + active_by_rank.get(r, [])
         for r, s in spans_by_rank.items()}, align=align,
    )
    # one global origin so ts starts near zero (viewers dislike 1e9-second
    # offsets): the earliest ALIGNED t0 across the fleet
    origin = min(
        span["t0"] + offsets[rank]["offset_s"]
        for rank, spans in spans_by_rank.items()
        for span in spans + active_by_rank.get(rank, [])
    )

    def _us(rank: int, t: float) -> float:
        return round((t + offsets[rank]["offset_s"] - origin) * 1e6, 3)

    events: List[dict] = []
    tids: Dict[Tuple[int, str], int] = {}

    def _tid(rank: int, thread: str) -> int:
        key = (rank, thread or "MainThread")
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == rank]) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": rank,
                "tid": tids[key], "args": {"name": key[1]},
            })
        return tids[key]

    for rank in sorted(spans_by_rank):
        events.append({
            "ph": "M", "name": "process_name", "pid": rank,
            "args": {"name": f"rank {rank}"},
        })
        events.append({
            "ph": "M", "name": "process_sort_index", "pid": rank,
            "args": {"sort_index": rank},
        })
        for span in sorted(spans_by_rank[rank], key=lambda s: s["t0"]):
            args: Dict[str, Any] = dict(span.get("attrs") or {})
            if span.get("step") is not None:
                args["step"] = span["step"]
            if span.get("error"):
                args["error"] = span["error"]
            events.append({
                "ph": "X", "name": span["name"], "pid": rank,
                "tid": _tid(rank, span.get("thread")),
                "ts": _us(rank, span["t0"]),
                "dur": round(max(0.0, span["t1"] - span["t0"]) * 1e6, 3),
                "cat": span["name"].split("/", 1)[0],
                "args": args,
            })
        # spans still OPEN at dump time: begin-without-end events — the
        # wedged sections a hang post-mortem cares about; Perfetto renders
        # them as unfinished slices
        for span in sorted(active_by_rank.get(rank, []),
                           key=lambda s: s["t0"]):
            args = dict(span.get("attrs") or {})
            args["unfinished"] = True
            if span.get("step") is not None:
                args["step"] = span["step"]
            events.append({
                "ph": "B", "name": span["name"], "pid": rank,
                "tid": _tid(rank, span.get("thread")),
                "ts": _us(rank, span["t0"]),
                "cat": span["name"].split("/", 1)[0],
                "args": args,
            })
        # goodput-ledger counter track: cumulative per-class seconds
        # sampled at each step-window close — Perfetto stacks the series,
        # so badput growth is visible at a glance next to the span track
        for t in sorted(ledger_by_rank.get(rank, {})):
            events.append({
                "ph": "C", "name": "ledger_s", "pid": rank,
                "ts": _us(rank, t),
                "cat": "ledger",
                "args": {cls: val for cls, val
                         in sorted(ledger_by_rank[rank][t].items())},
            })
    events.sort(key=lambda e: (e.get("ts", -1), e["pid"]))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "schema": TIMELINE_SCHEMA,
            "generated_by": "python -m bagua_tpu.obs.timeline",
            "aligned": all(o["aligned"] for o in offsets.values()),
            "ranks": {
                str(rank): {
                    "clock_offset_s": round(offsets[rank]["offset_s"], 9),
                    "aligned": offsets[rank]["aligned"],
                    "anchor_spans": offsets[rank]["anchors"],
                    "spans": len(spans_by_rank[rank]),
                    "active_spans": len(active_by_rank.get(rank, [])),
                    # a non-zero drop count means the track is a TAIL, not
                    # the whole run — the satellite that makes truncation
                    # visible instead of silent
                    "spans_dropped": dropped_by_rank.get(rank, 0),
                    "ledger_samples": len(ledger_by_rank.get(rank, {})),
                    "sources": sorted(set(sources_by_rank.get(rank, []))),
                }
                for rank in sorted(spans_by_rank)
            },
        },
    }


# ---- validation (shared by tests, CI stage, and --check) ------------------


def validate_timeline(record: dict) -> List[str]:
    """Schema problems with an assembled timeline ([] = valid): the object
    trace form, event fields per the Chrome Trace Event spec (X needs
    ts+dur, B needs ts, M carries no timestamp), and the v1 metadata."""
    problems: List[str] = []
    if not isinstance(record, dict):
        return ["not a JSON object"]
    meta = record.get("metadata") or {}
    if meta.get("schema") != TIMELINE_SCHEMA:
        problems.append(f"metadata.schema != {TIMELINE_SCHEMA}")
    events = record.get("traceEvents")
    if not isinstance(events, list) or not events:
        problems.append("traceEvents missing or empty")
        return problems
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev \
                or "pid" not in ev:
            problems.append(f"event[{i}]: missing ph/name/pid")
            continue
        if ev["ph"] == "X":
            if not isinstance(ev.get("ts"), (int, float)) \
                    or not isinstance(ev.get("dur"), (int, float)) \
                    or ev["dur"] < 0 or "tid" not in ev:
                problems.append(f"event[{i}]: X needs ts, dur>=0, tid")
        elif ev["ph"] == "B":
            if not isinstance(ev.get("ts"), (int, float)) or "tid" not in ev:
                problems.append(f"event[{i}]: B needs ts, tid")
        elif ev["ph"] == "C":
            if not isinstance(ev.get("ts"), (int, float)) \
                    or not isinstance(ev.get("args"), dict) \
                    or not ev["args"]:
                problems.append(f"event[{i}]: C needs ts and series args")
        elif ev["ph"] != "M":
            problems.append(f"event[{i}]: unexpected phase {ev['ph']!r}")
        if len(problems) > 20:
            problems.append("... (truncated)")
            break
    ranks = meta.get("ranks")
    if not isinstance(ranks, dict) or not ranks:
        problems.append("metadata.ranks missing/empty")
    else:
        for rid, entry in ranks.items():
            for key in ("clock_offset_s", "aligned", "spans_dropped"):
                if key not in entry:
                    problems.append(f"metadata.ranks[{rid}] missing {key}")
    return problems


# ---- CLI ------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bagua_tpu.obs.timeline",
        description="Merge per-rank span dumps (flight_*.json / "
                    "spans_*.json) into one clock-aligned Perfetto trace.",
    )
    ap.add_argument("inputs", nargs="+",
                    help="dump files and/or directories to scan")
    ap.add_argument("--out", default="timeline.json",
                    help="output trace path (default: timeline.json)")
    ap.add_argument("--check", action="store_true",
                    help="schema-validate the assembled trace; non-zero "
                         "exit on problems")
    ap.add_argument("--no-align", action="store_true",
                    help="skip cross-rank clock alignment (raw monotonic "
                         "origins per rank)")
    args = ap.parse_args(argv)

    records = load_rank_records(args.inputs)
    if not records:
        print(f"no rank span dumps found under {args.inputs}",
              file=sys.stderr)
        return 2
    try:
        trace = assemble_timeline(records, align=not args.no_align)
    except ValueError as e:
        print(f"timeline assembly failed: {e}", file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump(trace, f, indent=1, sort_keys=True)
    meta = trace["metadata"]
    n_events = len(trace["traceEvents"])
    print(f"wrote {args.out}: {n_events} events from "
          f"{len(meta['ranks'])} rank(s) "
          f"({sum(len(r.get('spans') or []) for r in records)} spans read); "
          f"aligned={meta['aligned']}")
    for rid, entry in sorted(meta["ranks"].items(), key=lambda kv: int(kv[0])):
        print(f"  rank {rid}: offset {entry['clock_offset_s']:+.6f}s "
              f"({'aligned' if entry['aligned'] else 'UNALIGNED'}, "
              f"{entry['anchor_spans']} anchor(s), "
              f"{entry['spans_dropped']} span(s) dropped)")
    if args.check:
        problems = validate_timeline(trace)
        if problems:
            print("schema problems: " + "; ".join(problems),
                  file=sys.stderr)
            return 1
        print(f"schema {TIMELINE_SCHEMA} valid")
    return 0


if __name__ == "__main__":
    sys.exit(main())
