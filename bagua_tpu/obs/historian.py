"""Fleet telemetry historian: bounded time-series memory over the
fleet-snapshot stream.

The obs plane through PR 13 is rich but memoryless: metrics land in
``metrics.jsonl``/``metrics.prom`` files and the coordinator's fleet
snapshot is a point-in-time record, so the autopilot's "sustained"
windows live only as in-memory streak counters and nothing can answer
"is HBM headroom shrinking?" or "what share of the step is DCN seconds,
trending over the last 10 minutes?".  The MegaScale goodput lens the
ledger adopted (arXiv 2402.15627) is explicitly a *fleet-historical*
diagnosis tool, and the reference Bagua (arXiv 2107.01499) runs its
autotuner off a live metrics service rather than files — this module is
that memory, coordinator-side:

* **Bounded rings.**  Every numeric field of every rank's obs summary in
  each ingested ``bagua-obs-fleet-v1`` record lands in a per-(rank,
  metric) ring of ``BAGUA_OBS_HISTORIAN_CAPACITY`` samples (plus the
  fleet-level efficiency aggregates under the pseudo-rank ``fleet``).
* **Windowed queries.**  :meth:`Historian.rate`,
  :meth:`Historian.percentile`, and :meth:`Historian.slope`
  (least-squares, per second) over a trailing window — the primitives
  behind the trend gauges and the ``/history`` HTTP endpoint
  (:mod:`bagua_tpu.obs.http`).
* **Trend gauges back into the snapshot.**  :meth:`Historian.ingest`
  augments each rank summary with a ``trends`` sub-dict
  (``goodput_slope``, ``hbm_headroom_slope``, ``hbm_headroom_eta_s``,
  ``dcn_comm_share``) and publishes the fleet-worst values as the
  ``obs/goodput_slope`` / ``obs/hbm_headroom_slope`` /
  ``obs/dcn_comm_share`` gauges — the evidence the autopilot's trend
  rules (pre-OOM resize, DCN compression escalation;
  :mod:`bagua_tpu.autopilot.policy`) consume.
* **Restart persistence.**  Rings serialize through the restart TCPStore
  (key ``obs/historian``, epoch-UNfenced like the autopilot's policy
  state) so a relaunched coordinator keeps its history instead of
  re-earning every trend window from scratch.

Deterministic by construction: samples are timestamped by the ingested
record's own ``time_unix`` (never the wall clock), so a recorded stream
replayed through ``python -m bagua_tpu.autopilot --historian`` computes
the exact trends the live coordinator saw.  Import-light (no jax): the
launcher's monitor loop hosts it.
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import env as _env
from ..telemetry import counters

logger = logging.getLogger(__name__)

__all__ = ["Historian", "maybe_build_historian", "STORE_KEY",
           "least_squares_slope"]

#: restart-store key the rings persist under — OUTSIDE the epoch-fenced
#: ``elastic/<e>/`` keyspace: trend windows must survive epoch bumps and
#: coordinator relaunches (the autopilot state-persistence pattern)
STORE_KEY = "obs/historian"

#: pseudo-rank carrying the fleet-level efficiency aggregates
FLEET_RANK = "fleet"

#: minimum samples before a windowed slope/share is emitted — one or two
#: points fit a line perfectly and would fire trend rules off noise
MIN_TREND_SAMPLES = 4

#: how many ingests between restart-store persists (each persist is one
#: store round-trip; trend windows tolerate losing a few trailing samples
#: on a coordinator crash — they merely re-earn them)
PERSIST_EVERY = 5


def least_squares_slope(samples: List[Tuple[float, float]]
                        ) -> Optional[float]:
    """Ordinary least-squares slope (value units per second) of
    ``(time_unix, value)`` samples; None when under
    :data:`MIN_TREND_SAMPLES` or the time spread is degenerate."""
    if len(samples) < MIN_TREND_SAMPLES:
        return None
    t0 = samples[0][0]
    xs = [t - t0 for t, _ in samples]
    ys = [v for _, v in samples]
    n = float(len(samples))
    mean_x = sum(xs) / n
    mean_y = sum(ys) / n
    sxx = sum((x - mean_x) ** 2 for x in xs)
    if sxx <= 0.0:
        return None  # all samples at one instant: slope undefined
    sxy = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, ys))
    return sxy / sxx


def _percentile(sorted_vals: List[float], q: float) -> float:
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _first_to_last_rate(samples: List[Tuple[float, float]]
                        ) -> Optional[float]:
    """First-to-last delta per second — the honest rate for monotonic
    counters (shared by :meth:`Historian.rate` and ``/history``'s
    ``rate_per_s`` so the two can never diverge)."""
    if len(samples) < 2:
        return None
    (t0, v0), (t1, v1) = samples[0], samples[-1]
    if t1 <= t0:
        return None
    return (v1 - v0) / (t1 - t0)


def maybe_build_historian(store=None) -> Optional["Historian"]:
    """The launcher's tolerant factory: a :class:`Historian` when
    ``BAGUA_OBS_HISTORIAN=on``, else None — and None WITH a warning on a
    misconfigured knob (e.g. a non-positive capacity).  An observability
    setting must degrade to "historian off", never kill the coordinator
    at bring-up (the HTTP plane's contract, held here too)."""
    if not _env.is_obs_historian_on():
        return None
    try:
        return Historian(store=store)
    except (ValueError, TypeError) as e:
        logger.warning("telemetry historian disabled (bad configuration): "
                       "%s", e)
        return None


class Historian:
    """Coordinator-side time-series store over the fleet-snapshot stream.

    Thread-safe: the monitor loop ingests while the HTTP plane's
    ``/history`` handler queries.
    """

    def __init__(self, capacity: Optional[int] = None,
                 window_s: Optional[float] = None, store=None,
                 persist_every: int = PERSIST_EVERY):
        self.capacity = int(
            _env.get_obs_historian_capacity() if capacity is None
            else capacity
        )
        if self.capacity <= 0:
            raise ValueError(
                f"historian capacity must be positive, got {self.capacity}"
            )
        self.window_s = float(
            _env.get_obs_historian_window_s() if window_s is None
            else window_s
        )
        self._store = store
        self._persist_every = max(1, int(persist_every))
        self._lock = threading.Lock()
        #: (rank_id, metric) -> deque[(time_unix, value)]
        self._rings: Dict[Tuple[str, str], deque] = {}
        self._last_ingest_unix: Optional[float] = None
        self._ingests_since_persist = 0
        if store is not None:
            self._load(store)

    # ---- restart persistence -------------------------------------------

    def _load(self, store) -> None:
        try:
            raw = store.get(STORE_KEY)
        except Exception as e:  # noqa: BLE001 - store may be coming up
            logger.debug("historian state not loaded: %s", e)
            return
        if not raw:
            return
        try:
            self.load_json(raw)
            logger.info(
                "historian: resumed %d series (last sample %.0f)",
                len(self._rings), self._last_ingest_unix or 0.0,
            )
        except (ValueError, TypeError, KeyError) as e:
            logger.warning("historian: persisted state unreadable (%s); "
                           "starting fresh", e)

    def _maybe_persist(self) -> None:
        if self._store is None:
            return
        self._ingests_since_persist += 1
        if self._ingests_since_persist < self._persist_every:
            return
        self._ingests_since_persist = 0
        try:
            self._store.set(STORE_KEY, self.to_json())
        except Exception as e:  # noqa: BLE001 - monitoring must not die
            logger.debug("historian state not persisted: %s", e)

    def to_json(self) -> str:
        with self._lock:
            payload = {
                "capacity": self.capacity,
                "last_ingest_unix": self._last_ingest_unix,
                "series": {
                    f"{rank}\x00{metric}": [[t, v] for t, v in ring]
                    for (rank, metric), ring in self._rings.items()
                },
            }
        return json.dumps(payload)

    def load_json(self, raw) -> None:
        text = raw.decode() if isinstance(raw, bytes) else str(raw)
        payload = json.loads(text)
        series = payload["series"]
        with self._lock:
            self._rings.clear()
            for key, samples in series.items():
                rank, _, metric = key.partition("\x00")
                ring = deque(maxlen=self.capacity)
                ring.extend((float(t), float(v)) for t, v in samples)
                self._rings[(rank, metric)] = ring
            self._last_ingest_unix = payload.get("last_ingest_unix")

    # ---- ingest ---------------------------------------------------------

    def _append(self, rank: str, metric: str, t: float, value) -> None:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        key = (str(rank), str(metric))
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = deque(maxlen=self.capacity)
        ring.append((float(t), float(value)))

    def ingest(self, record: dict) -> dict:
        """Consume one ``bagua-obs-fleet-v1`` record: append every numeric
        per-rank summary field (and the fleet efficiency aggregates) at
        the record's OWN ``time_unix``, then augment the record in place —
        each rank summary gains a ``trends`` sub-dict and the record a
        fleet-level ``trends`` rollup — and publish the fleet-worst trend
        gauges.  A duplicate/older ``time_unix`` is not new evidence and
        leaves the rings untouched (the autopilot's duplicate-snapshot
        guard, mirrored here so a re-read cannot bend a slope).  Returns
        the (augmented) record."""
        t = record.get("time_unix")
        if t is None:
            return record
        t = float(t)
        with self._lock:
            fresh = (self._last_ingest_unix is None
                     or t > self._last_ingest_unix)
            if fresh:
                self._last_ingest_unix = t
                for entry in (record.get("ranks") or {}).values():
                    if not isinstance(entry, dict):
                        continue
                    for rank_id, summary in (entry.get("obs") or {}).items():
                        if not isinstance(summary, dict):
                            continue
                        for metric, value in summary.items():
                            self._append(rank_id, metric, t, value)
                eff = record.get("efficiency") or {}
                for metric in ("goodput_fraction_mean",
                               "goodput_fraction_min"):
                    if eff.get(metric) is not None:
                        self._append(FLEET_RANK, metric, t, eff[metric])
        self._publish_trends(record)
        if fresh:
            self._maybe_persist()
        return record

    # ---- windowed queries ----------------------------------------------

    def metrics(self) -> List[Tuple[str, str]]:
        """Every (rank, metric) series held, sorted."""
        with self._lock:
            return sorted(self._rings)

    def ranks_for(self, metric: str) -> List[str]:
        with self._lock:
            return sorted({r for r, m in self._rings if m == metric})

    def window(self, rank, metric: str, window_s: Optional[float] = None,
               asof: Optional[float] = None) -> List[Tuple[float, float]]:
        """Samples of one series inside the trailing window.  The window
        anchors on ``asof`` when given (the trend path passes the last
        ingest time, so a series that STOPPED updating ages out of its
        window instead of republishing its final slope forever); without
        ``asof`` it anchors on the series' newest sample (the exploratory
        ``/history`` behavior).  Wall-clock-free either way — replays see
        identical windows."""
        window_s = self.window_s if window_s is None else float(window_s)
        with self._lock:
            ring = self._rings.get((str(rank), str(metric)))
            if not ring:
                return []
            anchor = ring[-1][0] if asof is None else float(asof)
            return [(t, v) for t, v in ring if 0 <= anchor - t <= window_s]

    def latest(self, rank, metric: str) -> Optional[float]:
        with self._lock:
            ring = self._rings.get((str(rank), str(metric)))
            return ring[-1][1] if ring else None

    def slope(self, rank, metric: str, window_s: Optional[float] = None,
              asof: Optional[float] = None) -> Optional[float]:
        """Least-squares slope (units/second) over the trailing window."""
        return least_squares_slope(self.window(rank, metric, window_s,
                                               asof=asof))

    def rate(self, rank, metric: str, window_s: Optional[float] = None,
             asof: Optional[float] = None) -> Optional[float]:
        """First-to-last delta per second over the window — the honest
        rate for monotonic counters (steps, tokens, event counts)."""
        return _first_to_last_rate(self.window(rank, metric, window_s,
                                               asof=asof))

    def percentile(self, rank, metric: str, q: float,
                   window_s: Optional[float] = None,
                   asof: Optional[float] = None) -> Optional[float]:
        samples = self.window(rank, metric, window_s, asof=asof)
        if not samples:
            return None
        return _percentile(sorted(v for _, v in samples), float(q))

    def mean(self, rank, metric: str, window_s: Optional[float] = None,
             asof: Optional[float] = None) -> Optional[float]:
        samples = self.window(rank, metric, window_s, asof=asof)
        if not samples:
            return None
        return sum(v for _, v in samples) / len(samples)

    def history_report(self, metric: str, rank=None,
                       window_s: Optional[float] = None) -> dict:
        """The ``/history?metric=&rank=&window=`` payload: per-rank
        samples + windowed stats for one metric."""
        window_s = self.window_s if window_s is None else float(window_s)
        ranks = [str(rank)] if rank is not None else self.ranks_for(metric)
        out: Dict[str, dict] = {}
        for rid in ranks:
            samples = self.window(rid, metric, window_s)
            if not samples:
                continue
            values = sorted(v for _, v in samples)
            out[rid] = {
                "samples": [[t, v] for t, v in samples],
                "latest": samples[-1][1],
                "p50": _percentile(values, 0.5),
                "p90": _percentile(values, 0.9),
                "slope_per_s": least_squares_slope(samples),
                "rate_per_s": _first_to_last_rate(samples),
            }
        return {"metric": str(metric), "window_s": window_s, "ranks": out}

    # ---- derived trends -------------------------------------------------

    def trend_summary(self, rank, asof: Optional[float] = None
                      ) -> Optional[dict]:
        """The derived trend gauges for one rank over the trailing window
        (None when nothing is computable yet):

        * ``goodput_slope`` — goodput_fraction per second.
        * ``hbm_headroom_slope`` — live HBM headroom bytes per second;
          ``hbm_headroom_eta_s`` projects exhaustion (latest headroom /
          -slope) when the slope is negative.
        * ``dcn_comm_share`` — windowed mean DCN device seconds over
          windowed mean step time (falls back to the DCN share of total
          comm when no step cadence rides the summary).

        Every window anchors on ``asof`` (default: the last ingest time):
        a series that stopped updating — a dead memory poll, a rank that
        no longer reports DCN seconds — ages out of its window instead of
        republishing its final slope into every later snapshot, so the
        autopilot can never act on evidence older than the window (the
        per-series analog of the suspect TTL).
        """
        asof = self._last_ingest_unix if asof is None else float(asof)
        out: dict = {}
        gp = self.slope(rank, "goodput_fraction", asof=asof)
        if gp is not None:
            out["goodput_slope"] = gp
        hbm_samples = self.window(rank, "hbm_headroom_bytes", asof=asof)
        hbm = least_squares_slope(hbm_samples)
        if hbm is not None:
            out["hbm_headroom_slope"] = hbm
            headroom = hbm_samples[-1][1]
            if hbm < 0 and headroom > 0:
                out["hbm_headroom_eta_s"] = headroom / -hbm
        dcn_samples = self.window(rank, "device_comm_dcn_s_per_step",
                                  asof=asof)
        if len(dcn_samples) >= MIN_TREND_SAMPLES:
            dcn = sum(v for _, v in dcn_samples) / len(dcn_samples)
            step_dt = self.mean(rank, "step_dt_p50", asof=asof)
            if step_dt and step_dt > 0:
                out["dcn_comm_share"] = min(1.0, dcn / step_dt)
            else:
                ici = self.mean(rank, "device_comm_ici_s_per_step",
                                asof=asof) or 0.0
                if dcn + ici > 0:
                    out["dcn_comm_share"] = dcn / (dcn + ici)
        if not out:
            return None
        out["window_s"] = self.window_s
        return out

    def _publish_trends(self, record: dict) -> None:
        """Augment the record's rank summaries with their ``trends`` and
        publish the fleet-worst values as gauges + a fleet rollup."""
        worst: Dict[str, float] = {}
        for entry in (record.get("ranks") or {}).values():
            if not isinstance(entry, dict):
                continue
            for rank_id, summary in (entry.get("obs") or {}).items():
                if not isinstance(summary, dict):
                    continue
                trends = self.trend_summary(rank_id)
                if not trends:
                    continue
                summary["trends"] = trends
                for key, keep_worse in (("goodput_slope", min),
                                        ("hbm_headroom_slope", min),
                                        ("dcn_comm_share", max)):
                    v = trends.get(key)
                    if v is None:
                        continue
                    worst[key] = (v if key not in worst
                                  else keep_worse(worst[key], v))
        if worst:
            record["trends"] = {f"{k}_worst": v for k, v in worst.items()}
            record["trends"]["window_s"] = self.window_s
        # gauges are refreshed EVERY publish, expired evidence included:
        # a key whose series aged out of the window reads 0 (flat / no
        # evidence), never the last alarming value — a resized-away
        # rank's steep headroom slope must not haunt dashboards for the
        # rest of the run
        for key, gauge in (("goodput_slope", "obs/goodput_slope"),
                           ("hbm_headroom_slope", "obs/hbm_headroom_slope"),
                           ("dcn_comm_share", "obs/dcn_comm_share")):
            counters.set_gauge(gauge, worst.get(key, 0.0))
