"""Crash flight recorder: every failure mode leaves a post-mortem artifact.

When a defense path fires — watchdog abort, grad-guard abort or skip-budget
escalation, health-fence stop, an armed fault firing, a fatal signal — this
module atomically dumps the last-N spans, a counters snapshot, and the
latest host-safe step metrics to a rank-tagged JSON under
``BAGUA_OBS_DUMP_DIR``.  The dump answers the question the scattered logs
could not: *what was this rank doing, and what had already gone wrong, at
the moment the defense tripped?*

Contracts:

* **Never raises, never blocks on the device.**  Callers are abort paths
  (the watchdog is about to ``os._exit``; the process may be wedged), so
  the dump reads only host state — the span ring, the counters, step
  metrics that were ALREADY read back (``export.note_step_metrics``).
* **Deterministic trigger-keyed filenames** (one file per trigger × rank ×
  pid, overwritten atomically) so drills can assert "this failure mode left
  its artifact" without parsing timestamps; repeated fires of one fault
  point update the same file to the latest state.
* **Worker-counter flush.**  ``BAGUA_ELASTIC_TELEMETRY_OUT`` used to get
  counters only on clean launcher exits; the dumps that matter most —
  watchdog abort (``os._exit`` skips atexit) and health-fence kills — now
  flush this process's counters to ``<out>.rank<r>.json`` too.
* **Import-light** (no jax): the watchdog waiter and the launcher call in.
"""

from __future__ import annotations

import json
import logging
import os
import re
import signal
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from .. import env as _env
from ..telemetry import counters
from . import export as _export
from . import spans as _spans

logger = logging.getLogger(__name__)

__all__ = ["dump_flight_record", "note_fault_fire", "validate_flight_record",
           "maybe_install_signal_hook", "FLIGHT_SCHEMA"]

FLIGHT_SCHEMA = "bagua-obs-flight-v1"

#: triggers the recorder knows about (documentation + schema validation;
#: unknown triggers still dump — a new defense path must not lose its
#: artifact to an enum)
KNOWN_TRIGGERS = ("watchdog_abort", "grad_guard_abort", "health_fence",
                  "fault_fire", "signal", "step_anomaly",
                  "autopilot_action")

_SAFE = re.compile(r"[^A-Za-z0-9_.-]")
_DUMP_LOCK = threading.Lock()


def _armed_fault_summaries() -> List[dict]:
    from ..faults import inject as _inject

    plan = _inject.get_plan()
    if plan is None:
        return []
    return [
        {"point": s.point, "kind": s.kind, "step": s.step, "op": s.op,
         "count": s.count, "seed": s.seed}
        for s in plan.specs
    ]


def _ledger_report() -> Optional[dict]:
    try:
        from .ledger import ledger

        return ledger.report()
    except Exception:  # noqa: BLE001 - a dying process must still die
        return None


def _fired_fault_counts(snap: Dict[str, Any]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for name, value in snap.items():
        if name.startswith("faults/") and name.endswith("/fired") and value:
            out[name[len("faults/"):-len("/fired")]] = int(value)
    return out


def _flush_elastic_counters(snap, trigger: str) -> None:
    """The satellite fix: on abort-class exits, this process's counters
    reach ``BAGUA_ELASTIC_TELEMETRY_OUT`` too — rank-suffixed, so a worker
    flush never clobbers the launcher's own ``{counters, transitions}``
    dump."""
    out = _env.get_elastic_telemetry_out()
    if not out:
        return
    path = f"{out}.rank{int(_env.get_rank())}.json"
    _export._atomic_write(path, json.dumps(
        {"trigger": trigger, "counters": dict(snap),
         "time_unix": time.time()}, indent=1))


def dump_flight_record(trigger: str, reason: str = "",
                       fault_point: Optional[str] = None,
                       extra: Optional[dict] = None) -> Optional[str]:
    """Write the post-mortem dump; returns its path (None when no dump dir
    is configured and no elastic-telemetry flush applies, or the plane is
    off).  Exception-free by contract."""
    try:
        if not _spans.enabled():
            return None
        dump_dir = _env.get_obs_dump_dir()
        snap = counters.snapshot()
        try:
            _flush_elastic_counters(snap, trigger)
        except OSError as e:
            logger.debug("elastic counter flush failed: %s", e)
        if not dump_dir:
            return None
        record: Dict[str, Any] = {
            "schema": FLIGHT_SCHEMA,
            "trigger": trigger,
            "reason": reason,
            "fault_point": fault_point,
            "rank": int(_env.get_rank()),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "time_unix": time.time(),
            "spans": _spans.recorder.snapshot(),
            # sections still IN FLIGHT at dump time — a wedged collective's
            # watched section never exits, so this list is the headline of
            # a hang post-mortem
            "active_spans": _spans.recorder.active_snapshot(),
            "spans_dropped": _spans.recorder.dropped,
            "counters": dict(snap),
            "counters_collected_at": snap.collected_at,
            "step_metrics": _export.last_step_metrics(),
            "obs_summary": _export.local_obs_summary(),
            "armed_faults": _armed_fault_summaries(),
            "fired_faults": _fired_fault_counts(snap),
            # goodput ledger at the moment the defense tripped: "the run
            # died having spent N s in class X" is post-mortem headline
            # material (None before any step window was noted)
            "ledger": _ledger_report(),
        }
        if extra:
            record["extra"] = extra
        name = "flight_{}_rank{}_pid{}.json".format(
            _SAFE.sub("_", trigger)
            + (("_" + _SAFE.sub("_", fault_point)) if fault_point else ""),
            record["rank"], os.getpid(),
        )
        path = os.path.join(dump_dir, name)
        with _DUMP_LOCK:
            os.makedirs(dump_dir, exist_ok=True)
            _export._atomic_write(
                path, json.dumps(record, indent=1, sort_keys=True)
            )
            _prune_dumps(dump_dir, keep=path)
        counters.incr_many({"obs/flight_dumps": 1})
        logger.warning("flight recorder: %s dump written to %s", trigger,
                       path)
        return path
    except Exception as e:  # noqa: BLE001 - a dying process must still die
        logger.warning("flight recorder dump failed: %s", e)
        return None


def _prune_dumps(dump_dir: str, keep: str) -> None:
    """Retention cap for the dump directory (``BAGUA_OBS_DUMP_MAX_FILES``,
    0 = unbounded): dumps are overwritten per (trigger, fault point,
    rank, pid), so growth comes from restarts minting fresh pids — a long
    run with recurring throttled faults used to accumulate dumps without
    limit.  Oldest-first by mtime, never the file just written; pruned
    count lands in ``obs/flight_dumps_pruned``.  Caller holds
    ``_DUMP_LOCK``; only ``flight_*.json`` files are candidates (span-ring
    ``spans_*.json`` dumps live in the same directory and are not ours to
    reap)."""
    max_files = _env.get_obs_dump_max_files()
    if max_files <= 0:
        return
    try:
        entries = []
        with os.scandir(dump_dir) as it:
            for entry in it:
                if not entry.name.startswith("flight_") \
                        or not entry.name.endswith(".json"):
                    continue
                try:
                    entries.append((entry.stat().st_mtime, entry.path))
                except OSError:
                    continue  # vanished between scandir and stat
        excess = len(entries) - max_files
        if excess <= 0:
            return
        pruned = 0
        keep = os.path.abspath(keep)
        for _, victim in sorted(entries):
            if pruned >= excess:
                break
            if os.path.abspath(victim) == keep:
                continue
            try:
                os.unlink(victim)
                pruned += 1
            except OSError:
                continue
        if pruned:
            counters.incr_many({"obs/flight_dumps_pruned": pruned})
            logger.info("flight recorder: pruned %d dump(s) over the "
                        "%d-file retention cap", pruned, max_files)
    except OSError as e:  # pragma: no cover - directory-level races
        logger.debug("flight dump pruning skipped: %s", e)


_LAST_FIRE_DUMP: Dict[str, float] = {}
_FIRE_DUMP_MIN_INTERVAL_S = 2.0


def note_fault_fire(point: str, kind: str) -> None:
    """Hook for :mod:`bagua_tpu.faults.inject`: an armed-fault fire leaves
    (or refreshes) a dump naming the firing point, so every chaos-drill
    failure mode has its artifact even when the defense path dies before
    its own dump.  Cheap no-op unless a dump dir or elastic telemetry out
    is configured.  A point's FIRST fire always dumps; repeat fires
    (``count=-1`` specs like ``step.straggle`` fire once per step, inside
    legs whose throughput the drills measure) refresh the file at most
    every ~2 s — the dump is overwritten per (trigger, point, rank, pid)
    anyway, so a repeat fire only buys a fresher snapshot."""
    if not (_env.get_obs_dump_dir() or _env.get_elastic_telemetry_out()):
        return
    now = time.monotonic()
    last = _LAST_FIRE_DUMP.get(point)
    if last is not None and now - last < _FIRE_DUMP_MIN_INTERVAL_S:
        return
    _LAST_FIRE_DUMP[point] = now
    dump_flight_record("fault_fire", reason=f"{point}:{kind} fired",
                       fault_point=point)


def validate_flight_record(record: dict) -> List[str]:
    """Schema problems with a flight dump ([] = valid) — shared by the
    chaos drills, the CI smoke trace, and the bench-sanity gate."""
    problems: List[str] = []
    if record.get("schema") != FLIGHT_SCHEMA:
        problems.append(f"schema != {FLIGHT_SCHEMA}")
    if not record.get("trigger"):
        problems.append("missing trigger")
    for key, typ in (("rank", int), ("pid", int), ("time_unix", (int, float)),
                     ("spans", list), ("active_spans", list),
                     ("spans_dropped", int),
                     ("counters", dict), ("step_metrics", dict),
                     ("armed_faults", list), ("fired_faults", dict)):
        if not isinstance(record.get(key), typ):
            problems.append(f"missing/mistyped {key}")
    for i, span in enumerate(record.get("spans") or []):
        for key in ("name", "t0", "t1", "dur_s", "rank", "depth"):
            if key not in span:
                problems.append(f"span[{i}] missing {key}")
                break
    if record.get("trigger") == "fault_fire" and not record.get("fault_point"):
        problems.append("fault_fire dump without fault_point")
    return problems


_SIGNAL_HOOKED = False


def maybe_install_signal_hook() -> bool:
    """Chain a SIGTERM handler that dumps a ``signal`` flight record before
    the previous disposition runs — the launcher's ``kill_gang`` SIGTERM is
    how fenced/stopped workers die, and their counters would otherwise
    vanish.  Main-thread only (signal module restriction); installed once
    per process, only while a dump dir is configured."""
    global _SIGNAL_HOOKED
    if _SIGNAL_HOOKED or not _env.get_obs_dump_dir():
        return False
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            # The handler interrupts the main thread mid-bytecode — it may
            # already hold the counters lock, the span-ring lock, or
            # _DUMP_LOCK, all non-reentrant.  Dump from a helper thread and
            # give up after a bounded join: in that (rare) race we lose the
            # dump, never the exit — a dying process must still die.
            t = threading.Thread(
                target=dump_flight_record, args=("signal",),
                kwargs={"reason": "SIGTERM"},
                name="bagua-obs-sigterm-dump", daemon=True,
            )
            t.start()
            t.join(timeout=5)
            if prev is signal.SIG_IGN:
                return  # the process was configured to ignore SIGTERM
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        _SIGNAL_HOOKED = True
        return True
    except (ValueError, OSError) as e:  # pragma: no cover - env-dependent
        logger.debug("signal hook not installed: %s", e)
        return False
