"""Step-span tracer: a host-side structured timeline of what this process
was doing.

Counterpart of the reference's OpenTelemetry span pipeline — the Rust
backend opens a ``tensor_ready`` span per gradient and a custom exporter
POSTs batches to the autotune sidecar (bagua-core-internal/src/lib.rs:305-308,
bagua-opentelemetry/src/exporter/mod.rs:15-59).  Under XLA the compiled step
is opaque, so what a span can honestly time is the HOST side: dispatch,
trace/compile, grad-guard verdict readbacks, async negotiation boundaries,
checkpoint save/restore, elastic rendezvous rounds, watchdog sections — the
exact phases a human (or the autotune-v2 scorer) needs to answer "what was
rank 3 doing when the watchdog fired?".

Design constraints, in order:

* **Never touches the device.**  ``trace_span`` records two
  ``time.monotonic()`` reads and a deque append — no jnp ops, no readbacks —
  so the compiled step program is IDENTICAL with tracing on or off
  (jaxpr-equality-pinned in ``tests/test_obs.py``).  Spans opened inside
  traced code (the per-bucket collective launches in the overlap scheduler)
  run at *trace time* and document the launch schedule, not per-step
  runtime.
* **Bounded.**  Spans land in a ring buffer (``BAGUA_OBS_RING``, default
  512); the oldest drop and the drop count is kept, so a long run can crash
  at step 10^6 and still leave a readable tail.
* **Import-light.**  No jax import: the launcher and the watchdog waiter
  thread open spans too.

``BAGUA_OBS=off`` turns every hook into a cheap early return (one module
flag read) — the default-compatible mode.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .. import env as _env

__all__ = ["trace_span", "recorder", "span_ring", "SpanRecorder", "enabled",
           "set_enabled", "set_current_step", "set_ledger_sink"]

#: resolved master switch; None = not yet read from BAGUA_OBS
_ENABLED: Optional[bool] = None
_ENABLED_LOCK = threading.Lock()


def enabled() -> bool:
    """Whether the observability plane is on (``BAGUA_OBS``, default on).
    Cached after the first read — the check sits on the train-step hot
    path."""
    global _ENABLED
    if _ENABLED is None:
        with _ENABLED_LOCK:
            if _ENABLED is None:
                _ENABLED = _env.get_obs_mode() == "on"
    return _ENABLED


def set_enabled(value: Optional[bool]) -> None:
    """Override the cached switch (tests); ``None`` re-reads ``BAGUA_OBS``
    on the next :func:`enabled` call."""
    global _ENABLED
    with _ENABLED_LOCK:
        _ENABLED = value


def _cached_rank() -> int:
    global _RANK
    if _RANK is None:
        try:
            _RANK = int(_env.get_rank())
        except Exception:  # noqa: BLE001 - spans must never raise
            _RANK = 0
    return _RANK


_RANK: Optional[int] = None

#: the trainer's current step counter, stamped onto every span opened while
#: that step is being driven (threads like the watchdog waiter inherit it —
#: "which step was in flight" is exactly what a post-mortem wants to know)
_CURRENT_STEP: Optional[int] = None


def set_current_step(step: Optional[int]) -> None:
    global _CURRENT_STEP
    _CURRENT_STEP = step


#: goodput-ledger sink (``bagua_tpu.obs.ledger.install()`` sets it): spans
#: whose names map to a ledger class feed their wall seconds on close.
#: None (the default) keeps the enter/exit pair at its pre-ledger cost.
_LEDGER_SINK = None


def set_ledger_sink(sink) -> None:
    """Install (or clear, with None) the goodput-ledger span sink — an
    object with ``span_enter(name) -> cls|None`` and
    ``span_exit(cls, dur_s)``."""
    global _LEDGER_SINK
    _LEDGER_SINK = sink


class SpanRecorder:
    """Thread-safe bounded ring buffer of finished spans.

    One per process (:data:`recorder`), like the telemetry counters; the
    flight recorder snapshots it on failure, the exporter may sample it.
    Capacity comes from ``BAGUA_OBS_RING`` lazily (the module imports
    before test harnesses set their env)."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._spans: Optional[deque] = (
            deque(maxlen=capacity) if capacity else None
        )
        self._dropped = 0
        self._local = threading.local()
        #: spans currently OPEN (entered, not yet exited), keyed by the
        #: span object: the flight recorder reports these as "what was in
        #: flight when the defense tripped" — a wedged watched section
        #: never reaches the ring, but it IS the post-mortem's headline
        self._open: Dict[int, Dict[str, Any]] = {}

    def _buf(self) -> deque:
        if self._spans is None:
            self._capacity = max(1, _env.get_obs_ring_size())
            self._spans = deque(maxlen=self._capacity)
        return self._spans

    def set_capacity(self, capacity: int) -> None:
        """Re-size the ring (tests); drops existing spans."""
        with self._lock:
            self._capacity = int(capacity)
            self._spans = deque(maxlen=self._capacity)
            self._dropped = 0

    # -- depth bookkeeping (per thread, so nesting renders correctly even
    # with the watchdog waiter recording concurrently) ----------------------

    def _enter(self) -> int:
        d = getattr(self._local, "depth", 0)
        self._local.depth = d + 1
        return d

    def _exit(self) -> None:
        self._local.depth = max(0, getattr(self._local, "depth", 1) - 1)

    def open_span(self, key: int, stub: Dict[str, Any]) -> None:
        with self._lock:
            self._open[key] = stub

    def close_span(self, key: int, span: Dict[str, Any]) -> None:
        """Pop the open stub and append the finished span — one lock
        acquisition for both."""
        with self._lock:
            self._open.pop(key, None)
            buf = self._buf()
            if len(buf) == buf.maxlen:
                self._dropped += 1
            buf.append(span)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Copies of every retained (finished) span, oldest first."""
        with self._lock:
            return [dict(s) for s in self._buf()]

    def active_snapshot(self) -> List[Dict[str, Any]]:
        """Copies of spans currently in flight (entered, not exited),
        oldest first — the sections a hang is pinning."""
        with self._lock:
            return sorted((dict(s) for s in self._open.values()),
                          key=lambda s: s["t0"])

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            if self._spans is not None:
                self._spans.clear()
            self._open.clear()
            self._dropped = 0


#: process-wide span ring (one per process, like ``telemetry.counters``);
#: ``span_ring`` is the collision-free alias the package re-exports
#: (``obs.recorder`` is the flight-recorder MODULE)
recorder = SpanRecorder()
span_ring = recorder


class _Span:
    """The context manager behind :func:`trace_span` — a plain class with
    ``__slots__`` instead of ``contextlib.contextmanager`` because the
    enter/exit pair sits on the train-step hot path (measured in
    ``tests/test_obs.py`` against the <2%-of-step-time budget)."""

    __slots__ = ("name", "attrs", "t0", "step", "ledger_cls")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self.step = self.attrs.pop("step", _CURRENT_STEP)
        depth = recorder._enter()
        # ledger ownership resolves at open (outermost mapped span wins);
        # one global read when no sink is installed
        self.ledger_cls = (
            _LEDGER_SINK.span_enter(self.name) if _LEDGER_SINK else None
        )
        self.t0 = time.monotonic()
        recorder.open_span(id(self), {
            "name": self.name,
            "t0": self.t0,
            "rank": _cached_rank(),
            "step": self.step,
            "depth": depth,
            "thread": threading.current_thread().name,
        })
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.monotonic()
        depth = getattr(recorder._local, "depth", 1) - 1
        recorder._exit()
        if self.ledger_cls is not None and _LEDGER_SINK is not None:
            _LEDGER_SINK.span_exit(self.ledger_cls, t1 - self.t0)
        span = {
            "name": self.name,
            "t0": self.t0,
            "t1": t1,
            "dur_s": t1 - self.t0,
            "rank": _cached_rank(),
            "step": self.step,
            "depth": depth,
            "thread": threading.current_thread().name,
        }
        if exc_type is not None:
            span["error"] = exc_type.__name__
        if self.attrs:
            span["attrs"] = self.attrs
        recorder.close_span(id(self), span)
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL = _NullSpan()


def trace_span(name: str, **attrs):
    """Open a structured span::

        with trace_span("step/bucket_collective", bucket=i, bytes=n):
            ...

    Records monotonic start/end, duration, rank, the trainer's current step
    (override with ``step=``), nesting depth, thread name, and the given
    key=value attrs into the process ring buffer.  A no-op (returns a
    shared null context) while ``BAGUA_OBS=off``.  Attrs must be host
    values (ints/floats/strings) — never tracers."""
    if not enabled():
        return _NULL
    return _Span(name, attrs)
