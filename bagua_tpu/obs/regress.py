"""Bench-trend sentinel: is this tree slower than the committed record?

The repo carries measured artifacts (``BENCH_FLAT.json``,
``BENCH_OVERLAP.json``, ...) whose records were produced by the interleaved
A/B best-of-trials protocol (``benchmarks/_ab.py``).  Nothing re-reads them
after commit — a hot-path regression shows up only when someone happens to
re-run a bench.  This module closes the loop: re-measure a small probe
suite with the SAME measurement functions, compare record-by-record
against the committed values, and write ``BENCH_TREND.json``
(schema ``bagua-bench-trend-v1``).

The comparison is **noise-bound-aware**, in the _ab.py sense: a committed
record's ``per_trial_ratios`` spread is its own honesty statement about
run-to-run variance, so the regression tolerance for that metric is at
least that half-spread (never below ``--tolerance``, default 10% — the
observed cpu-sim noise floor); a committed or fresh record flagged
``noise_bound`` can only ever produce a ``noise_bound`` verdict, never a
``regressed`` one.  Fewer probe trials than the committed run (3 vs 5)
bias the fresh best-of LOW, i.e. toward false alarms — which is why the
sentinel runs **advisory** in ``scripts/ci.sh`` (prints, writes the trend,
exits 0); ``--strict`` turns regressions into a non-zero exit for operator
use.

CLI::

    python -m bagua_tpu.obs.regress                 # quick probe vs BENCH_FLAT.json
    python -m bagua_tpu.obs.regress --fresh f.json --against BENCH_FLAT.json
    python -m bagua_tpu.obs.regress --strict --trials 5
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

__all__ = ["TREND_SCHEMA", "compare_records", "run_quick_probe",
           "validate_bench_trend", "main"]

TREND_SCHEMA = "bagua-bench-trend-v1"

#: observed run-to-run variance floor of the cpu-sim throughput benches
#: (BENCH_FLAT gate provenance records 0.88-1.13x across runs)
DEFAULT_TOLERANCE = 0.10

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _half_spread(record: dict) -> float:
    """Half the per-trial ratio spread of an _ab.py record — its own
    measured noise band (0 when the record carries no trials)."""
    ratios = record.get("per_trial_ratios")
    if not isinstance(ratios, list) or len(ratios) < 2:
        return 0.0
    try:
        return (max(ratios) - min(ratios)) / 2.0
    except TypeError:
        return 0.0


def _direction(*records: dict) -> Optional[bool]:
    """The comparison direction for a record pair: True (higher is
    better), False (lower is better — e.g. memory footprints), or None
    (unknown — the pair is skipped rather than compared with an assumed
    direction: a lower-is-better metric run through a higher-is-better
    comparison INVERTS the verdict, which is worse than no verdict).

    An explicit ``higher_better`` field (the EFFICIENCY.json trend records
    carry one) wins; otherwise the heuristic recognizes throughput records
    (unit carries a rate, ``.../s...``) and _ab.py speedup records
    (``per_trial_ratios``/``faster_path``) as higher-is-better."""
    for rec in records:
        if isinstance(rec.get("higher_better"), bool):
            return rec["higher_better"]
    for rec in records:
        unit = rec.get("unit") or ""
        if "/s" in unit:
            return True
        if "per_trial_ratios" in rec or "faster_path" in rec:
            return True
    return None


def compare_records(fresh: Sequence[dict], committed: Sequence[dict],
                    tolerance: float = DEFAULT_TOLERANCE) -> List[dict]:
    """Per-metric fresh/committed comparison; returns one verdict dict per
    metric present in BOTH with a positive numeric value and a KNOWN
    direction (see :func:`_is_higher_better` — direction-unknown metrics
    are skipped, never guessed).

    Verdicts: ``ok`` (within tolerance), ``improved`` (above it),
    ``regressed`` (below it, and neither side is noise-bound),
    ``noise_bound`` (below it but either side's own trial spread says the
    comparison cannot support a conclusion)."""
    by_metric: Dict[str, dict] = {
        r["metric"]: r for r in committed
        if isinstance(r, dict) and r.get("metric")
    }
    out: List[dict] = []
    for rec in fresh:
        if not isinstance(rec, dict):
            continue
        name = rec.get("metric")
        base = by_metric.get(name)
        if base is None:
            continue
        fv, cv = rec.get("value"), base.get("value")
        if not isinstance(fv, (int, float)) \
                or not isinstance(cv, (int, float)) or cv <= 0 or fv <= 0:
            continue
        higher = _direction(rec, base)
        if higher is None:
            continue
        ratio = fv / cv
        # score normalizes direction: > 1 is always "got better"
        score = ratio if higher else cv / fv
        tol = max(float(tolerance), _half_spread(base), _half_spread(rec))
        noisy = bool(base.get("noise_bound") or rec.get("noise_bound"))
        if score < 1.0 - tol:
            verdict = "noise_bound" if noisy else "regressed"
        elif score > 1.0 + tol:
            verdict = "improved"
        else:
            verdict = "ok"
        out.append({
            "metric": name,
            "fresh_value": fv,
            "committed_value": cv,
            "unit": rec.get("unit") or base.get("unit"),
            "ratio": round(ratio, 3),
            "higher_better": higher,
            "tolerance": round(tol, 3),
            "noise_bound": noisy,
            "verdict": verdict,
        })
    return out


def run_quick_probe(trials: int = 3) -> List[dict]:
    """Re-measure the BENCH_FLAT headline config (gradient_allreduce,
    accum 1, flat on vs off) with the SAME measurement function and
    interleaved protocol the committed artifact used — smaller trial
    count, recorded in the output's ``timing`` tags.

    Runs in a SUBPROCESS pinned to the 8-device cpu-sim mesh: that is
    where the committed cpu records were measured (a probe on a different
    topology compares nothing), and by the time this module can act, the
    importing process has usually initialized jax already —
    ``JAX_PLATFORMS``/``XLA_FLAGS`` only bind before first device use.
    The probe's own anomaly detector is disabled: an interleaved bench's
    leg switches are not fleet anomalies."""
    import subprocess

    import re

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # force EXACTLY 8 devices: an inherited ...device_count=4 (local
    # debugging) would otherwise survive a substring check and measure the
    # wrong mesh against the committed 8-device records
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", "")).strip()
    env["XLA_FLAGS"] = (flags + " "
                        "--xla_force_host_platform_device_count=8").strip()
    env["BAGUA_OBS_ANOMALY"] = "off"
    proc = subprocess.run(
        [sys.executable, "-m", "bagua_tpu.obs.regress", "--probe-only",
         "--trials", str(int(trials))],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=1200,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"quick probe subprocess failed (rc {proc.returncode}): "
            f"{proc.stderr[-2000:]}"
        )
    # records are the last line of stdout (the benches print progress)
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _run_probe_inprocess(trials: int) -> List[dict]:
    """The subprocess half of :func:`run_quick_probe` — assumes the env
    (cpu-sim topology) was set before jax initialized."""
    import jax
    import numpy as np

    sys.path.insert(0, _REPO)
    from benchmarks._ab import interleaved_ab, speedup_record
    from benchmarks.flat_resident_bench import measure

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) != 8:
        logger.warning(
            "quick probe running on %d %s device(s), not the 8-dev "
            "cpu-sim mesh the committed records used — expect no or "
            "meaningless comparisons",
            len(jax.devices()), jax.devices()[0].platform,
        )
    off, on, ratios = interleaved_ab(
        lambda: measure("gradient_allreduce", 1, "off", repeats=1),
        lambda: measure("gradient_allreduce", 1, "on", repeats=1),
        trials=trials,
    )
    faster = "on" if float(np.median(ratios)) >= 1.0 else "off"
    speedup = speedup_record(
        "flat_speedup_gradient_allreduce_accum1", ratios, "flat/leaf",
        faster_path=faster, platform=on["platform"],
    )
    records = [off, on, speedup]
    # efficiency trend records (EFFICIENCY.json consumption): the quick
    # probe re-measures the headline config's goodput + static footprint.
    # The footprint comparison is deterministic (memory bloat WILL flag);
    # the goodput one is marked noise_bound by the quick measure itself.
    try:
        from benchmarks.efficiency_bench import efficiency_trend_records

        records += efficiency_trend_records(quick=True)
    except Exception as e:  # noqa: BLE001 - advisory sentinel stays alive
        logger.warning("efficiency probe skipped: %s", e)
    return records


def build_trend(comparisons: List[dict], mode: str,
                against: Sequence[str], trials: Optional[int],
                strict: bool) -> dict:
    regressions = [c["metric"] for c in comparisons
                   if c["verdict"] == "regressed"]
    record = {
        "schema": TREND_SCHEMA,
        "time_unix": time.time(),
        "mode": mode,
        "against": list(against),
        "advisory": not strict,
        "tolerance_floor": DEFAULT_TOLERANCE,
        "comparisons": comparisons,
        "regressions": regressions,
        "improved": [c["metric"] for c in comparisons
                     if c["verdict"] == "improved"],
        "noise_bound": [c["metric"] for c in comparisons
                        if c["verdict"] == "noise_bound"],
        "pass": not regressions,
    }
    if trials is not None:
        record["probe_trials"] = trials
    try:
        import jax

        record["platform"] = jax.devices()[0].platform
    except Exception:  # noqa: BLE001 - file-vs-file mode needs no jax
        record["platform"] = None
    return record


def validate_bench_trend(record: dict) -> List[str]:
    """Schema problems with a BENCH_TREND.json ([] = valid) — the
    ``test_bench_sanity`` gate."""
    problems: List[str] = []
    if not isinstance(record, dict):
        return ["not a JSON object"]
    if record.get("schema") != TREND_SCHEMA:
        problems.append(f"schema != {TREND_SCHEMA}")
    for key, typ in (("time_unix", (int, float)), ("comparisons", list),
                     ("regressions", list), ("pass", bool),
                     ("advisory", bool), ("against", list)):
        if not isinstance(record.get(key), typ):
            problems.append(f"missing/mistyped {key}")
    for i, cmp_ in enumerate(record.get("comparisons") or []):
        for key in ("metric", "fresh_value", "committed_value", "ratio",
                    "tolerance", "verdict"):
            if key not in cmp_:
                problems.append(f"comparisons[{i}] missing {key}")
                break
        if cmp_.get("verdict") not in ("ok", "improved", "regressed",
                                       "noise_bound"):
            problems.append(
                f"comparisons[{i}] bad verdict {cmp_.get('verdict')!r}")
    if not (record.get("comparisons") or []):
        problems.append("comparisons empty — the sentinel measured nothing")
    return problems


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bagua_tpu.obs.regress",
        description="Compare a fresh bench run against the committed "
                    "BENCH_*.json records (noise-bound-aware) and write "
                    "BENCH_TREND.json.",
    )
    ap.add_argument("--fresh", default=None,
                    help="fresh bench records (JSON list); default: run "
                         "the quick probe suite in-process")
    ap.add_argument("--against", action="append", default=None,
                    help="committed artifact(s) to compare against "
                         "(default: BENCH_FLAT.json at the repo root); "
                         "repeatable")
    ap.add_argument("--out", default=os.path.join(_REPO, "BENCH_TREND.json"),
                    help="trend artifact path (default: BENCH_TREND.json)")
    ap.add_argument("--trials", type=int, default=3,
                    help="quick-probe interleaved trials (default 3)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="regression tolerance floor (default 0.10)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on regressions (default: advisory exit 0)")
    ap.add_argument("--probe-only", action="store_true",
                    help=argparse.SUPPRESS)  # internal: subprocess half
    args = ap.parse_args(argv)

    if args.probe_only:
        records = _run_probe_inprocess(max(1, args.trials))
        print(json.dumps(records))
        return 0

    against = args.against
    if not against:
        against = [os.path.join(_REPO, "BENCH_FLAT.json")]
        # the efficiency artifact joins the default comparison set when
        # committed: its trend_records carry explicit directions
        efficiency = os.path.join(_REPO, "EFFICIENCY.json")
        if os.path.exists(efficiency):
            against.append(efficiency)
    committed: List[dict] = []
    for path in against:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read committed records {path}: {e}",
                  file=sys.stderr)
            return 2
        if isinstance(data, dict) and "trend_records" in data:
            # an EFFICIENCY.json-shaped artifact: compare its embedded
            # trend records (schema-gated in test_bench_sanity)
            committed.extend(data["trend_records"])
        else:
            committed.extend(data if isinstance(data, list) else [data])

    trials: Optional[int] = None
    if args.fresh:
        with open(args.fresh) as f:
            fresh = json.load(f)
        if isinstance(fresh, dict) and "trend_records" in fresh:
            fresh = fresh["trend_records"]
        mode = "files"
    else:
        trials = max(1, args.trials)
        print(f"running quick probe ({trials} interleaved trials)...",
              flush=True)
        fresh = run_quick_probe(trials=trials)
        mode = "quick_probe"

    comparisons = compare_records(fresh, committed,
                                  tolerance=args.tolerance)
    if not comparisons:
        print("no comparable metrics between fresh and committed records",
              file=sys.stderr)
        return 2
    record = build_trend(comparisons, mode, against, trials, args.strict)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")

    for c in comparisons:
        print(f"  {c['verdict']:>11}  {c['metric']}: "
              f"{c['fresh_value']} vs {c['committed_value']} "
              f"(x{c['ratio']}, tol ±{c['tolerance']})")
    n_reg = len(record["regressions"])
    print(f"wrote {args.out}: {len(comparisons)} metric(s), "
          f"{n_reg} regression(s), "
          f"{len(record['noise_bound'])} noise-bound — "
          f"{'PASS' if record['pass'] else 'REGRESSED'}"
          f"{' (advisory)' if record['advisory'] else ''}")
    if n_reg and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
