"""HTTP status plane: a stdlib-threaded pull endpoint per process.

The obs plane's push half (the :class:`~bagua_tpu.obs.export
.MetricsExporter` writing ``metrics.jsonl``/``metrics.prom``) needs a
filesystem an operator can reach; a fleet of pods does not have one.
This module is the pull half — the reference Bagua runs a Flask autotune
sidecar every rank talks to; here every process can serve its own
read-only status over ``http.server`` (no new dependency, no app
framework):

* ``GET /metrics`` — Prometheus text rendered from the SAME prepared
  counters snapshot ``metrics.prom`` is written from
  (:func:`bagua_tpu.obs.export.prepared_snapshot`), so a live scrape and
  the on-disk file expose the identical series set, each with the
  registry's ``# HELP``/``# TYPE`` lines.
* ``GET /healthz`` — liveness JSON (rank, latest step, goodput fraction
  when the ledger has one).
* ``GET /ledger`` — the goodput ledger report
  (:meth:`bagua_tpu.obs.ledger.GoodputLedger.report`).
* Coordinator only (the process hosting the fleet merge): ``GET /fleet``
  — the latest ``bagua-obs-fleet-v1`` record — and
  ``GET /history?metric=&rank=&window=`` — windowed samples + stats from
  the telemetry historian (:mod:`bagua_tpu.obs.historian`).

Gated by ``BAGUA_OBS_HTTP_PORT`` (0 = off, the default) and bound to
``BAGUA_OBS_HTTP_ADDR`` (loopback by default — the endpoints are
read-only but unauthenticated).  A taken port falls back to an ephemeral
one: on a single host the elastic launcher offsets each worker's port,
but ad-hoc runs must never die on a bind race.  The bound port is logged
and published as the ``obs/http_port`` gauge.

Host-side only by construction — handlers read counters, the span ring,
and pre-read-back summaries; they never touch a device array — so the
compiled step is identical with the server on or off (jaxpr-pinned in
``tests/test_obs_http.py``).  Import-light (no jax): the launcher's
coordinator serves ``/fleet`` without paying a jax import.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlsplit

from .. import env as _env
from ..telemetry import counters
from . import export as _export

logger = logging.getLogger(__name__)

__all__ = ["ObsHTTPServer", "maybe_start_global_http_server"]

#: Prometheus exposition-format content type (text version 0.0.4)
_PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _ledger_report() -> Optional[dict]:
    from .ledger import ledger

    return ledger.report()


class _Handler(BaseHTTPRequestHandler):
    """One request handler; the owning :class:`ObsHTTPServer` hangs its
    hooks off the server object (``self.server``)."""

    server_version = "bagua-obs/1"

    # ---- plumbing -------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        logger.debug("obs http: " + fmt, *args)

    def _respond(self, code: int, body: str, content_type: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _json(self, payload: Any, code: int = 200) -> None:
        self._respond(code, json.dumps(payload, indent=1, sort_keys=True),
                      "application/json")

    def _not_found(self, why: str) -> None:
        self._json({"error": why}, code=404)

    # ---- routes ---------------------------------------------------------

    def do_GET(self):  # noqa: N802 - stdlib handler name
        counters.incr("obs/http_requests")
        url = urlsplit(self.path)
        try:
            if url.path == "/metrics":
                self._respond(
                    200,
                    _export.render_prometheus(_export.prepared_snapshot()),
                    _PROM_CONTENT_TYPE,
                )
            elif url.path == "/healthz":
                self._healthz()
            elif url.path == "/ledger":
                report = _ledger_report()
                self._json(report if report is not None
                           else {"available": False,
                                 "rationale": "no step window noted yet"})
            elif url.path == "/fleet":
                self._fleet()
            elif url.path == "/history":
                self._history(parse_qs(url.query))
            else:
                self._not_found(f"no route {url.path}; have /metrics "
                                "/healthz /ledger /fleet /history")
        except BrokenPipeError:  # client went away mid-write
            pass
        except Exception as e:  # noqa: BLE001 - a scrape must not kill
            logger.warning("obs http: %s failed: %s", url.path, e)
            try:
                self._json({"error": f"{type(e).__name__}: {e}"}, code=500)
            except OSError:
                pass

    def _healthz(self) -> None:
        import time

        summary = _export.local_obs_summary()
        payload: dict = {
            "status": "ok",
            "rank": int(_env.get_rank()),
            "time_unix": time.time(),
        }
        if summary:
            payload["step"] = summary.get("step")
            if "goodput_fraction" in summary:
                payload["goodput_fraction"] = summary["goodput_fraction"]
        self._json(payload)

    def _fleet(self) -> None:
        provider = getattr(self.server, "fleet_provider", None)
        record = provider() if provider is not None else None
        if record is None:
            self._not_found("no fleet record (not the coordinator, or no "
                            "snapshot merged yet)")
            return
        self._respond(200, self._fleet_body(record), "application/json")

    def _fleet_body(self, record: dict) -> str:
        # /fleet is the one route whose payload is identical between
        # monitor ticks but whose serialization grows with world size
        # (O(nnodes) record, sorted keys, indentation) — under a many-
        # scraper load at pod scale the coordinator burned its single
        # monitor core re-rendering the same record per request.  Cache
        # the rendered body keyed on record object IDENTITY: the monitor
        # loop builds a fresh record object per tick, so `is` is exactly
        # "same tick's record" with no hashing or deep comparison.
        if not getattr(self.server, "cache_fleet_json", True):
            return json.dumps(record, indent=1, sort_keys=True)
        lock = getattr(self.server, "fleet_cache_lock", None)
        if lock is None:
            lock = self.server.fleet_cache_lock = threading.Lock()
            self.server.fleet_json_cache = [None, ""]
        with lock:
            cache = self.server.fleet_json_cache
            if cache[0] is not record:
                cache[0] = record
                cache[1] = json.dumps(record, indent=1, sort_keys=True)
            return cache[1]

    def _history(self, query) -> None:
        historian = getattr(self.server, "historian", None)
        if historian is None:
            self._not_found("no historian on this process "
                            "(BAGUA_OBS_HISTORIAN=on, coordinator only)")
            return
        metric = (query.get("metric") or [None])[0]
        if not metric:
            self._json({"error": "metric= is required",
                        "series": historian.metrics()}, code=400)
            return
        rank = (query.get("rank") or [None])[0]
        window_raw = (query.get("window") or [None])[0]
        try:
            window_s = float(window_raw) if window_raw is not None else None
        except ValueError:
            self._json({"error": f"window={window_raw!r} is not a number"},
                       code=400)
            return
        self._json(historian.history_report(metric, rank=rank,
                                            window_s=window_s))


class ObsHTTPServer:
    """One status server per process.  ``fleet_provider`` (a callable
    returning the latest fleet record, or None) and ``historian`` are the
    coordinator-only hooks; worker processes leave them unset and serve
    the per-process routes only."""

    def __init__(self, port: Optional[int] = None, addr: Optional[str] = None,
                 fleet_provider: Optional[Callable[[], Optional[dict]]] = None,
                 historian=None, cache_fleet_json: bool = True):
        self._requested_port = int(
            _env.get_obs_http_port() if port is None else port
        )
        self.addr = str(_env.get_obs_http_addr() if addr is None else addr)
        self._fleet_provider = fleet_provider
        self._historian = historian
        # cache_fleet_json=False restores per-request /fleet rendering —
        # the scale drill's before/after benchmark knob
        self._cache_fleet_json = bool(cache_fleet_json)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObsHTTPServer":
        # bind fallback chain: the configured (addr, port), then an
        # ephemeral port on the same addr (bind race with another local
        # process), then loopback-ephemeral (a mistyped/unassigned
        # BAGUA_OBS_HTTP_ADDR) — status must degrade to "different
        # port/addr", never to "process died on bring-up"
        for addr, port in ((self.addr, self._requested_port),
                           (self.addr, 0), ("127.0.0.1", 0)):
            try:
                self._httpd = ThreadingHTTPServer((addr, port), _Handler)
                self.addr = addr
                break
            except OSError as e:
                logger.warning(
                    "obs http: cannot bind %s:%d (%s); falling back",
                    addr, port, e,
                )
        else:  # pragma: no cover - loopback-ephemeral essentially binds
            logger.error("obs http: no bindable address; server disabled")
            return self
        self._httpd.daemon_threads = True
        self._httpd.fleet_provider = self._fleet_provider
        self._httpd.historian = self._historian
        self._httpd.cache_fleet_json = self._cache_fleet_json
        self._httpd.fleet_cache_lock = threading.Lock()
        self._httpd.fleet_json_cache = [None, ""]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bagua-obs-http",
            daemon=True,
        )
        self._thread.start()
        counters.set_gauge("obs/http_port", self.port)
        logger.info("obs http: serving on %s:%d", self.addr, self.port)
        return self

    @property
    def port(self) -> int:
        """The port actually bound (differs from the requested one after
        an ephemeral fallback)."""
        if self._httpd is None:
            return self._requested_port
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self.addr}:{self.port}"

    def set_fleet_provider(self, provider) -> None:
        self._fleet_provider = provider
        if self._httpd is not None:
            self._httpd.fleet_provider = provider

    def set_historian(self, historian) -> None:
        self._historian = historian
        if self._httpd is not None:
            self._httpd.historian = historian

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # a stopped server must not shadow the process-wide slot: the
        # next maybe_start_global_http_server() brings up a live one
        # instead of handing back a dead socket
        global _GLOBAL_SERVER
        with _GLOBAL_SERVER_LOCK:
            if _GLOBAL_SERVER is self:
                _GLOBAL_SERVER = None


_GLOBAL_SERVER: Optional[ObsHTTPServer] = None
_GLOBAL_SERVER_LOCK = threading.Lock()


def maybe_start_global_http_server(fleet_provider=None, historian=None
                                   ) -> Optional[ObsHTTPServer]:
    """Process-wide status server, started once when
    ``BAGUA_OBS_HTTP_PORT`` is set (> 0) — the global-exporter pattern.
    Later callers may attach the coordinator hooks (fleet provider /
    historian) to the already-running server."""
    port = _env.get_obs_http_port()
    if port <= 0:
        return None
    global _GLOBAL_SERVER
    with _GLOBAL_SERVER_LOCK:
        if _GLOBAL_SERVER is None:
            try:
                _GLOBAL_SERVER = ObsHTTPServer(
                    port=port, fleet_provider=fleet_provider,
                    historian=historian,
                ).start()
            except Exception as e:  # noqa: BLE001 - a status knob must
                # never kill training bring-up
                logger.warning("obs http: server not started: %s", e)
                return None
        else:
            if fleet_provider is not None:
                _GLOBAL_SERVER.set_fleet_provider(fleet_provider)
            if historian is not None:
                _GLOBAL_SERVER.set_historian(historian)
        return _GLOBAL_SERVER
