"""Goodput ledger: where did the wall-clock go?

The obs plane (spans, flight dumps, anomalies, timelines) answers *what
happened*; this module answers *what it cost*: every second of a training
process's wall-clock lands in exactly one **ledger class** —

* ``productive_step`` — a dispatched train step making forward progress
* ``compile`` — trace/compile of a (re)built step, incl. cost-model queries
* ``state_migration`` — queued layout migrations (autotune rebucket,
  flat-resident relayout) converting live state before a recompiled step
* ``checkpoint`` — save/restore/verify walls
* ``rendezvous`` — elastic rendezvous rounds
* ``catchup_sync`` — async negotiation gathers and forced catch-up averages
* ``rewind`` — steps the grad guard rewound (their wall was spent, their
  update was discarded)
* ``stall`` — injected ``step.straggle`` stalls (drills; a real slow host
  shows up as dilated ``productive_step`` windows the anomaly detector
  flags instead)
* ``prefill`` / ``decode`` — a SERVING replica's forward progress: the
  engine's chunked-prefill and decode-tick walls (docs/serving.md)
* ``batch_formation_idle`` — a serving replica waiting for arrivals with
  an empty batch (the continuous-batching scheduler's named idle)
* ``weight_load`` — integrity-verified serving weight loads
* ``idle_other`` — everything else (data loading, eval, host work between
  steps), computed as the remainder so the classes always sum to the wall

— the goodput/badput lens MegaScale (arXiv 2402.15627) uses to diagnose
10k-accelerator fleets, and the score signal ROADMAP's autotune-v2 wants.
``goodput_fraction = sum(GOODPUT_CLASSES) / wall`` — a training rank's
productive steps plus a serving replica's prefill/decode; every other
class is badput with a name.

Feeding is piggybacked on machinery that already exists: the span tracer
(``ckpt/*``, ``elastic/rendezvous``, ``async/*``, ``step/build`` spans map
to classes via :data:`SPAN_CLASS_MAP` — installed as a lightweight close
hook in :mod:`bagua_tpu.obs.spans`), the trainer's step-cadence windows,
its injected-stall reports, and the grad guard's skip verdicts.  All
host-side: the compiled step program is untouched (the ``BAGUA_OBS`` off
switch and the jaxpr-equality pin keep holding).

MFU accounting rides along: :data:`PEAK_TFLOPS_BF16` (per-chip silicon
peaks, shared with ``bench.py``) turns the cached ``step_cost_analysis()``
flops and the measured step cadence into a per-step ``obs/mfu`` gauge —
null-with-rationale on cpu-sim, like ``trace_overlap``.

CLI::

    python -m bagua_tpu.obs.ledger EXPORT_DIR_OR_METRICS_JSONL... \
        [--flight DUMP_DIR] [--check] [--tolerance 0.01]

renders a per-run, per-rank efficiency report from ``metrics.jsonl``
(+ rotated ``.1`` siblings) and flight dumps; ``--check`` gates
conservation (classes sum to wall within tolerance) for CI.

Import-light (no jax): the CLI and the launcher-side consumers must not
pay a jax import.
"""

from __future__ import annotations

import argparse
import glob
import json
import logging
import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "LEDGER_CLASSES", "GOODPUT_CLASSES", "BADPUT_CLASSES", "SPAN_CLASS_MAP",
    "DRILL_BADPUT_EXPECTATIONS", "GoodputLedger",
    "ledger", "install", "PEAK_TFLOPS_BF16", "PEAK_HBM_GBPS",
    "peak_flops_for_device_kind", "EFFICIENCY_SCHEMA", "validate_efficiency",
    "load_ledger_reports", "main",
]

#: every wall-clock second lands in exactly one of these (defined next to
#: the `obs/ledger/<cls>_s` gauge declarations in obs.export — the single
#: source of truth for the metric names)
from .export import LEDGER_CLASSES  # noqa: E402

#: the classes that ARE forward progress: a training rank's productive
#: steps, a serving replica's prefill/decode walls (docs/serving.md) —
#: ``goodput_fraction`` sums these, so the headline number means the same
#: thing for both kinds of process (a class the process never feeds
#: contributes zero)
GOODPUT_CLASSES = ("productive_step", "prefill", "decode")

#: the classes that are NOT forward progress
BADPUT_CLASSES = tuple(c for c in LEDGER_CLASSES
                       if c not in GOODPUT_CLASSES)

#: span name -> ledger class: the spans that already bracket the
#: non-productive walls.  Outermost-mapped-span-wins (ckpt/verify nests
#: inside ckpt/restore; async/catchup can nest inside a negotiate path) —
#: the per-thread guard in :meth:`GoodputLedger.span_enter` dedupes.
SPAN_CLASS_MAP = {
    "step/build": "compile",
    "step/cost_analysis": "compile",
    "step/state_migration": "state_migration",
    "ckpt/save": "checkpoint",
    "ckpt/restore": "checkpoint",
    "ckpt/verify": "checkpoint",
    "elastic/rendezvous": "rendezvous",
    "async/negotiate": "catchup_sync",
    "async/catchup": "catchup_sync",
    # serving plane (docs/serving.md): the engine's prefill/decode walls
    # are serving goodput; weight loads are badput with a name.
    # batch_formation_idle is fed directly by the engine's run loop (the
    # wait-for-arrivals wall has no span to ride).
    "serve/prefill": "prefill",
    "serve/decode": "decode",
    "serve/weight_load": "weight_load",
}

#: chaos-drill name -> the badput class its defense path must FEED: the
#: single source both scripts/chaos_drill.py (producer: class-delta
#: verdicts in CHAOS_DRILL.json) and tests/test_bench_sanity.py (gate)
#: iterate, so adding a ledger-checked drill can't silently drop out of
#: the artifact gate
DRILL_BADPUT_EXPECTATIONS = {
    "nan_grad_skip_loss_continuity": "rewind",
    "async_partition_staleness_catchup": "catchup_sync",
    "checkpoint_corruption_fallback_restore": "checkpoint",
    # the autopilot's quarantine drill walks a real fallback restore (3
    # torn steps) before the engine acts — that walk is checkpoint badput
    "autopilot_ckpt_quarantine": "checkpoint",
}

# Peak per-chip silicon specs for MFU / roofline reporting, keyed by
# ``jax.devices()[0].device_kind`` (moved here from bench.py so the
# trainer's per-step gauge and the bench share one table).
PEAK_TFLOPS_BF16 = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,       # v5e
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,       # Trillium
    "TPU v6e": 918.0,
}
PEAK_HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


def peak_flops_for_device_kind(kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for a device kind (None when unknown — cpu-sim,
    new silicon): the MFU denominator, ``None`` meaning the ``obs/mfu``
    gauge stays null-with-rationale."""
    peak_tflops = PEAK_TFLOPS_BF16.get(kind)
    return peak_tflops * 1e12 if peak_tflops else None


class GoodputLedger:
    """Per-process wall-clock attribution state machine.

    Thread-safe; one per process (:data:`ledger`), like the telemetry
    counters.  The wall anchors at the FIRST noted window (start of that
    window, so the window itself is inside the wall); ``idle_other`` is the
    remainder at report time, which makes conservation hold by
    construction — the test gate then only has to prove the explicit
    classes never EXCEED the wall.
    """

    #: bounded history of (t_mono, cumulative class seconds) samples for
    #: the timeline's counter track — one sample per step window
    SAMPLE_CAP = 512
    #: recent per-step productive windows kept for rewind reclassification
    #: (the grad-guard verdict runs one step behind; 64 >> the verdict lag)
    RECENT_CAP = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t_start: Optional[float] = None
        self._totals: Dict[str, float] = {
            c: 0.0 for c in LEDGER_CLASSES if c != "idle_other"
        }
        #: class seconds noted since the last step window closed — the
        #: part of the next raw window that is NOT productive-step time
        self._deductions = 0.0
        self._recent: "OrderedDict[int, float]" = OrderedDict()
        self._rewind_windows = 0
        self._step_windows = 0
        self._samples: deque = deque(maxlen=self.SAMPLE_CAP)

    # -- feeding ----------------------------------------------------------

    def _anchor(self, now: float, seconds: float) -> None:
        if self._t_start is None:
            # anchor the wall at the START of the first noted window, so
            # that window's seconds are inside it
            self._t_start = now - max(0.0, seconds)

    def note_class_window(self, cls: str, seconds: float) -> None:
        """Attribute ``seconds`` of host wall to a non-step class.  Windows
        noted between two step-cadence marks are deducted from the next
        step window (they happened inside it)."""
        if seconds <= 0 or cls not in self._totals:
            return
        now = time.monotonic()
        with self._lock:
            self._anchor(now, seconds)
            self._totals[cls] += seconds
            self._deductions += seconds

    def note_step_window(self, step: int, raw_seconds: float,
                         cls: str = "productive_step") -> None:
        """Close one step's wall window (the trainer's cadence hook): the
        window minus the class windows noted inside it is productive-step
        time.  A window that contained a trace+compile or a state
        migration (the trainer's ``_skip_next_speed_sample`` mirror)
        passes ``cls="compile"``/``"state_migration"`` instead — its
        remainder is attributed there, not dropped and not mistaken for a
        step's worth of progress."""
        if raw_seconds <= 0 or cls not in self._totals:
            return
        now = time.monotonic()
        with self._lock:
            self._anchor(now, raw_seconds)
            remainder = max(0.0, raw_seconds - min(self._deductions,
                                                   raw_seconds))
            self._deductions = 0.0
            self._totals[cls] += remainder
            self._step_windows += 1
            if cls == "productive_step":
                # only productive windows are rewind-reclassifiable
                self._recent[int(step)] = remainder
                while len(self._recent) > self.RECENT_CAP:
                    self._recent.popitem(last=False)
            self._samples.append(
                (now, {c: round(v, 6) for c, v in self._totals.items()})
            )

    def reclassify_step_rewind(self, step: int) -> None:
        """The grad guard rewound ``step``: its wall was spent but its
        update discarded — move the recorded productive seconds to
        ``rewind``.  A window not recorded as productive (the final step
        of a run drained by ``flush_grad_health``, or a poison firing on a
        compile-classified window) moves the most recent window's size
        instead — always MOVED out of ``productive_step``, never invented,
        so conservation can't break (at worst the estimate is capped by
        the productive seconds actually on the books)."""
        with self._lock:
            seconds = self._recent.pop(int(step), None)
            if seconds is None:
                estimate = (next(reversed(self._recent.values()))
                            if self._recent else 0.0)
                seconds = min(estimate, self._totals["productive_step"])
            self._totals["productive_step"] = max(
                0.0, self._totals["productive_step"] - seconds
            )
            self._totals["rewind"] += seconds
            self._rewind_windows += 1

    # -- span hook (installed into bagua_tpu.obs.spans) --------------------

    def span_enter(self, name: str) -> Optional[str]:
        """Span-open hook: returns the ledger class this span OWNS, or
        None.  Only the outermost mapped span on a thread owns its window
        (``ckpt/verify`` inside ``ckpt/restore`` must not double-count)."""
        cls = SPAN_CLASS_MAP.get(name)
        if cls is None:
            return None
        if getattr(self._local, "owned", False):
            return None
        self._local.owned = True
        return cls

    def span_exit(self, cls: str, seconds: float) -> None:
        """Span-close hook for a span :meth:`span_enter` gave ownership."""
        self._local.owned = False
        self.note_class_window(cls, seconds)

    # -- reading ----------------------------------------------------------

    def report(self, now: Optional[float] = None) -> Optional[dict]:
        """The ledger's current verdict: per-class cumulative seconds
        (``idle_other`` = wall remainder), ``wall_s``, ``goodput_fraction``,
        the badput breakdown and its worst class.  None before any window
        was noted (launcher processes, pure-eval jobs)."""
        with self._lock:
            if self._t_start is None:
                return None
            now = time.monotonic() if now is None else now
            wall = max(1e-9, now - self._t_start)
            classes = {c: round(v, 6) for c, v in self._totals.items()}
            explicit = sum(self._totals.values())
            classes["idle_other"] = round(max(0.0, wall - explicit), 6)
            badput = {c: classes[c] for c in BADPUT_CLASSES if classes[c] > 0}
            worst = max(badput, key=badput.get) if badput else None
            goodput = sum(classes[c] for c in GOODPUT_CLASSES)
            return {
                "wall_s": round(wall, 6),
                "classes": classes,
                "goodput_fraction": round(goodput / wall, 6),
                "badput_s": round(sum(badput.values()), 6),
                "worst_badput_class": worst,
                "step_windows": self._step_windows,
                "rewind_windows": self._rewind_windows,
            }

    def samples(self) -> List[dict]:
        """Bounded (t_mono, cumulative class seconds) history — the
        timeline's per-rank counter track."""
        with self._lock:
            return [{"t": t, "classes": dict(c)} for t, c in self._samples]

    def publish_gauges(self, counters) -> None:
        """Export the cumulative classes + goodput fraction as registered
        gauges (one snapshot; the metrics exporter calls this before every
        export)."""
        rep = self.report()
        if rep is None:
            return
        for cls, seconds in rep["classes"].items():
            counters.set_gauge(f"obs/ledger/{cls}_s", round(seconds, 6))
        counters.set_gauge("obs/ledger/wall_s", rep["wall_s"])
        counters.set_gauge("obs/goodput_fraction", rep["goodput_fraction"])

    def reset(self) -> None:
        """Forget everything (tests, the efficiency bench's measured
        window)."""
        with self._lock:
            self._t_start = None
            for c in self._totals:
                self._totals[c] = 0.0
            self._deductions = 0.0
            self._recent.clear()
            self._rewind_windows = 0
            self._step_windows = 0
            self._samples.clear()


#: process-wide ledger (one per process, like ``telemetry.counters``)
ledger = GoodputLedger()

_INSTALLED = False
_INSTALL_LOCK = threading.Lock()


def install() -> GoodputLedger:
    """Idempotently hook :data:`ledger` into the span tracer so mapped
    spans (checkpoint, rendezvous, async boundaries, step builds) feed
    their classes automatically.  Called by the trainer when the obs plane
    is on; safe from any thread."""
    global _INSTALLED
    with _INSTALL_LOCK:
        if not _INSTALLED:
            from . import spans as _spans

            _spans.set_ledger_sink(ledger)
            _INSTALLED = True
    return ledger


# ---- EFFICIENCY.json schema (benchmarks/efficiency_bench.py writes it) ----

EFFICIENCY_SCHEMA = "bagua-efficiency-v1"


def validate_efficiency(record: dict) -> List[str]:
    """Schema problems with an EFFICIENCY.json record ([] = valid) — the
    ``test_bench_sanity`` gate and the regress sentinel's admission check."""
    problems: List[str] = []
    if not isinstance(record, dict):
        return ["not a JSON object"]
    if record.get("schema") != EFFICIENCY_SCHEMA:
        problems.append(f"schema != {EFFICIENCY_SCHEMA}")
    for key, typ in (("time_unix", (int, float)), ("platform", str),
                     ("n_devices", int), ("config", dict),
                     ("ledger", dict), ("footprint", dict),
                     ("mfu", dict), ("trend_records", list)):
        if not isinstance(record.get(key), typ):
            problems.append(f"missing/mistyped {key}")
    led = record.get("ledger") or {}
    classes = led.get("classes")
    if not isinstance(classes, dict):
        problems.append("ledger.classes missing")
    else:
        for cls in LEDGER_CLASSES:
            if cls not in classes:
                problems.append(f"ledger.classes missing {cls}")
        wall = led.get("wall_s")
        if not isinstance(wall, (int, float)) or wall <= 0:
            problems.append("ledger.wall_s missing/nonpositive")
        elif sum(classes.values()) > wall * 1.01 + 1e-6:
            problems.append("ledger classes sum exceeds wall_s (+1%)")
    if not isinstance(led.get("goodput_fraction"), (int, float)):
        problems.append("ledger.goodput_fraction missing")
    fp = record.get("footprint") or {}
    for key in ("params_bytes", "opt_state_bytes", "algo_state_bytes",
                "grad_flats_bytes", "total_bytes"):
        if not isinstance(fp.get(key), int):
            problems.append(f"footprint.{key} missing/mistyped")
    if isinstance(fp.get("total_bytes"), int) and all(
        isinstance(fp.get(k), int)
        for k in ("params_bytes", "opt_state_bytes", "algo_state_bytes",
                  "grad_flats_bytes")
    ):
        if fp["total_bytes"] != (fp["params_bytes"] + fp["opt_state_bytes"]
                                 + fp["algo_state_bytes"]
                                 + fp["grad_flats_bytes"]):
            problems.append("footprint.total_bytes != sum of components")
    mfu = record.get("mfu") or {}
    if "available" not in mfu:
        problems.append("mfu.available missing")
    elif not mfu.get("available") and not mfu.get("rationale"):
        problems.append("mfu unavailable without rationale")
    for rec in record.get("trend_records") or []:
        if not isinstance(rec, dict) or "metric" not in rec \
                or "value" not in rec:
            problems.append("trend_records entry missing metric/value")
            break
    return problems


# ---- CLI: per-run report from metrics.jsonl + flight dumps ----------------


def _metrics_files(paths: Sequence[str]) -> List[str]:
    """Expand export dirs / file paths into metrics.jsonl files, rotated
    ``.1`` siblings first so cumulative gauges read oldest-to-newest."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in ("metrics.jsonl.1", "metrics.jsonl"):
                f = os.path.join(p, name)
                if os.path.exists(f):
                    files.append(f)
        else:
            rotated = p + ".1"
            if os.path.exists(rotated):
                files.append(rotated)
            files.append(p)
    return files


def load_ledger_reports(paths: Sequence[str]) -> Dict[int, dict]:
    """Last-seen per-rank ledger state from metrics.jsonl snapshots: the
    ``obs/ledger/*`` + ``obs/goodput_fraction`` gauges of each rank's
    newest record (gauges are cumulative, so the last line wins), plus the
    record's obs summary if present."""
    out: Dict[int, dict] = {}
    for path in _metrics_files(paths):
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            logger.warning("ledger: skipping unreadable %s (%s)", path, e)
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line of a live exporter
            counters = rec.get("counters") or {}
            classes = {
                c: counters[f"obs/ledger/{c}_s"]
                for c in LEDGER_CLASSES
                if f"obs/ledger/{c}_s" in counters
            }
            if not classes:
                continue
            rank = int(rec.get("rank", 0))
            out[rank] = {
                "rank": rank,
                "time_unix": rec.get("time_unix"),
                "classes": classes,
                "wall_s": counters.get("obs/ledger/wall_s"),
                "goodput_fraction": counters.get("obs/goodput_fraction"),
                "mfu": counters.get("obs/mfu"),
                "obs": rec.get("obs") or {},
            }
    return out


def _load_flight_context(dump_dir: str) -> List[dict]:
    out = []
    for path in sorted(glob.glob(os.path.join(dump_dir, "flight_*.json"))):
        try:
            rec = json.load(open(path))
        except (OSError, ValueError):
            continue
        out.append({
            "source": os.path.basename(path),
            "trigger": rec.get("trigger"),
            "fault_point": rec.get("fault_point"),
            "rank": rec.get("rank"),
            "ledger": rec.get("ledger"),
        })
    return out


def check_conservation(report: dict, tolerance: float = 0.01
                       ) -> List[str]:
    """Conservation problems with one rank's loaded ledger state ([] =
    holds): the explicit classes must not exceed the wall by more than
    ``tolerance`` (idle_other is a remainder, so the sum can only come up
    short when gauges and wall were snapshot at slightly different
    instants — allowed), and the goodput fraction must be a fraction."""
    problems: List[str] = []
    wall = report.get("wall_s")
    classes = report.get("classes") or {}
    if not isinstance(wall, (int, float)) or wall <= 0:
        return ["no obs/ledger/wall_s gauge in the newest snapshot"]
    missing = [c for c in LEDGER_CLASSES if c not in classes]
    if missing:
        problems.append(f"missing class gauges: {missing}")
    total = sum(v for v in classes.values() if isinstance(v, (int, float)))
    if total > wall * (1.0 + tolerance) + 1e-6:
        problems.append(
            f"classes sum {total:.3f}s exceeds wall {wall:.3f}s "
            f"(+{tolerance:.0%} tolerance)"
        )
    gf = report.get("goodput_fraction")
    if not isinstance(gf, (int, float)) or not (0.0 <= gf <= 1.0):
        problems.append(f"goodput_fraction {gf!r} not in [0, 1]")
    return problems


def render_report(reports: Dict[int, dict],
                  flights: Sequence[dict]) -> str:
    lines: List[str] = []
    for rank in sorted(reports):
        rep = reports[rank]
        wall = rep.get("wall_s") or 0.0
        lines.append(f"rank {rank}: wall {wall:.2f}s, goodput "
                     f"{(rep.get('goodput_fraction') or 0.0):.1%}"
                     + (f", mfu {rep['mfu']:.3f}"
                        if isinstance(rep.get("mfu"), (int, float)) else ""))
        classes = rep.get("classes") or {}
        for cls in LEDGER_CLASSES:
            v = classes.get(cls)
            if v is None:
                continue
            pct = (v / wall * 100.0) if wall else 0.0
            bar = "#" * int(round(pct / 2))
            lines.append(f"  {cls:>16} {v:>10.3f}s {pct:5.1f}% {bar}")
        badput = {c: classes.get(c, 0.0) for c in BADPUT_CLASSES
                  if classes.get(c, 0.0) > 0}
        if badput:
            worst = max(badput, key=badput.get)
            lines.append(f"  worst badput class: {worst} "
                         f"({badput[worst]:.3f}s)")
    if flights:
        lines.append("flight dumps:")
        for fl in flights:
            tag = fl["trigger"] or "?"
            if fl.get("fault_point"):
                tag += f" ({fl['fault_point']})"
            lines.append(f"  rank {fl.get('rank')}: {tag} — {fl['source']}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bagua_tpu.obs.ledger",
        description="Render a per-run goodput/badput report from a "
                    "metrics-exporter directory (metrics.jsonl + rotated "
                    "siblings) and optional flight dumps.",
    )
    ap.add_argument("inputs", nargs="+",
                    help="export directories and/or metrics.jsonl files")
    ap.add_argument("--flight", default=None,
                    help="flight-dump directory for post-mortem context")
    ap.add_argument("--check", action="store_true",
                    help="gate conservation (classes sum to wall within "
                         "--tolerance); non-zero exit on problems")
    ap.add_argument("--tolerance", type=float, default=0.01,
                    help="conservation tolerance as a fraction of wall "
                         "(default 0.01)")
    args = ap.parse_args(argv)

    reports = load_ledger_reports(args.inputs)
    if not reports:
        print(f"no ledger gauges found under {args.inputs} — was the run "
              "exported with BAGUA_OBS_EXPORT_DIR set and the obs plane "
              "on?", file=sys.stderr)
        return 2
    flights = _load_flight_context(args.flight) if args.flight else []
    print(render_report(reports, flights))
    if args.check:
        problems = []
        for rank, rep in sorted(reports.items()):
            problems += [f"rank {rank}: {p}"
                         for p in check_conservation(rep, args.tolerance)]
        if problems:
            print("conservation problems: " + "; ".join(problems),
                  file=sys.stderr)
            return 1
        print(f"conservation holds for {len(reports)} rank(s) "
              f"(±{args.tolerance:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
