"""Hang watchdog: convert silent stalls into crashes a launcher can restart.

Counterpart of the reference's comm monitor thread, which panics the process
when a scheduled comm op exceeds 300 s
(/root/reference/rust/bagua-core/bagua-core-internal/src/lib.rs:255-265), and
of its panic-escalation hook (bagua-core-py/src/lib.rs:518-523) — under XLA
the analogous failure is a collective deadlock across ranks (e.g. one rank
compiled a different program) that blocks forever.  A hung worker holds the
whole gang; killing it lets ``bagua_tpu.distributed.run``'s gang restart
recover from the checkpoint.

ON BY DEFAULT at the reference's 300 s (``BAGUA_COMM_TIMEOUT_S``; set 0/off
to disable).  Always-on is affordable because watching is asynchronous: the
trainer hands each step's loss array to a background *waiter* thread that
performs the reliable host readback inside a watched section — the main
thread keeps dispatching at full speed, and a wedged collective surfaces as
the waiter stuck past the timeout.  (``jax.Array.is_ready`` polling would be
cheaper still, but ``block_until_ready``-family signals have been observed
returning early on tunneled transports; an actual readback is the fence that
cannot lie.)

On firing, the watchdog raises the cooperative abort flag
(:func:`bagua_tpu.communication.abort`) so control loops stop, then dumps
all thread stacks and terminates (``action="exit"``).  ``action="abort"``
stops at the flag (in-process recovery; tests), ``action="log"`` only
records.
"""

from __future__ import annotations

import atexit
import faulthandler
import logging
import os
import queue
import sys
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from .faults import inject as _inject

logger = logging.getLogger(__name__)

DEFAULT_TIMEOUT_S = 300.0  # the reference's comm monitor bound (lib.rs:255)


def get_comm_timeout_s() -> Optional[float]:
    """Watchdog timeout in seconds, or None when disabled.  The off-value
    semantics (``0``/``off``/``false``/``no``/``none``/empty) live in the
    env registry's :func:`bagua_tpu.env.env_seconds_or_off` accessor, so
    ``bagua-lint``'s registry coverage stays total."""
    from . import env

    return env.get_comm_timeout_s()


class HangWatchdog:
    """Monitors watched sections; if one runs past ``timeout_s``, raises the
    global comm abort flag, then terminates the process (``action="exit"``),
    stops at the flag (``action="abort"``), or just records
    (``action="log"``, for tests).

    Two watching styles:

    * :meth:`watch` — context manager around blocking host work.
    * :meth:`watch_result` — non-blocking: enqueue an async step result; the
      internal waiter thread reads it back inside a watched section.
    """

    _CHECK_INTERVAL_S = 1.0
    _QUEUE_MAX = 64  # backlog cap; a hang pins the waiter on ONE item anyway

    def __init__(self, timeout_s: float = DEFAULT_TIMEOUT_S,
                 action: str = "exit"):
        assert action in ("exit", "abort", "log")
        self.timeout_s = timeout_s
        self.action = action
        self.fired = threading.Event()  # informational latch (never cleared)
        self._armed = True  # re-arms when all overdue sections clear
        self._active: Dict[object, tuple] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._queue: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_MAX)
        self._waiter: Optional[threading.Thread] = None
        self._readback_warned = False
        self._thread = threading.Thread(
            target=self._monitor, name="bagua-watchdog", daemon=True
        )
        self._thread.start()

    @contextmanager
    def watch(self, label: str = "comm"):
        # token is a fresh object per entry, NOT the thread id: keying by
        # get_ident() made an inner (nested) watch clobber the outer entry
        # and its exit pop the shared key — leaving the outer section
        # unwatched for the rest of its run
        from .obs.spans import trace_span

        token = object()
        with self._lock:
            self._active[token] = (label, time.monotonic())
        try:
            # the watched section doubles as a span: a post-mortem's span
            # tail shows exactly which section the waiter was pinned in
            with trace_span(f"watchdog/{label}"):
                yield
        finally:
            with self._lock:
                self._active.pop(token, None)

    def watch_result(self, array, label: str = "step") -> None:
        """Watch an async result without blocking the caller.  When the
        backlog is full the item is dropped — safe, because a wedged
        collective pins the waiter on whichever item it is currently
        reading back, and every later step queues behind the same hang."""
        if self._waiter is None:
            with self._lock:
                if self._waiter is None:
                    self._waiter = threading.Thread(
                        target=self._wait_loop, name="bagua-watchdog-waiter",
                        daemon=True,
                    )
                    self._waiter.start()
        try:
            self._queue.put_nowait((label, array))
        except queue.Full:
            pass

    def _wait_loop(self):
        import numpy as np

        while not self._stop.is_set():
            try:
                label, array = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            with self.watch(label):
                # chaos hook: an armed ``collective.hang`` fault wedges
                # this readback inside the watched section — exactly the
                # signature of a cross-rank collective deadlock (bounded
                # by the spec's duration; the stop event cuts it short)
                _inject.maybe_hang(stop_event=self._stop)
                try:
                    # host readback: the reliable fence.  Multi-process
                    # global arrays can't be fetched whole — their LOCAL
                    # shard is the per-process fence instead.
                    if (
                        hasattr(array, "is_fully_addressable")
                        and not array.is_fully_addressable
                    ):
                        np.asarray(array.addressable_shards[0].data)
                    else:
                        np.asarray(array)
                except Exception as e:
                    # runtime errors surface on the main thread's own use
                    # of the result; the watchdog only cares about hangs.
                    # BUT an instantly-failing readback (donated/deleted
                    # buffer, non-replicated global array) silently disarms
                    # hang detection — make the degradation visible once.
                    if not self._readback_warned:
                        self._readback_warned = True
                        logger.warning(
                            "watchdog: readback of %r failed (%s: %s) — "
                            "sections from watch_result() no longer fence "
                            "device work; hang detection may be degraded",
                            label, type(e).__name__, e,
                        )

    def _monitor(self):
        while not self._stop.wait(self._CHECK_INTERVAL_S):
            now = time.monotonic()
            with self._lock:
                overdue = [
                    (label, now - t0)
                    for label, t0 in self._active.values()
                    if now - t0 > self.timeout_s
                ]
            if overdue:
                label, dt = overdue[0]
                logger.error(
                    "watchdog: section %r stuck for %.0f s (timeout %.0f s) — "
                    "dumping stacks", label, dt, self.timeout_s,
                )
                self.fired.set()
                if self._armed:
                    # cooperative abort first: control loops (async model
                    # average) stop launching work even in abort mode
                    if self.action != "log":
                        from .communication import abort

                        abort(f"watchdog: {label} stuck for {dt:.0f} s")
                        # flight recorder: the post-mortem artifact for
                        # this hang episode — host-only reads (span ring,
                        # counters), so a wedged device cannot block it
                        from .obs.recorder import dump_flight_record

                        dump_flight_record(
                            "watchdog_abort",
                            reason=f"section {label!r} stuck for {dt:.0f} s "
                                   f"(timeout {self.timeout_s:.0f} s)",
                        )
                    # dump stacks once per hang episode, not every tick
                    faulthandler.dump_traceback(file=sys.stderr)
                    self._armed = False
                if self.action == "exit":
                    # elastic jobs: tell the membership registry this is a
                    # DELIBERATE departure, so the coordinator logs a leave
                    # (watchdog kill) rather than a silent hang/crash.
                    # No-op outside elastic mode; bounded; never raises.
                    try:
                        from .elastic.membership import publish_leave_intent

                        publish_leave_intent(
                            f"watchdog: {label} stuck for {dt:.0f} s"
                        )
                    except Exception:
                        pass
                    # flush queued async checkpoint saves first — os._exit
                    # skips atexit handlers, and the whole point of dying is
                    # to restart from the freshest durable checkpoint.
                    # Bounded: a wedged flush cannot block the exit.
                    try:
                        from .checkpoint import flush_all_checkpoints

                        flush_all_checkpoints(timeout_s=10.0)
                    except Exception:
                        pass
                    # the gang-restart contract: die loudly, let the
                    # launcher respawn from the checkpoint
                    os._exit(3)
                # abort/log modes: keep monitoring (later hangs surface too)
            elif not self._armed:
                # hang episode over (sections cleared, e.g. after
                # reset_abort recovery): re-arm so the NEXT hang re-raises
                # the abort flag and dumps stacks again
                self._armed = True

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        if self._waiter is not None:
            self._waiter.join(timeout=5)


_GLOBAL: Optional[HangWatchdog] = None
_GLOBAL_LOCK = threading.Lock()


def get_global_watchdog(timeout_s: float) -> HangWatchdog:
    """Process-wide watchdog (one monitor thread no matter how many trainers
    exist — the reference also runs ONE comm monitor per backend process,
    lib.rs:255-265).  When later callers ask for a different timeout the
    STRICTER (smaller) one is adopted — silently keeping the first caller's
    looser bound would leave the later trainer under-protected — and the
    difference is logged either way."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = HangWatchdog(timeout_s)
            # stop the waiter BEFORE interpreter teardown: a daemon thread
            # killed mid-readback inside PJRT aborts the whole process at
            # exit (SIGABRT after a perfectly good run)
            atexit.register(_GLOBAL.stop)
        elif float(timeout_s) != _GLOBAL.timeout_s:
            adopted = min(float(timeout_s), _GLOBAL.timeout_s)
            logger.warning(
                "get_global_watchdog: requested timeout %.0f s differs from "
                "the active %.0f s (one watchdog per process); adopting the "
                "stricter %.0f s",
                timeout_s, _GLOBAL.timeout_s, adopted,
            )
            _GLOBAL.timeout_s = adopted
        return _GLOBAL
