"""Hang watchdog: convert silent stalls into crashes a launcher can restart.

Counterpart of the reference's comm monitor thread, which panics the process
when a scheduled comm op exceeds 300 s
(/root/reference/rust/bagua-core/bagua-core-internal/src/lib.rs:255-265), and
of its panic-escalation hook (bagua-core-py/src/lib.rs:518-523) — under XLA
the analogous failure is a collective deadlock across ranks (e.g. one rank
compiled a different program) that blocks ``block_until_ready`` forever.  A
hung worker holds the whole gang; killing it lets
``bagua_tpu.distributed.run``'s gang restart recover from the checkpoint.

Enabled via ``BAGUA_COMM_TIMEOUT_S`` (default off).  When on, the trainer
synchronizes each step inside a watched section — trading step-level async
dispatch for hang detection, the same serialization the reference's comm
monitor implies.
"""

from __future__ import annotations

import faulthandler
import logging
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

logger = logging.getLogger(__name__)


def get_comm_timeout_s() -> Optional[float]:
    v = os.environ.get("BAGUA_COMM_TIMEOUT_S")
    return float(v) if v else None


class HangWatchdog:
    """Monitors watched sections; if one runs past ``timeout_s``, dumps all
    thread stacks and terminates the process (``action="exit"``) or raises in
    the monitor (``action="log"``, for tests)."""

    _CHECK_INTERVAL_S = 1.0

    def __init__(self, timeout_s: float = 300.0, action: str = "exit"):
        assert action in ("exit", "log")
        self.timeout_s = timeout_s
        self.action = action
        self.fired = threading.Event()
        self._active: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._monitor, name="bagua-watchdog", daemon=True
        )
        self._thread.start()

    @contextmanager
    def watch(self, label: str = "comm"):
        token = threading.get_ident()
        with self._lock:
            self._active[token] = (label, time.monotonic())
        try:
            yield
        finally:
            with self._lock:
                self._active.pop(token, None)

    def _monitor(self):
        while not self._stop.wait(self._CHECK_INTERVAL_S):
            now = time.monotonic()
            with self._lock:
                overdue = [
                    (label, now - t0)
                    for label, t0 in self._active.values()
                    if now - t0 > self.timeout_s
                ]
            if overdue:
                label, dt = overdue[0]
                logger.error(
                    "watchdog: section %r stuck for %.0f s (timeout %.0f s) — "
                    "dumping stacks", label, dt, self.timeout_s,
                )
                already_fired = self.fired.is_set()
                self.fired.set()
                if not already_fired:  # dump stacks once, not every tick
                    faulthandler.dump_traceback(file=sys.stderr)
                if self.action == "exit":
                    # the gang-restart contract: die loudly, let the
                    # launcher respawn from the checkpoint
                    os._exit(3)
                # log mode: keep monitoring (later hangs must also surface)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


_GLOBAL: Optional[HangWatchdog] = None
_GLOBAL_LOCK = threading.Lock()


def get_global_watchdog(timeout_s: float) -> HangWatchdog:
    """Process-wide watchdog (one monitor thread no matter how many trainers
    exist — the reference also runs ONE comm monitor per backend process,
    lib.rs:255-265).  The first caller's timeout wins."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = HangWatchdog(timeout_s)
        return _GLOBAL
