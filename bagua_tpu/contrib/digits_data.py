"""Real handwritten-digit data for the convergence gates.

The reference's CI trains on real MNIST/ImageNet/SQuAD and gates on exact
losses (/root/reference/.buildkite/scripts/benchmark_master.sh:83-153,
/root/reference/examples/mnist/main.py:1).  This image has no network
egress, so MNIST's IDX files can't be fetched; the stand-in is the UCI
handwritten-digits set (1,797 real 8x8 scans of hand-written digits — the
dataset scikit-learn packages as ``load_digits``), VENDORED here as
``data/digits_8x8.npz`` (~45 KB) so loading it never imports sklearn:
sklearn's OpenMP runtime aborts XLA:CPU's thread pools when both live in
one pytest process.  ``examples/mnist_mlp.py --data digits`` and
``tests/test_real_data_convergence.py`` consume it; real MNIST IDX files
still work via ``examples/moe_mnist.py --mnist-dir``.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

_VENDORED = os.path.join(os.path.dirname(__file__), "data", "digits_8x8.npz")


def _raw_digits() -> Tuple[np.ndarray, np.ndarray]:
    if os.path.exists(_VENDORED):
        with np.load(_VENDORED) as z:
            return z["images"], z["labels"]
    # fallback for source trees without the vendored file
    from sklearn.datasets import load_digits  # noqa: PLC0415

    d = load_digits()
    return d.data, d.target


def load_digits_dataset(
    test_frac: float = 0.15,
    seed: int = 0,
    train_multiple_of: int = 8,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic train/test split of the real digits data.

    Returns ``(x_train, y_train, x_test, y_test)``; images are flat f32
    in [0, 1] (64 features), labels int32 in [0, 10).  The train split is
    truncated to a multiple of ``train_multiple_of`` so it shards evenly
    over the test mesh.
    """
    images, labels = _raw_digits()
    x = (np.asarray(images, np.float32) / 16.0)  # pixel range is 0..16
    y = np.asarray(labels, np.int32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    n_test = int(len(x) * test_frac)
    x_test, y_test = x[:n_test], y[:n_test]
    x_train, y_train = x[n_test:], y[n_test:]
    n_train = len(x_train) - len(x_train) % train_multiple_of
    return x_train[:n_train], y_train[:n_train], x_test, y_test
