"""Dataset wrapper that caches samples through :class:`CacheLoader`.

Counterpart of /root/reference/bagua/torch_api/contrib/cached_dataset.py.
Duck-typed: wraps anything indexable with ``__len__`` (a torch ``Dataset``,
a list, an HF dataset...) — useful when producing a sample involves slow IO
or preprocessing on the TPU host.
"""

from __future__ import annotations

from .cache_loader import CacheLoader

__all__ = ["CachedDataset"]


class CachedDataset:
    """Caches ``dataset[i]`` under key ``"{dataset_name}_{i}"`` on first access.

    >>> ds = CachedDataset(dataset, backend="memory", dataset_name="train")
    >>> sample = ds[3]          # slow the first time, cached after
    """

    def __init__(
        self,
        dataset,
        backend: str = "memory",
        dataset_name: str = "",
        writer_buffer_size: int = 20,
        **kwargs,
    ):
        self.dataset = dataset
        self.cache_loader = CacheLoader(
            backend, dataset_name, writer_buffer_size, **kwargs
        )

    def __getitem__(self, item):
        return self.cache_loader.get(item, lambda i: self.dataset[i])

    def __len__(self):
        return len(self.dataset)
