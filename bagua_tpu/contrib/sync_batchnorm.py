"""Cross-device synchronous batch normalization.

Counterpart of /root/reference/bagua/torch_api/contrib/sync_batchnorm.py:31+
(a custom autograd Function allreducing batch moments across workers).  The
TPU-native form needs no custom gradient: moments are averaged with
``lax.pmean`` over the data-parallel mesh axes *inside* the jitted SPMD step,
and XLA differentiates through the collective (the pmean backward is itself a
pmean — exactly the reference's hand-written backward allreduce).

Plugs into :class:`bagua_tpu.models.resnet.ResNet` via ``norm_cls``::

    from functools import partial
    model = ResNet50(norm_cls=partial(SyncBatchNorm, axis_name=("dp",)))

When ``axis_name`` is None (or the axis is not bound, e.g. called outside
``shard_map``), behaves exactly like local ``nn.BatchNorm`` — the world-size-1
fallback of the reference (:83-85).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["SyncBatchNorm"]

Axes = Union[str, Tuple[str, ...]]


def _bound_axes(axis_name: Optional[Axes]) -> Tuple[str, ...]:
    """Filter ``axis_name`` down to axes bound in the current trace, so the
    module also works un-sharded (single-device eval, plain ``jit``)."""
    if axis_name is None:
        return ()
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    bound = []
    for a in axes:
        try:  # psum of a constant: trace-time probe, no runtime cost
            jax.lax.psum(jnp.zeros(()), a)
        except NameError:
            continue
        bound.append(a)
    return tuple(bound)


class SyncBatchNorm(nn.Module):
    """BatchNorm whose batch statistics are averaged over mesh axes.

    Field-compatible with ``flax.linen.BatchNorm`` (momentum / epsilon /
    use_running_average / dtype / scale_init / bias_init), plus ``axis_name``:
    the mesh axis (or axes) carrying data parallelism.
    """

    use_running_average: Optional[bool] = None
    axis_name: Optional[Axes] = None
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Callable = nn.initializers.zeros
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))

        ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (features,)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (features,)
        )

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            sync = _bound_axes(self.axis_name)
            if sync:
                # equal per-shard batch sizes => pmean of the per-shard
                # moments is the exact global moment (the reference
                # allgathers mean/var/count and recombines; counts are
                # uniform under SPMD so the mean suffices)
                mean = jax.lax.pmean(mean, sync)
                mean_sq = jax.lax.pmean(mean_sq, sync)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            y = y * self.param(
                "scale", self.scale_init, (features,), self.param_dtype
            )
        if self.use_bias:
            y = y + self.param(
                "bias", self.bias_init, (features,), self.param_dtype
            )
        return y.astype(self.dtype or x.dtype)
