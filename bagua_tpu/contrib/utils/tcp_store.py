"""Spawnable cross-process KV store over TCP — Python client + two servers.

Role counterpart of the reference's ``RedisStore``
(/root/reference/bagua/torch_api/contrib/utils/redis_store.py:38+), which
spawns ``redis-server`` (a native C server) per node and shards a cluster
view over them.  Here the native server is our own:
``csrc/bagua_store_server.cpp`` (thread-per-connection C++; built on demand
with g++ — see :mod:`.native_build`), with a stdlib-Python threaded server as
the always-available fallback.  Both speak the same language-neutral binary
protocol, so the client doesn't care which it reached.

Wire protocol (little-endian):
    request:  u8 op | op-specific payload;  bytes fields are u32 len + raw
    ops:      1=SET k v   2=GET k     3=MSET n (k v)*   4=MGET n k*
              5=NUM_KEYS  6=CLEAR     7=PING            8=SHUTDOWN
    response: GET   -> u8 present + [val]
              MGET  -> u32 n + n * (u8 present + [val])
              NUM_KEYS -> u64
              others  -> u8 0 (ack)
Values are opaque bytes (the cache layer pickles sample payloads itself,
reference cache_loader.py serialize/deserialize).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import subprocess
import threading
from typing import Dict, List, Optional, Tuple, Union

from .store import ClusterStore, Store

__all__ = ["TCPStoreServer", "TCPStore", "TCPClusterStore", "start_tcp_store"]

Value = Union[str, bytes]

OP_SET, OP_GET, OP_MSET, OP_MGET, OP_NUM_KEYS, OP_CLEAR, OP_PING, OP_SHUTDOWN = (
    range(1, 9)
)

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# sanity caps: a desynced or malicious client must not make the shared
# server allocate gigabytes from one malformed length field
_MAX_FRAME = 1 << 30   # 1 GiB per value
_MAX_BATCH = 1 << 20   # keys per mset/mget


class _ProtocolError(ConnectionError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tcp store connection closed")
        buf += chunk
    return bytes(buf)


def _recv_bytes(sock: socket.socket) -> bytes:
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    if n > _MAX_FRAME:
        raise _ProtocolError(f"frame of {n} bytes exceeds cap {_MAX_FRAME}")
    return _recv_exact(sock, n)


def _recv_count(sock: socket.socket) -> int:
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    if n > _MAX_BATCH:
        raise _ProtocolError(f"batch of {n} items exceeds cap {_MAX_BATCH}")
    return n


def _pack_bytes(data: bytes) -> bytes:
    return _U32.pack(len(data)) + data


def _to_bytes(v: Value) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


# ---------------------------------------------------------------------------
# Python fallback server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        data: Dict[bytes, bytes] = self.server.data  # type: ignore[attr-defined]
        lock: threading.Lock = self.server.data_lock  # type: ignore[attr-defined]
        sock = self.request
        try:
            while True:
                (op,) = _U8.unpack(_recv_exact(sock, 1))
                if op == OP_SET:
                    k, v = _recv_bytes(sock), _recv_bytes(sock)
                    with lock:
                        data[k] = v
                    sock.sendall(_U8.pack(0))
                elif op == OP_GET:
                    k = _recv_bytes(sock)
                    with lock:
                        v = data.get(k)
                    sock.sendall(
                        _U8.pack(0) if v is None
                        else _U8.pack(1) + _pack_bytes(v)
                    )
                elif op == OP_MSET:
                    n = _recv_count(sock)
                    items = [
                        (_recv_bytes(sock), _recv_bytes(sock)) for _ in range(n)
                    ]
                    with lock:
                        data.update(items)
                    sock.sendall(_U8.pack(0))
                elif op == OP_MGET:
                    n = _recv_count(sock)
                    keys = [_recv_bytes(sock) for _ in range(n)]
                    with lock:
                        vals = [data.get(k) for k in keys]
                    out = [_U32.pack(n)]
                    for v in vals:
                        out.append(
                            _U8.pack(0) if v is None
                            else _U8.pack(1) + _pack_bytes(v)
                        )
                    sock.sendall(b"".join(out))
                elif op == OP_NUM_KEYS:
                    with lock:
                        sock.sendall(_U64.pack(len(data)))
                elif op == OP_CLEAR:
                    with lock:
                        data.clear()
                    sock.sendall(_U8.pack(0))
                elif op == OP_PING:
                    sock.sendall(_U8.pack(0))
                elif op == OP_SHUTDOWN:
                    sock.sendall(_U8.pack(0))
                    threading.Thread(
                        target=self.server.shutdown, daemon=True
                    ).start()
                    return
                else:
                    return  # unknown op: drop the connection
        except (ConnectionError, OSError):
            return


class _Server(socketserver.ThreadingTCPServer):
    # class attrs take effect before bind (instance assignment after
    # bind_and_activate=True would be a no-op)
    allow_reuse_address = True
    daemon_threads = True
    # socketserver's default listen backlog is 5.  A pod-scale cold start
    # is a connect STORM — every launcher plus every heartbeat thread
    # dials the one restart store within the same join window — and with
    # a 5-deep accept queue the kernel drops the overflow SYNs, which
    # clients only recover from after a ≥1 s retransmit.  That turns an
    # O(ms) rendezvous into O(seconds) at 128+ connections (measured by
    # scripts/scale_drill.py, before/after in BENCH_SCALE.json).
    request_queue_size = 256


class TCPStoreServer:
    """A KV server on (host, port); port 0 = auto-pick.

    ``backend="auto"`` prefers the compiled C++ server (building it on first
    use) and falls back to the in-process Python server; ``"python"`` /
    ``"cpp"`` force one.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "auto"):
        self._proc: Optional[subprocess.Popen] = None
        self._server = None
        self._addr: Tuple[str, int] = (host, port)
        if backend in ("auto", "cpp"):
            from .native_build import ensure_store_server

            binary = ensure_store_server(required=(backend == "cpp"))
            if binary is not None:
                self._spawn_native(binary, host, port)
                return
        self._start_python(host, port)

    def _start_python(self, host: str, port: int) -> None:
        self._server = _Server((host, port), _Handler, bind_and_activate=True)
        self._server.data = {}  # type: ignore[attr-defined]
        self._server.data_lock = threading.Lock()  # type: ignore[attr-defined]
        self._addr = self._server.server_address[:2]
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()

    def _spawn_native(self, binary: str, host: str, port: int) -> None:
        # the server prints "LISTENING <port>\n" once bound
        self._proc = subprocess.Popen(
            [binary, host, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        line = self._proc.stdout.readline()
        if not line.startswith("LISTENING"):
            raise RuntimeError(f"native store server failed to start: {line!r}")
        self._addr = (host, int(line.split()[1]))

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    @property
    def is_native(self) -> bool:
        return self._proc is not None

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None


class TCPStore(Store):
    """Client for one store server (one connection, lock-guarded)."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.host, self.port = host, int(port)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def set(self, key: str, value: Value) -> None:
        msg = _U8.pack(OP_SET) + _pack_bytes(key.encode()) + _pack_bytes(
            _to_bytes(value)
        )
        with self._lock:
            self._sock.sendall(msg)
            _recv_exact(self._sock, 1)

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            self._sock.sendall(_U8.pack(OP_GET) + _pack_bytes(key.encode()))
            (present,) = _U8.unpack(_recv_exact(self._sock, 1))
            return _recv_bytes(self._sock) if present else None

    def mset(self, dictionary: Dict[str, Value]) -> None:
        parts = [_U8.pack(OP_MSET), _U32.pack(len(dictionary))]
        for k, v in dictionary.items():
            parts.append(_pack_bytes(k.encode()))
            parts.append(_pack_bytes(_to_bytes(v)))
        with self._lock:
            self._sock.sendall(b"".join(parts))
            _recv_exact(self._sock, 1)

    def mget(self, keys: List[str]) -> List[Optional[bytes]]:
        parts = [_U8.pack(OP_MGET), _U32.pack(len(keys))]
        parts += [_pack_bytes(k.encode()) for k in keys]
        with self._lock:
            self._sock.sendall(b"".join(parts))
            (n,) = _U32.unpack(_recv_exact(self._sock, 4))
            out: List[Optional[bytes]] = []
            for _ in range(n):
                (present,) = _U8.unpack(_recv_exact(self._sock, 1))
                out.append(_recv_bytes(self._sock) if present else None)
            return out

    def num_keys(self) -> int:
        with self._lock:
            self._sock.sendall(_U8.pack(OP_NUM_KEYS))
            return _U64.unpack(_recv_exact(self._sock, 8))[0]

    def clear(self) -> None:
        with self._lock:
            self._sock.sendall(_U8.pack(OP_CLEAR))
            _recv_exact(self._sock, 1)

    def status(self) -> bool:
        try:
            with self._lock:
                self._sock.sendall(_U8.pack(OP_PING))
                _recv_exact(self._sock, 1)
            return True
        except (ConnectionError, OSError):
            return False

    def shutdown(self) -> None:
        """Ask the server to exit (for servers this client manages)."""
        try:
            with self._lock:
                self._sock.sendall(_U8.pack(OP_SHUTDOWN))
                _recv_exact(self._sock, 1)
        except (ConnectionError, OSError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class TCPClusterStore(ClusterStore):
    """Hash-sharded view over several TCP stores.

    ``hosts``: list of ``{"host": ..., "port": ...}`` dicts (same bootstrap
    shape the reference's RedisStore takes).  When ``hosts`` is None, spawns
    ``num_shards`` local servers (the single-host convenience path).
    """

    def __init__(self, hosts=None, num_shards: int = 1, backend: str = "auto"):
        self._servers: List[TCPStoreServer] = []
        if hosts is None:
            for _ in range(max(1, num_shards)):
                self._servers.append(TCPStoreServer(backend=backend))
            hosts = [
                {"host": s.address[0], "port": s.address[1]}
                for s in self._servers
            ]
        clients = [TCPStore(h["host"], int(h["port"])) for h in hosts]
        super().__init__(clients)

    def shutdown(self) -> None:
        if self._servers:  # only kill servers we spawned
            super().shutdown()
            for s in self._servers:
                s.stop()
            self._servers = []


def start_tcp_store(host: str = "127.0.0.1", port: int = 0,
                    backend: str = "auto") -> TCPStoreServer:
    """Spawn a store server and return it (its ``.address`` is connectable)."""
    return TCPStoreServer(host, port, backend=backend)
