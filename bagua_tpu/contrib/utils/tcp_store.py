"""Spawnable cross-process KV store over TCP (stdlib only).

Role counterpart of the reference's ``RedisStore``
(/root/reference/bagua/torch_api/contrib/utils/redis_store.py:38+), which
spawns ``redis-server`` processes per node and bootstraps a hash-sharded
cluster view.  This environment has no redis, and a TPU pod's host network is
plain TCP anyway, so the native equivalent is a small threaded socket server:
each host can spawn one (or connect to existing ones), and a
:class:`~bagua_tpu.contrib.utils.store.ClusterStore` over the clients gives
the same sharded shared-cache semantics.

Wire protocol: length-prefixed pickle request/response per connection
(requests: (op, args...) tuples) — values are opaque bytes, mirroring redis
GET/SET/MSET/MGET/DBSIZE/FLUSHDB/PING/SHUTDOWN.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple, Union

from .store import ClusterStore, Store

__all__ = ["TCPStoreServer", "TCPStore", "TCPClusterStore", "start_tcp_store"]

Value = Union[str, bytes]
_LEN = struct.Struct("!I")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tcp store connection closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        data: Dict[str, Value] = self.server.data  # type: ignore[attr-defined]
        lock: threading.Lock = self.server.data_lock  # type: ignore[attr-defined]
        try:
            while True:
                op, *args = _recv_msg(self.request)
                if op == "set":
                    with lock:
                        data[args[0]] = args[1]
                    reply = True
                elif op == "get":
                    with lock:
                        reply = data.get(args[0])
                elif op == "mset":
                    with lock:
                        data.update(args[0])
                    reply = True
                elif op == "mget":
                    with lock:
                        reply = [data.get(k) for k in args[0]]
                elif op == "num_keys":
                    with lock:
                        reply = len(data)
                elif op == "clear":
                    with lock:
                        data.clear()
                    reply = True
                elif op == "ping":
                    reply = "pong"
                elif op == "shutdown":
                    _send_msg(self.request, True)
                    threading.Thread(
                        target=self.server.shutdown, daemon=True
                    ).start()
                    return
                else:
                    reply = RuntimeError(f"unknown op {op!r}")
                _send_msg(self.request, reply)
        except (ConnectionError, OSError):
            return


class _Server(socketserver.ThreadingTCPServer):
    # class attrs take effect before bind (instance assignment after
    # bind_and_activate=True would be a no-op)
    allow_reuse_address = True
    daemon_threads = True


class TCPStoreServer:
    """A threaded KV server bound to (host, port); port 0 = auto-pick."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = _Server((host, port), _Handler, bind_and_activate=True)
        self._server.data = {}  # type: ignore[attr-defined]
        self._server.data_lock = threading.Lock()  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address[:2]

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class TCPStore(Store):
    """Client for one :class:`TCPStoreServer` (one connection, lock-guarded)."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.host, self.port = host, int(port)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout_s)
        self._lock = threading.Lock()
        self._alive = True

    def _call(self, op: str, *args):
        with self._lock:
            _send_msg(self._sock, (op, *args))
            reply = _recv_msg(self._sock)
        if isinstance(reply, Exception):
            raise reply
        return reply

    def set(self, key: str, value: Value) -> None:
        self._call("set", key, value)

    def get(self, key: str) -> Optional[Value]:
        return self._call("get", key)

    def mset(self, dictionary: Dict[str, Value]) -> None:
        self._call("mset", dict(dictionary))

    def mget(self, keys: List[str]) -> List[Optional[Value]]:
        return self._call("mget", list(keys))

    def num_keys(self) -> int:
        return self._call("num_keys")

    def clear(self) -> None:
        self._call("clear")

    def status(self) -> bool:
        try:
            return self._call("ping") == "pong"
        except (ConnectionError, OSError):
            return False

    def shutdown(self) -> None:
        """Ask the server to exit (for servers this client manages)."""
        try:
            self._call("shutdown")
        except (ConnectionError, OSError):
            pass
        try:
            self._sock.close()
        finally:
            self._alive = False


class TCPClusterStore(ClusterStore):
    """Hash-sharded view over several TCP stores.

    ``hosts``: list of ``{"host": ..., "port": ...}`` dicts (same bootstrap
    shape the reference's RedisStore takes).  When ``hosts`` is None, spawns
    ``num_shards`` in-process servers (the single-host convenience path).
    """

    def __init__(self, hosts=None, num_shards: int = 1):
        self._servers: List[TCPStoreServer] = []
        if hosts is None:
            for _ in range(max(1, num_shards)):
                self._servers.append(TCPStoreServer())
            hosts = [
                {"host": s.address[0], "port": s.address[1]}
                for s in self._servers
            ]
        clients = [TCPStore(h["host"], int(h["port"])) for h in hosts]
        super().__init__(clients)

    def shutdown(self) -> None:
        if self._servers:  # only kill servers we spawned
            super().shutdown()
            for s in self._servers:
                s.stop()
            self._servers = []


def start_tcp_store(host: str = "127.0.0.1", port: int = 0) -> TCPStoreServer:
    """Spawn a store server and return it (its ``.address`` is connectable)."""
    return TCPStoreServer(host, port)
