"""Spawnable cross-process KV store over TCP — Python client + two servers.

Role counterpart of the reference's ``RedisStore``
(/root/reference/bagua/torch_api/contrib/utils/redis_store.py:38+), which
spawns ``redis-server`` (a native C server) per node and shards a cluster
view over them.  Here the native server is our own:
``csrc/bagua_store_server.cpp`` (thread-per-connection C++; built on demand
with g++ — see :mod:`.native_build`), with a stdlib-Python threaded server as
the always-available fallback.  Both speak the same language-neutral binary
protocol, so the client doesn't care which it reached.

Wire protocol (little-endian):
    request:  u8 op | op-specific payload;  bytes fields are u32 len + raw
    ops:      1=SET k v   2=GET k     3=MSET n (k v)*   4=MGET n k*
              5=NUM_KEYS  6=CLEAR     7=PING            8=SHUTDOWN
              9=GENERATION            10=PROMOTE new_gen(u64)
              11=REPL gen(u64) n (frame)*
              12=REPL_SNAPSHOT gen(u64) n (k v)*
              13=DUMP
    frames:   u8 0 (SET) k v | u8 1 (CLEAR)
    response: GET   -> u8 present + [val]
              MGET  -> u32 n + n * (u8 present + [val])
              NUM_KEYS -> u64
              GENERATION -> u8 primary + u64 generation
              PROMOTE / REPL / REPL_SNAPSHOT -> u8 status + u64 generation
              DUMP  -> u8 primary + u64 generation + u32 n + n * (k v)
              others  -> u8 status (0 = ok, 2 = write fenced)
Values are opaque bytes (the cache layer pickles sample payloads itself,
reference cache_loader.py serialize/deserialize).

Replication + generation fence (Python backend only): a server started
with ``peers`` streams every applied write (op log, in apply order) to each
peer over a per-peer link thread, resynchronizing with a full snapshot on
(re)connect or queue overflow.  Every server carries a monotonic **store
generation**; a ``PROMOTE`` with a higher generation turns a standby into
the primary, and any replication frame carrying a *lower* generation is
refused with a fence status — which the stale sender obeys by demoting
itself, after which its clients' writes get the fence ack (status 2) and
the failover client (:mod:`bagua_tpu.elastic.failover`) moves on to the
promoted endpoint.  A plain server (no peers, default role) keeps
generation 0 / primary and is byte-for-byte the pre-replication protocol.
"""

from __future__ import annotations

import logging
import random
import socket
import socketserver
import struct
import subprocess
import threading
from typing import Dict, List, Optional, Tuple, Union

from .store import ClusterStore, Store

__all__ = [
    "TCPStoreServer", "TCPStore", "TCPClusterStore", "start_tcp_store",
    "StoreFencedError",
]

log = logging.getLogger(__name__)

Value = Union[str, bytes]

OP_SET, OP_GET, OP_MSET, OP_MGET, OP_NUM_KEYS, OP_CLEAR, OP_PING, OP_SHUTDOWN = (
    range(1, 9)
)
OP_GENERATION, OP_PROMOTE, OP_REPL, OP_REPL_SNAPSHOT, OP_DUMP = range(9, 14)

ACK_OK = 0
ACK_FENCED = 2

_FRAME_SET = 0
_FRAME_CLEAR = 1

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# sanity caps: a desynced or malicious client must not make the shared
# server allocate gigabytes from one malformed length field
_MAX_FRAME = 1 << 30   # 1 GiB per value
_MAX_BATCH = 1 << 20   # keys per mset/mget


class _ProtocolError(ConnectionError):
    pass


class StoreFencedError(ConnectionError):
    """A write was refused by a demoted / standby server (generation fence).

    A ``ConnectionError`` subclass on purpose: every production retry path
    (`_STORE_RETRY_ERRORS`) already treats it as "this endpoint is not
    usable, reconnect" — which for the failover client means *try the next
    endpoint*, exactly the right response to a fenced write."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tcp store connection closed")
        buf += chunk
    return bytes(buf)


def _recv_bytes(sock: socket.socket) -> bytes:
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    if n > _MAX_FRAME:
        raise _ProtocolError(f"frame of {n} bytes exceeds cap {_MAX_FRAME}")
    return _recv_exact(sock, n)


def _recv_count(sock: socket.socket) -> int:
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    if n > _MAX_BATCH:
        raise _ProtocolError(f"batch of {n} items exceeds cap {_MAX_BATCH}")
    return n


def _pack_bytes(data: bytes) -> bytes:
    return _U32.pack(len(data)) + data


def _to_bytes(v: Value) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


# ---------------------------------------------------------------------------
# Python fallback server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        data: Dict[bytes, bytes] = self.server.data  # type: ignore[attr-defined]
        lock: threading.Lock = self.server.data_lock  # type: ignore[attr-defined]
        srv = self.server
        sock = self.request
        # registered so stop() can sever established connections too — a
        # stopped server must not keep serving a stale world to clients
        # that dialed in before it died (failover correctness: their next
        # op must fail over, not read a zombie's dict)
        with lock:
            srv.live_socks.add(sock)
        try:
            while True:
                (op,) = _U8.unpack(_recv_exact(sock, 1))
                if op == OP_SET:
                    k, v = _recv_bytes(sock), _recv_bytes(sock)
                    with lock:
                        fenced = not srv.primary
                        if not fenced:
                            data[k] = v
                            if srv.replicator is not None:
                                srv.replicator.log_set([(k, v)])
                    sock.sendall(_U8.pack(ACK_FENCED if fenced else ACK_OK))
                elif op == OP_GET:
                    k = _recv_bytes(sock)
                    with lock:
                        v = data.get(k)
                    sock.sendall(
                        _U8.pack(0) if v is None
                        else _U8.pack(1) + _pack_bytes(v)
                    )
                elif op == OP_MSET:
                    n = _recv_count(sock)
                    items = [
                        (_recv_bytes(sock), _recv_bytes(sock)) for _ in range(n)
                    ]
                    with lock:
                        fenced = not srv.primary
                        if not fenced:
                            data.update(items)
                            if srv.replicator is not None:
                                srv.replicator.log_set(items)
                    sock.sendall(_U8.pack(ACK_FENCED if fenced else ACK_OK))
                elif op == OP_MGET:
                    n = _recv_count(sock)
                    keys = [_recv_bytes(sock) for _ in range(n)]
                    with lock:
                        vals = [data.get(k) for k in keys]
                    out = [_U32.pack(n)]
                    for v in vals:
                        out.append(
                            _U8.pack(0) if v is None
                            else _U8.pack(1) + _pack_bytes(v)
                        )
                    sock.sendall(b"".join(out))
                elif op == OP_NUM_KEYS:
                    with lock:
                        sock.sendall(_U64.pack(len(data)))
                elif op == OP_CLEAR:
                    with lock:
                        fenced = not srv.primary
                        if not fenced:
                            data.clear()
                            if srv.replicator is not None:
                                srv.replicator.log_clear()
                    sock.sendall(_U8.pack(ACK_FENCED if fenced else ACK_OK))
                elif op == OP_PING:
                    sock.sendall(_U8.pack(0))
                elif op == OP_GENERATION:
                    with lock:
                        primary, gen = srv.primary, srv.generation
                    sock.sendall(_U8.pack(1 if primary else 0) + _U64.pack(gen))
                elif op == OP_PROMOTE:
                    (new_gen,) = _U64.unpack(_recv_exact(sock, 8))
                    with lock:
                        if new_gen > srv.generation:
                            srv.generation = new_gen
                            was_primary, srv.primary = srv.primary, True
                            status, gen = ACK_OK, new_gen
                        else:
                            status, gen = ACK_FENCED, srv.generation
                    if status == ACK_OK and not was_primary:
                        log.info("tcp store: promoted to primary "
                                 "(generation %d)", gen)
                        if srv.replicator is not None:
                            srv.replicator.resync()
                    sock.sendall(_U8.pack(status) + _U64.pack(gen))
                elif op == OP_REPL:
                    (sender_gen,) = _U64.unpack(_recv_exact(sock, 8))
                    n = _recv_count(sock)
                    frames = []
                    for _ in range(n):
                        (kind,) = _U8.unpack(_recv_exact(sock, 1))
                        if kind == _FRAME_SET:
                            frames.append(
                                (kind, _recv_bytes(sock), _recv_bytes(sock))
                            )
                        elif kind == _FRAME_CLEAR:
                            frames.append((kind, b"", b""))
                        else:
                            raise _ProtocolError(f"bad repl frame kind {kind}")
                    with lock:
                        if sender_gen < srv.generation:
                            status, gen = ACK_FENCED, srv.generation
                        else:
                            srv.generation = sender_gen
                            srv.primary = False  # replica of a live primary
                            for kind, k, v in frames:
                                if kind == _FRAME_SET:
                                    data[k] = v
                                else:
                                    data.clear()
                            status, gen = ACK_OK, sender_gen
                    sock.sendall(_U8.pack(status) + _U64.pack(gen))
                elif op == OP_REPL_SNAPSHOT:
                    (sender_gen,) = _U64.unpack(_recv_exact(sock, 8))
                    n = _recv_count(sock)
                    items = [
                        (_recv_bytes(sock), _recv_bytes(sock)) for _ in range(n)
                    ]
                    with lock:
                        if sender_gen < srv.generation:
                            status, gen = ACK_FENCED, srv.generation
                        else:
                            srv.generation = sender_gen
                            srv.primary = False
                            data.clear()
                            data.update(items)
                            status, gen = ACK_OK, sender_gen
                    sock.sendall(_U8.pack(status) + _U64.pack(gen))
                elif op == OP_DUMP:
                    with lock:
                        primary, gen = srv.primary, srv.generation
                        items = list(data.items())
                    out = [_U8.pack(1 if primary else 0), _U64.pack(gen),
                           _U32.pack(len(items))]
                    for k, v in items:
                        out.append(_pack_bytes(k))
                        out.append(_pack_bytes(v))
                    sock.sendall(b"".join(out))
                elif op == OP_SHUTDOWN:
                    sock.sendall(_U8.pack(0))
                    threading.Thread(
                        target=self.server.shutdown, daemon=True
                    ).start()
                    return
                else:
                    return  # unknown op: drop the connection
        except (ConnectionError, OSError):
            return
        finally:
            with lock:
                srv.live_socks.discard(sock)


class _Server(socketserver.ThreadingTCPServer):
    # class attrs take effect before bind (instance assignment after
    # bind_and_activate=True would be a no-op)
    allow_reuse_address = True
    daemon_threads = True
    # socketserver's default listen backlog is 5.  A pod-scale cold start
    # is a connect STORM — every launcher plus every heartbeat thread
    # dials the one restart store within the same join window — and with
    # a 5-deep accept queue the kernel drops the overflow SYNs, which
    # clients only recover from after a ≥1 s retransmit.  That turns an
    # O(ms) rendezvous into O(seconds) at 128+ connections (measured by
    # scripts/scale_drill.py, before/after in BENCH_SCALE.json).
    request_queue_size = 256
    # replication defaults for a plain server; instance attrs (all guarded
    # by data_lock) override them when the server participates in a
    # replicated group
    primary = True
    generation = 0
    replicator: Optional["_Replicator"] = None


class _ReplLink:
    """One replication link: primary -> one peer endpoint.

    Owns a bounded op-log queue and a sender thread.  The handler appends
    frames *while holding data_lock* so the log order is exactly the apply
    order; the sender drains and ships them outside every lock.  A
    (re)connect or a queue overflow falls back to a full snapshot, so a
    follower that missed frames always converges.  A fence response (the
    peer runs a higher generation) demotes the local server: its clients'
    writes start failing with the fence ack, which is what makes "a stale
    primary can never keep accepting writes after takeover" true."""

    _BATCH = 256          # frames per OP_REPL message
    _MAX_QUEUE = 8192     # frames buffered before snapshot fallback

    def __init__(self, server: "_Server", host: str, port: int):
        self._server = server
        self.host, self.port = host, int(port)
        self._cond = threading.Condition()
        self._queue: List[Tuple[int, bytes, bytes]] = []
        self._need_snapshot = True
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"store-repl-{host}:{port}",
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    # -- producer side (called by _Handler, data_lock held) --

    def append(self, frames: List[Tuple[int, bytes, bytes]]) -> None:
        with self._cond:
            if len(self._queue) + len(frames) > self._MAX_QUEUE:
                # overflow: drop the log, resync with a snapshot instead
                self._queue.clear()
                self._need_snapshot = True
            else:
                self._queue.extend(frames)
            self._cond.notify_all()

    def mark_resync(self) -> None:
        with self._cond:
            self._queue.clear()
            self._need_snapshot = True
            self._cond.notify_all()

    # -- sender thread --

    def _run(self) -> None:
        sock: Optional[socket.socket] = None
        backoff = 0.05
        while not self._stop.is_set():
            with self._server.data_lock:
                is_primary = self._server.primary
            if not is_primary:
                # demoted/standby: replication is the primary's job; park
                # (a later PROMOTE calls mark_resync() and we pick up here)
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                self._stop.wait(0.1)
                continue
            with self._cond:
                need_snapshot = self._need_snapshot
                if not need_snapshot and not self._queue:
                    self._cond.wait(timeout=0.2)
                    continue
                batch = [] if need_snapshot else self._queue[:self._BATCH]
                if not need_snapshot:
                    del self._queue[:len(batch)]
            try:
                if sock is None:
                    sock = socket.create_connection(
                        (self.host, self.port), timeout=5.0
                    )
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    with self._cond:
                        self._need_snapshot = True
                        need_snapshot = True
                        self._queue.clear()
                if need_snapshot:
                    status, peer_gen = self._send_snapshot(sock)
                    if status == ACK_OK:
                        with self._cond:
                            self._need_snapshot = False
                else:
                    status, peer_gen = self._send_frames(sock, batch)
                backoff = 0.05
            except (ConnectionError, OSError, struct.error):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                with self._cond:
                    self._need_snapshot = True
                # jittered backoff so N links don't re-dial in lockstep
                self._stop.wait(backoff * (1.0 + random.random()))
                backoff = min(2.0, backoff * 2)
                continue
            if status == ACK_FENCED:
                self._demote(peer_gen)

    def _snapshot(self) -> Tuple[int, List[Tuple[bytes, bytes]]]:
        with self._server.data_lock:
            return self._server.generation, list(self._server.data.items())

    def _send_snapshot(self, sock: socket.socket) -> Tuple[int, int]:
        gen, items = self._snapshot()
        parts = [_U8.pack(OP_REPL_SNAPSHOT), _U64.pack(gen),
                 _U32.pack(len(items))]
        for k, v in items:
            parts.append(_pack_bytes(k))
            parts.append(_pack_bytes(v))
        sock.sendall(b"".join(parts))
        (status,) = _U8.unpack(_recv_exact(sock, 1))
        (peer_gen,) = _U64.unpack(_recv_exact(sock, 8))
        return status, peer_gen

    def _send_frames(self, sock: socket.socket,
                     frames: List[Tuple[int, bytes, bytes]]) -> Tuple[int, int]:
        with self._server.data_lock:
            gen = self._server.generation
        parts = [_U8.pack(OP_REPL), _U64.pack(gen), _U32.pack(len(frames))]
        for kind, k, v in frames:
            if kind == _FRAME_SET:
                parts.append(_U8.pack(_FRAME_SET))
                parts.append(_pack_bytes(k))
                parts.append(_pack_bytes(v))
            else:
                parts.append(_U8.pack(_FRAME_CLEAR))
        sock.sendall(b"".join(parts))
        (status,) = _U8.unpack(_recv_exact(sock, 1))
        (peer_gen,) = _U64.unpack(_recv_exact(sock, 8))
        return status, peer_gen

    def _demote(self, peer_gen: int) -> None:
        with self._server.data_lock:
            if not self._server.primary:
                return
            self._server.primary = False
        log.warning(
            "tcp store: peer %s:%d runs generation %d > ours — demoting "
            "(late writes on this server are now fenced)",
            self.host, self.port, peer_gen,
        )


class _Replicator:
    """Fan-out of the primary's op log to every peer endpoint."""

    def __init__(self, server: "_Server",
                 peers: List[Tuple[str, int]]):
        self._links = [_ReplLink(server, h, p) for h, p in peers]

    def start(self) -> None:
        for link in self._links:
            link.start()

    def stop(self) -> None:
        for link in self._links:
            link.stop()

    def log_set(self, items: List[Tuple[bytes, bytes]]) -> None:
        frames = [(_FRAME_SET, k, v) for k, v in items]
        for link in self._links:
            link.append(frames)

    def log_clear(self) -> None:
        for link in self._links:
            link.append([(_FRAME_CLEAR, b"", b"")])

    def resync(self) -> None:
        """Freshly promoted: push a full snapshot at the new generation to
        every peer (their logs were cut against the dead primary)."""
        for link in self._links:
            link.mark_resync()


class TCPStoreServer:
    """A KV server on (host, port); port 0 = auto-pick.

    ``backend="auto"`` prefers the compiled C++ server (building it on first
    use) and falls back to the in-process Python server; ``"python"`` /
    ``"cpp"`` force one.

    ``peers`` (list of ``(host, port)``) enrolls this server in a
    replicated group: while primary, it streams its op log (snapshot
    fallback) to every peer.  ``role="standby"`` starts it fenced (writes
    refused) until a ``PROMOTE`` lands.  Replication forces the Python
    backend — the native C++ server speaks only the base protocol.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "auto",
                 peers: Optional[List[Tuple[str, int]]] = None,
                 role: str = "primary"):
        if role not in ("primary", "standby"):
            raise ValueError(f"bad store role {role!r}")
        self._proc: Optional[subprocess.Popen] = None
        self._server = None
        self._addr: Tuple[str, int] = (host, port)
        self._peers = [(h, int(p)) for h, p in (peers or [])]
        self._role = role
        if self._peers or role != "primary":
            backend = "python"  # replication lives in the Python server
        if backend in ("auto", "cpp"):
            from .native_build import ensure_store_server

            binary = ensure_store_server(required=(backend == "cpp"))
            if binary is not None:
                self._spawn_native(binary, host, port)
                return
        self._start_python(host, port)

    def _start_python(self, host: str, port: int) -> None:
        self._server = _Server((host, port), _Handler, bind_and_activate=True)
        self._server.data = {}  # type: ignore[attr-defined]
        self._server.data_lock = threading.Lock()  # type: ignore[attr-defined]
        self._server.live_socks = set()  # type: ignore[attr-defined]
        self._server.primary = self._role == "primary"
        self._server.generation = 0
        if self._peers and self._role == "primary":
            self._recover_from_peers()
        if self._peers:
            self._server.replicator = _Replicator(self._server, self._peers)
            self._server.replicator.start()
        self._addr = self._server.server_address[:2]
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()

    def _recover_from_peers(self) -> None:
        """Boot-time recovery for a (re)starting primary: adopt the best
        surviving peer's state instead of replicating an empty dict over
        it.  Without this, a relaunched primary (fresh process, generation
        0, zero keys) would snapshot-WIPE followers still holding the
        autopilot/historian state that replication exists to preserve.
        If any reachable peer claims the primary role, this server starts
        demoted — a takeover already happened, and the leadership layer
        (elastic.failover) must not see two willing primaries."""
        best: Optional[Tuple[int, int, Dict[bytes, bytes]]] = None
        peer_is_primary = False
        for host, port in self._peers:
            try:
                client = TCPStore(host, port, timeout_s=1.0)
            except OSError:
                continue  # peer still booting (fleet cold start)
            try:
                primary, gen, items = client.dump()
            except (ConnectionError, OSError):
                continue  # pre-replication peer: nothing to recover
            finally:
                try:
                    client._sock.close()
                except OSError:
                    pass
            peer_is_primary = peer_is_primary or primary
            if items or gen:
                rank = (gen, len(items))
                if best is None or rank > best[:2]:
                    best = (gen, len(items), items)
        if best is not None:
            gen, _n, items = best
            with self._server.data_lock:
                if not self._server.data:  # never clobber local state
                    self._server.data.update(items)
                    self._server.generation = max(
                        self._server.generation, gen)
            log.info(
                "tcp store: recovered %d key(s) at generation %d from a "
                "surviving peer", len(items), gen,
            )
        if peer_is_primary:
            with self._server.data_lock:
                self._server.primary = False
            log.warning(
                "tcp store: a peer already holds the primary role — "
                "starting demoted (leadership belongs to the takeover)"
            )

    def _spawn_native(self, binary: str, host: str, port: int) -> None:
        # the server prints "LISTENING <port>\n" once bound
        self._proc = subprocess.Popen(
            [binary, host, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        line = self._proc.stdout.readline()
        if not line.startswith("LISTENING"):
            raise RuntimeError(f"native store server failed to start: {line!r}")
        self._addr = (host, int(line.split()[1]))

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    @property
    def is_native(self) -> bool:
        return self._proc is not None

    @property
    def is_primary(self) -> bool:
        """False once this server has been fenced out of the write path
        (started standby, or demoted by a higher-generation peer)."""
        if self._server is None:
            return True  # native backend: always the base protocol
        with self._server.data_lock:
            return bool(self._server.primary)

    @property
    def generation(self) -> int:
        if self._server is None:
            return 0
        with self._server.data_lock:
            return int(self._server.generation)

    def stop(self) -> None:
        if self._server is not None:
            if self._server.replicator is not None:
                self._server.replicator.stop()
            self._server.shutdown()
            self._server.server_close()
            with self._server.data_lock:
                socks = list(self._server.live_socks)
                self._server.live_socks.clear()
            for sock in socks:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            self._server = None
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None


class TCPStore(Store):
    """Client for one store server (one connection, lock-guarded)."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.host, self.port = host, int(port)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _check_ack(self, ack: bytes) -> None:
        if ack == _U8.pack(ACK_FENCED):
            raise StoreFencedError(
                f"write fenced by {self.host}:{self.port} (demoted/standby "
                f"server — a newer store generation holds the write path)"
            )

    def set(self, key: str, value: Value) -> None:
        msg = _U8.pack(OP_SET) + _pack_bytes(key.encode()) + _pack_bytes(
            _to_bytes(value)
        )
        with self._lock:
            self._sock.sendall(msg)
            self._check_ack(_recv_exact(self._sock, 1))

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            self._sock.sendall(_U8.pack(OP_GET) + _pack_bytes(key.encode()))
            (present,) = _U8.unpack(_recv_exact(self._sock, 1))
            return _recv_bytes(self._sock) if present else None

    def mset(self, dictionary: Dict[str, Value]) -> None:
        parts = [_U8.pack(OP_MSET), _U32.pack(len(dictionary))]
        for k, v in dictionary.items():
            parts.append(_pack_bytes(k.encode()))
            parts.append(_pack_bytes(_to_bytes(v)))
        with self._lock:
            self._sock.sendall(b"".join(parts))
            self._check_ack(_recv_exact(self._sock, 1))

    def mget(self, keys: List[str]) -> List[Optional[bytes]]:
        parts = [_U8.pack(OP_MGET), _U32.pack(len(keys))]
        parts += [_pack_bytes(k.encode()) for k in keys]
        with self._lock:
            self._sock.sendall(b"".join(parts))
            (n,) = _U32.unpack(_recv_exact(self._sock, 4))
            out: List[Optional[bytes]] = []
            for _ in range(n):
                (present,) = _U8.unpack(_recv_exact(self._sock, 1))
                out.append(_recv_bytes(self._sock) if present else None)
            return out

    def num_keys(self) -> int:
        with self._lock:
            self._sock.sendall(_U8.pack(OP_NUM_KEYS))
            return _U64.unpack(_recv_exact(self._sock, 8))[0]

    def clear(self) -> None:
        with self._lock:
            self._sock.sendall(_U8.pack(OP_CLEAR))
            self._check_ack(_recv_exact(self._sock, 1))

    def generation(self) -> Tuple[bool, int]:
        """(is_primary, store generation) of the connected server.

        A pre-replication server drops the connection on the unknown op —
        surfaced as ``ConnectionError``, which callers treat as
        "generation 0, primary" when they want compatibility."""
        with self._lock:
            self._sock.sendall(_U8.pack(OP_GENERATION))
            (primary,) = _U8.unpack(_recv_exact(self._sock, 1))
            (gen,) = _U64.unpack(_recv_exact(self._sock, 8))
            return bool(primary), gen

    def dump(self) -> Tuple[bool, int, Dict[bytes, bytes]]:
        """(is_primary, generation, full KV copy) of the connected server
        — boot-time peer recovery and drill verification."""
        with self._lock:
            self._sock.sendall(_U8.pack(OP_DUMP))
            (primary,) = _U8.unpack(_recv_exact(self._sock, 1))
            (gen,) = _U64.unpack(_recv_exact(self._sock, 8))
            (n,) = _U32.unpack(_recv_exact(self._sock, 4))
            items = {}
            for _ in range(n):
                k = _recv_bytes(self._sock)
                items[k] = _recv_bytes(self._sock)
            return bool(primary), gen, items

    def promote(self, new_generation: int) -> Tuple[bool, int]:
        """Ask the server to take the write path at ``new_generation``.

        Returns ``(promoted, server_generation)``; ``promoted`` is False
        when the server already runs a generation >= ``new_generation``
        (the caller lost a promotion race — adopt the returned one)."""
        with self._lock:
            self._sock.sendall(
                _U8.pack(OP_PROMOTE) + _U64.pack(int(new_generation))
            )
            (status,) = _U8.unpack(_recv_exact(self._sock, 1))
            (gen,) = _U64.unpack(_recv_exact(self._sock, 8))
            return status == ACK_OK, gen

    def status(self) -> bool:
        try:
            with self._lock:
                self._sock.sendall(_U8.pack(OP_PING))
                _recv_exact(self._sock, 1)
            return True
        except (ConnectionError, OSError):
            return False

    def shutdown(self) -> None:
        """Ask the server to exit (for servers this client manages)."""
        try:
            with self._lock:
                self._sock.sendall(_U8.pack(OP_SHUTDOWN))
                _recv_exact(self._sock, 1)
        except (ConnectionError, OSError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class TCPClusterStore(ClusterStore):
    """Hash-sharded view over several TCP stores.

    ``hosts``: list of ``{"host": ..., "port": ...}`` dicts (same bootstrap
    shape the reference's RedisStore takes).  When ``hosts`` is None, spawns
    ``num_shards`` local servers (the single-host convenience path).
    """

    def __init__(self, hosts=None, num_shards: int = 1, backend: str = "auto"):
        self._servers: List[TCPStoreServer] = []
        if hosts is None:
            for _ in range(max(1, num_shards)):
                self._servers.append(TCPStoreServer(backend=backend))
            hosts = [
                {"host": s.address[0], "port": s.address[1]}
                for s in self._servers
            ]
        clients = [TCPStore(h["host"], int(h["port"])) for h in hosts]
        super().__init__(clients)

    def shutdown(self) -> None:
        if self._servers:  # only kill servers we spawned
            super().shutdown()
            for s in self._servers:
                s.stop()
            self._servers = []


def start_tcp_store(host: str = "127.0.0.1", port: int = 0,
                    backend: str = "auto") -> TCPStoreServer:
    """Spawn a store server and return it (its ``.address`` is connectable)."""
    return TCPStoreServer(host, port, backend=backend)
