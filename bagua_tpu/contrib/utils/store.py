"""Key-value store abstraction: ``Store`` + hash-sharded ``ClusterStore``.

Counterpart of /root/reference/bagua/torch_api/contrib/utils/store.py:8-145:
the same API surface (set/get/num_keys/clear/mset/mget/status/shutdown) and
the same sharding rule (stable 64-bit key hash modulo the number of store
instances) so entries written through one worker's cluster view are found by
every other worker's view.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Union

__all__ = ["Store", "ClusterStore", "InMemoryStore"]

Value = Union[str, bytes]


def _default_hash():
    """Stable (process- and install-independent) 64-bit key hash.

    Python's builtin ``hash`` is salted per process, which would route the
    same key to different shards in different workers.  The reference uses
    xxh64 (store.py:72-77); here it's stdlib blake2b *unconditionally* — an
    optional xxhash fast path would silently route the same key to different
    shards on workers with different installed packages, breaking the shared
    cache.  Hashing cost is noise next to the store round-trip.
    """
    import hashlib

    return lambda data: int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big"
    )


class Store:
    """Base class for key-value store implementations.

    Entries are added with :meth:`set`/:meth:`mset` and retrieved with
    :meth:`get`/:meth:`mget` (reference store.py:8-53).
    """

    def set(self, key: str, value: Value) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[Value]:
        raise NotImplementedError

    def num_keys(self) -> int:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError

    def mset(self, dictionary: Dict[str, Value]) -> None:
        for k, v in dictionary.items():
            self.set(k, v)

    def mget(self, keys: List[str]) -> List[Optional[Value]]:
        return [self.get(k) for k in keys]

    def status(self) -> bool:
        """True when the store is alive."""
        return True

    def shutdown(self) -> None:
        """Shut down managed store instances (unmanaged ones are left alone)."""


class InMemoryStore(Store):
    """Process-local dict-backed store (thread-safe).

    The single-process backend for :class:`~bagua_tpu.contrib.CacheLoader`:
    on a TPU host one JAX process drives all local chips, so "shared across
    local workers" degenerates to process-local memory.  Cross-process
    sharing uses :class:`bagua_tpu.contrib.utils.tcp_store.TCPStore`.
    """

    def __init__(self):
        self._data: Dict[str, Value] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: Value) -> None:
        with self._lock:
            self._data[key] = value

    def get(self, key: str) -> Optional[Value]:
        with self._lock:
            return self._data.get(key)

    def num_keys(self) -> int:
        with self._lock:
            return len(self._data)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def mset(self, dictionary: Dict[str, Value]) -> None:
        with self._lock:
            self._data.update(dictionary)

    def mget(self, keys: List[str]) -> List[Optional[Value]]:
        with self._lock:
            return [self._data.get(k) for k in keys]


class ClusterStore(Store):
    """Shards entries over multiple stores by a stable key hash.

    Same routing semantics as the reference (store.py:56-145): ``shard =
    hash64(key) % num_stores``, batch operations are routed per shard.
    """

    def __init__(self, stores: List[Store]):
        if not stores:
            raise ValueError("ClusterStore needs at least one store")
        self.stores = stores
        self.num_stores = len(stores)
        self.hash_fn = _default_hash()

    def _hash_key(self, key: str) -> int:
        return self.hash_fn(key.encode()) % self.num_stores

    def route(self, key: str) -> Store:
        if self.num_stores == 1:
            return self.stores[0]
        return self.stores[self._hash_key(key)]

    def set(self, key: str, value: Value) -> None:
        self.route(key).set(key, value)

    def get(self, key: str) -> Optional[Value]:
        return self.route(key).get(key)

    def num_keys(self) -> int:
        return sum(s.num_keys() for s in self.stores)

    def clear(self) -> None:
        for s in self.stores:
            s.clear()

    def mset(self, dictionary: Dict[str, Value]) -> None:
        if self.num_stores == 1:
            return self.stores[0].mset(dictionary)
        route_table: Dict[int, Dict[str, Value]] = defaultdict(dict)
        for k, v in dictionary.items():
            route_table[self._hash_key(k)][k] = v
        for sid, m in route_table.items():
            self.stores[sid].mset(m)

    def mget(self, keys: List[str]) -> List[Optional[Value]]:
        if self.num_stores == 1:
            return self.stores[0].mget(keys)
        route_table: Dict[int, List[int]] = defaultdict(list)
        for i, k in enumerate(keys):
            route_table[self._hash_key(k)].append(i)
        out: List[Optional[Value]] = [None] * len(keys)
        for sid, positions in route_table.items():
            values = self.stores[sid].mget([keys[i] for i in positions])
            for i, v in zip(positions, values):
                out[i] = v
        return out

    def status(self) -> bool:
        return all(s.status() for s in self.stores)

    def shutdown(self) -> None:
        for s in self.stores:
            s.shutdown()
