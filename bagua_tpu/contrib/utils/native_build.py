"""On-demand build of the native store server.

The reference ships its native components prebuilt (setup.py downloads
NCCL + bagua-net, builds the Rust core); this repo's only host-native
runtime piece is small enough to compile at first use with the toolchain on
the box.  The binary is cached next to the source keyed on a source hash, so
rebuilds only happen when ``csrc/bagua_store_server.cpp`` changes.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))),
    "csrc", "bagua_store_server.cpp",
)


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    path = os.path.join(base, "bagua_tpu")
    os.makedirs(path, exist_ok=True)
    return path


def ensure_store_server(required: bool = False) -> Optional[str]:
    """Path to the compiled server binary, building it if needed.

    Returns None (fallback to the Python server) when the source or a C++
    compiler is unavailable — unless ``required``, which raises instead.
    """
    if not os.path.exists(_SRC):
        if required:
            raise FileNotFoundError(_SRC)
        return None
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        if required:
            raise RuntimeError("no C++ compiler found for the native store")
        return None

    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    binary = os.path.join(_cache_dir(), f"bagua_store_server-{digest}")
    if os.path.exists(binary):
        return binary

    tmp = tempfile.mktemp(prefix="bagua_store_server-", dir=_cache_dir())
    cmd = [cxx, "-O2", "-std=c++17", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        stderr = getattr(e, "stderr", b"") or b""
        logger.warning("native store build failed: %s", stderr.decode()[-500:])
        if required:
            raise
        return None
    os.replace(tmp, binary)  # atomic vs concurrent builders
    logger.info("built native store server -> %s", binary)
    return binary
