"""Redis-backed cluster store (optional backend).

Counterpart of /root/reference/bagua/torch_api/contrib/utils/redis_store.py:38+
(spawn-or-connect redis servers, hash-sharded cluster view).  Redis is not
part of the TPU image, so this backend is import-gated: it works when
``redis-py`` (and, for spawning, a ``redis-server`` binary) is present and
raises a clear error otherwise.  The stdlib-native equivalent with the same
semantics is :class:`bagua_tpu.contrib.utils.tcp_store.TCPClusterStore`.
"""

from __future__ import annotations

import logging
import shutil
import subprocess
import time
from typing import Dict, List, Optional, Union

from .store import ClusterStore, Store

__all__ = ["RedisStore"]

logger = logging.getLogger(__name__)

Value = Union[str, bytes]

_DEFAULT_CAPACITY = 100 * 1024**2


def _require_redis():
    try:
        import redis  # noqa: F401

        return redis
    except ImportError as e:
        raise ImportError(
            "RedisStore needs the `redis` python package (and a local "
            "`redis-server` binary to spawn instances). Use "
            "bagua_tpu.contrib.utils.tcp_store.TCPClusterStore for a "
            "dependency-free equivalent."
        ) from e


class _RedisShard(Store):
    """One redis connection with the Store API (reference redis_store.py)."""

    def __init__(self, host: str, port: int, managed_proc=None):
        redis = _require_redis()
        self._client = redis.Redis(host=host, port=int(port), db=0)
        self._proc = managed_proc

    def set(self, key: str, value: Value) -> None:
        self._client.set(key, value)

    def get(self, key: str) -> Optional[Value]:
        return self._client.get(key)

    def mset(self, dictionary: Dict[str, Value]) -> None:
        self._client.mset(dictionary)

    def mget(self, keys: List[str]) -> List[Optional[Value]]:
        return self._client.mget(keys)

    def num_keys(self) -> int:
        return int(self._client.dbsize())

    def clear(self) -> None:
        self._client.flushdb()

    def status(self) -> bool:
        try:
            return bool(self._client.ping())
        except Exception:
            return False

    def shutdown(self) -> None:
        if self._proc is not None:  # only managed instances are killed
            try:
                self._client.shutdown(nosave=True)
            except Exception:
                pass
            self._proc.terminate()
            self._proc = None


def _spawn_redis_server(port: int, capacity_bytes: int) -> subprocess.Popen:
    binary = shutil.which("redis-server")
    if binary is None:
        raise RuntimeError(
            "redis-server binary not found; pass `hosts=` to connect to "
            "existing servers, or use TCPClusterStore"
        )
    proc = subprocess.Popen(
        [
            binary,
            "--port", str(port),
            "--maxmemory", str(capacity_bytes),
            "--maxmemory-policy", "allkeys-random",
            "--appendonly", "no",
            "--save", "",
            "--protected-mode", "yes",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    return proc


class RedisStore(ClusterStore):
    """Cluster store over redis instances (spawned or existing).

    Args:
        hosts: list of ``{"host": ..., "port": ...}`` dicts of *existing*
            redis servers.  When None, spawns one local server.
        cluster_mode: shard keys over all hosts (else only this node's).
        capacity_per_node: ``maxmemory`` for spawned servers.
    """

    def __init__(
        self,
        hosts: Optional[List[Dict[str, str]]] = None,
        cluster_mode: bool = True,
        capacity_per_node: int = _DEFAULT_CAPACITY,
    ):
        _require_redis()
        shards: List[Store] = []
        if hosts is None:
            port = 7000
            proc = _spawn_redis_server(port, capacity_per_node)
            shard = _RedisShard("127.0.0.1", port, managed_proc=proc)
            deadline = time.time() + 10
            while not shard.status():
                if time.time() > deadline:
                    raise RuntimeError("spawned redis-server did not come up")
                time.sleep(0.1)
            shards.append(shard)
        else:
            use = hosts if cluster_mode else hosts[:1]
            for h in use:
                shards.append(_RedisShard(h["host"], int(h["port"])))
        super().__init__(shards)
