"""Contrib utilities: KV stores (in-memory, TCP cluster, optional redis)."""

from .store import ClusterStore, InMemoryStore, Store  # noqa: F401
from .tcp_store import TCPClusterStore, TCPStore, TCPStoreServer  # noqa: F401

__all__ = [
    "Store",
    "ClusterStore",
    "InMemoryStore",
    "TCPStore",
    "TCPStoreServer",
    "TCPClusterStore",
]
