"""Contrib layer: optimizer fusion, load balancing, sample caching, SyncBN.

Counterpart of /root/reference/bagua/torch_api/contrib/ — every component the
reference ships, rebuilt TPU-native (optax wrapper, torch-free samplers, flax
SyncBatchNorm, stdlib TCP store standing in for redis).
"""

from .cache_loader import CacheLoader  # noqa: F401
from .cached_dataset import CachedDataset  # noqa: F401
from .fused_optimizer import FusedOptimizer, fuse_optimizer  # noqa: F401
from .load_balancing_data_loader import (  # noqa: F401
    LoadBalancingDistributedBatchSampler,
    LoadBalancingDistributedSampler,
)
from .prefetch import prefetch_to_device  # noqa: F401
from .sync_batchnorm import SyncBatchNorm  # noqa: F401

__all__ = [
    "fuse_optimizer",
    "FusedOptimizer",
    "LoadBalancingDistributedSampler",
    "LoadBalancingDistributedBatchSampler",
    "CacheLoader",
    "CachedDataset",
    "SyncBatchNorm",
    "prefetch_to_device",
]
