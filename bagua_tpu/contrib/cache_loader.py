"""Sample cache over a KV store with write batching.

Counterpart of /root/reference/bagua/torch_api/contrib/cache_loader.py:17-139:
same key scheme (``"{dataset_name}_{key}"``), same ``BatchFetcher`` write
batching (flush every ``writer_buffer_size`` writes, plus a flush every 1000
reads so stragglers land), same pickle serialization.  Backends: ``"memory"``
(in-process, the TPU-host default — one JAX process drives all local chips),
``"tcp"`` (cross-process stdlib server cluster), ``"redis"`` (optional).
"""

from __future__ import annotations

import pickle
from typing import Callable, Dict

from .utils.store import InMemoryStore, Store

__all__ = ["CacheLoader", "BatchFetcher", "serialize", "deserialize"]


def serialize(obj) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize(data: bytes):
    return pickle.loads(data)


def _make_store(backend: str, **kwargs) -> Store:
    if backend == "memory":
        return InMemoryStore()
    if backend == "tcp":
        from .utils.tcp_store import TCPClusterStore

        return TCPClusterStore(**kwargs)
    if backend == "redis":
        from .utils.redis_store import RedisStore

        return RedisStore(**kwargs)
    raise ValueError(
        f'invalid backend {backend!r}: expected "memory", "tcp" or "redis"'
    )


class CacheLoader:
    """Caches values produced by an expensive ``load_fn``, keyed by sample key.

    >>> loader = CacheLoader(backend="memory", dataset_name="ds")
    >>> value = loader.get(index, lambda k: expensive_produce(k))
    """

    def __init__(
        self,
        backend: str = "memory",
        dataset_name: str = "",
        writer_buffer_size: int = 1,
        **kwargs,
    ):
        self.backend = backend
        self.dataset_name = dataset_name
        self.store = _make_store(backend, **kwargs)
        self.fetcher = BatchFetcher(self.store, 1, writer_buffer_size)

    def get(self, key, load_fn: Callable):
        """Value for ``key``; on miss, computes ``load_fn(key)`` and caches it."""
        cache_key = "{}_{}".format(self.dataset_name, key)
        ret = self.fetcher.read(cache_key)
        if ret is None:
            ret = load_fn(key)
            self.fetcher.write(cache_key, ret)
        return ret

    def num_keys(self) -> int:
        """Number of cached entries."""
        return self.store.num_keys()


class BatchFetcher:
    """Write-batching shim between the loader and the store
    (reference cache_loader.py:96-139)."""

    def __init__(self, store: Store, read_buffer_size: int, writer_buffer_size: int):
        self.store = store
        self.read_buffer_size = max(1, read_buffer_size)
        self.writer_buffer_size = max(1, writer_buffer_size)
        self.write_map: Dict[str, bytes] = {}
        self.write_cnt = 0
        self.read_cnt = 0

    def read(self, key: str):
        self.read_cnt += 1
        # pending (unflushed) writes must be consulted BEFORE the periodic
        # flush below clears them, or the 1000th read of a buffered key
        # becomes a spurious miss
        pending = self.write_map.get(key)
        if pending is not None:
            self.write_post_read()
            return deserialize(pending)
        try:
            ret = self.store.get(key)
        except Exception:
            return None
        self.write_post_read()
        return deserialize(ret) if ret is not None else None

    def write(self, key: str, value) -> None:
        self.write_cnt += 1
        self.write_map[key] = serialize(value)
        if self.write_cnt % self.writer_buffer_size == 0:
            self.flush_write_map()

    def write_post_read(self) -> None:
        if self.read_cnt % 1000 == 0 and self.write_map:
            self.flush_write_map()

    def flush_write_map(self) -> None:
        try:
            self.store.mset(self.write_map)
        except Exception:
            # cache is best-effort; entries retry on the next flush — but a
            # persistently-dead store must not grow the buffer without bound
            limit = max(1000, 10 * self.writer_buffer_size)
            if len(self.write_map) > limit:
                self.write_map.clear()
        else:
            self.write_map.clear()
