"""Fused optimizer: run the inner optimizer over per-dtype flat buffers.

Counterpart of /root/reference/bagua/torch_api/contrib/fused_optimizer.py:8-134,
which flattens parameters into contiguous storages so one optimizer step
launches a few fused kernels instead of one per tensor.  Under XLA the
*kernel* fusion is automatic inside ``jit``, so the TPU-native win is
different but real: a model with thousands of small parameter leaves produces
thousands of tiny HLO ops per optimizer state leaf — flattening them into one
buffer per dtype shrinks the compiled program, speeds up compilation, and
turns the update into a handful of large, MXU/VPU-friendly elementwise ops.

Shape: an ``optax``-style wrapper, so it composes with the trainer the same
way the reference composes with ``with_bagua`` (any
``GradientTransformation`` can be fused)::

    tx = fuse_optimizer(optax.adam(1e-3))
    trainer = BaguaTrainer(loss_fn, tx, GradientAllReduceAlgorithm())

Exact step-equality with the unfused optimizer holds for elementwise
transforms (sgd, momentum, adam, adamw with uniform weight decay, ...) —
the same caveat as the reference's storage flattening.  Transforms that
inspect per-parameter shapes (e.g. factored second moments) change meaning
when fused; don't wrap those.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["fuse_optimizer", "FusedOptimizer"]


class _FusedState(NamedTuple):
    inner: Any


def _group_leaves(tree) -> Tuple[List[str], dict]:
    """Leaves grouped by dtype name, in stable tree-flatten order."""
    leaves = jax.tree_util.tree_leaves(tree)
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(jnp.asarray(leaf).dtype.name, []).append(i)
    return sorted(groups), groups


def _flatten(tree) -> dict:
    """Pytree -> {dtype_name: 1-D buffer} (concatenated raveled leaves)."""
    leaves = jax.tree_util.tree_leaves(tree)
    keys, groups = _group_leaves(tree)
    return {
        k: jnp.concatenate([jnp.ravel(leaves[i]) for i in groups[k]])
        for k in keys
    }


def _unflatten(flat: dict, like) -> Any:
    """{dtype_name: buffer} -> pytree with ``like``'s structure/shapes."""
    leaves = jax.tree_util.tree_leaves(like)
    treedef = jax.tree_util.tree_structure(like)
    _, groups = _group_leaves(like)
    out: List[Any] = [None] * len(leaves)
    for k, idxs in groups.items():
        buf, offset = flat[k], 0
        for i in idxs:
            n = leaves[i].size
            out[i] = jax.lax.dynamic_slice_in_dim(buf, offset, n).reshape(
                leaves[i].shape
            )
            offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def fuse_optimizer(
    inner: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Wrap ``inner`` to run over per-dtype flattened buffers."""

    def init_fn(params):
        return _FusedState(inner.init(_flatten(params)))

    def update_fn(updates, state, params=None):
        flat_updates = _flatten(updates)
        flat_params = _flatten(params) if params is not None else None
        flat_out, inner_state = inner.update(
            flat_updates, state.inner, flat_params
        )
        return _unflatten(flat_out, updates), _FusedState(inner_state)

    return optax.GradientTransformation(init_fn, update_fn)


# reference-compatible name
FusedOptimizer = fuse_optimizer
