"""Fused optimizer: run the inner optimizer over per-dtype flat buffers.

Counterpart of /root/reference/bagua/torch_api/contrib/fused_optimizer.py:8-134,
which flattens parameters into contiguous storages so one optimizer step
launches a few fused kernels instead of one per tensor.  Under XLA the
*kernel* fusion is automatic inside ``jit``, so the TPU-native win is
different but real: a model with thousands of small parameter leaves produces
thousands of tiny HLO ops per optimizer state leaf — flattening them into one
buffer per dtype shrinks the compiled program, speeds up compilation, and
turns the update into a handful of large, MXU/VPU-friendly elementwise ops.

Shape: an ``optax``-style wrapper, so it composes with the trainer the same
way the reference composes with ``with_bagua`` (any
``GradientTransformation`` can be fused)::

    tx = fuse_optimizer(optax.adam(1e-3))
    trainer = BaguaTrainer(loss_fn, tx, GradientAllReduceAlgorithm())

Under the trainer's FLAT-RESIDENT layout (``flat_resident=`` /
``BAGUA_FLAT_RESIDENT``, see docs/flat_layout.md) the params already live as
bucket-flat buffers, which IS the fused layout — so the trainer unwraps the
returned transformation (:attr:`FusedTransformation.fused_inner`) and runs
the inner optimizer on the bucket flats natively: no per-step concat, no
per-leaf slicing, and the private per-dtype grouping below never traces.
The wrapper's own flatten/unflatten only runs in the leaf layout.

Exact step-equality with the unfused optimizer holds for elementwise
transforms (sgd, momentum, adam, adamw with uniform weight decay, ...) —
the same caveat as the reference's storage flattening, and the same one the
flat-resident layout inherits (whether the buffers are grouped per dtype or
per bucket).  Transforms that inspect per-parameter shapes (e.g. factored
second moments) change meaning when fused; don't wrap those.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

__all__ = ["fuse_optimizer", "FusedOptimizer", "FusedTransformation"]


class _FusedState(NamedTuple):
    inner: Any


class FusedTransformation(NamedTuple):
    """An ``optax.GradientTransformation``-shaped pair that also exposes the
    wrapped transform, so the trainer's flat-resident layout can run it on
    bucket flats directly instead of through the per-dtype flatten below."""

    init: Callable
    update: Callable
    #: the unfused inner transform ``fuse_optimizer`` wrapped
    fused_inner: optax.GradientTransformation


def _group_leaves(tree) -> Tuple[List[str], dict]:
    """Leaves grouped by dtype name, in stable tree-flatten order."""
    leaves = jax.tree_util.tree_leaves(tree)
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(jnp.asarray(leaf).dtype.name, []).append(i)
    return sorted(groups), groups


def _flatten(tree) -> dict:
    """Pytree -> {dtype_name: 1-D buffer} (concatenated raveled leaves)."""
    leaves = jax.tree_util.tree_leaves(tree)
    keys, groups = _group_leaves(tree)
    return {
        k: jnp.concatenate([jnp.ravel(leaves[i]) for i in groups[k]])
        for k in keys
    }


def _unflatten(flat: dict, like) -> Any:
    """{dtype_name: buffer} -> pytree with ``like``'s structure/shapes.

    One static ``jnp.split`` at precomputed offsets per dtype buffer: the
    split points are compile-time constants, so XLA sees plain fusable
    slices — not the O(leaves) ``dynamic_slice`` ops an index-by-index
    unpack would emit, which is exactly the program bloat this module
    exists to avoid."""
    leaves = jax.tree_util.tree_leaves(like)
    treedef = jax.tree_util.tree_structure(like)
    _, groups = _group_leaves(like)
    out: List[Any] = [None] * len(leaves)
    for k, idxs in groups.items():
        offsets = np.cumsum([leaves[i].size for i in idxs])[:-1]
        parts = (
            jnp.split(flat[k], offsets) if len(idxs) > 1 else [flat[k]]
        )
        for i, seg in zip(idxs, parts):
            out[i] = seg.reshape(leaves[i].shape)
    return jax.tree_util.tree_unflatten(treedef, out)


def fuse_optimizer(
    inner: optax.GradientTransformation,
) -> FusedTransformation:
    """Wrap ``inner`` to run over per-dtype flattened buffers."""

    def init_fn(params):
        return _FusedState(inner.init(_flatten(params)))

    def update_fn(updates, state, params=None):
        flat_updates = _flatten(updates)
        flat_params = _flatten(params) if params is not None else None
        flat_out, inner_state = inner.update(
            flat_updates, state.inner, flat_params
        )
        return _unflatten(flat_out, updates), _FusedState(inner_state)

    return FusedTransformation(init_fn, update_fn, inner)


# reference-compatible name
FusedOptimizer = fuse_optimizer
