"""Load-balancing distributed samplers.

Counterpart of
/root/reference/bagua/torch_api/contrib/load_balancing_data_loader.py:12-324.
Same semantics: samples are sorted by a user ``complexity_fn``, split into
``num_replicas``-sized chunks of *similar* complexity, chunk order is shuffled
per epoch, and rank ``r`` takes element ``r`` of each chunk — so every rank's
step-``i`` sample has comparable cost and stragglers disappear.  Useful on
TPU for exactly the reference's scenario (variable-length NLP/speech batches
in an SPMD step where the slowest shard gates the collective).

Torch-free: works with any indexable dataset; determinism comes from
``numpy.random.default_rng(seed + epoch)``, identical across ranks.  Drop-in
for ``torch.utils.data.DataLoader(sampler=...)`` (it only needs ``__iter__``
/ ``__len__`` / ``set_epoch``).
"""

from __future__ import annotations

import math
from typing import Callable, Iterator, List, Optional

import numpy as np

__all__ = [
    "LoadBalancingDistributedSampler",
    "LoadBalancingDistributedBatchSampler",
]


class LoadBalancingDistributedSampler:
    """Distributed sampler that equalizes per-step sample complexity.

    Args:
        dataset: indexable dataset of constant size.
        complexity_fn: sample -> int complexity measure.
        num_replicas: world size (default: ``bagua_tpu.env`` world size).
        rank: this worker's rank (default from env).
        shuffle: shuffle chunk order each epoch (seeded, rank-identical).
        seed: shared base seed.
        drop_last: drop the tail instead of wrap-padding it.
        random_level: 0.0 = perfect balance .. 1.0 = fully random; implemented
            as additive uniform noise on complexities scaled by their range
            (reference :146-152).
    """

    def __init__(
        self,
        dataset,
        complexity_fn: Callable[..., int],
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        random_level: float = 0.0,
    ) -> None:
        if num_replicas is None or rank is None:
            from .. import env

            num_replicas = num_replicas or env.get_world_size()
            rank = env.get_rank() if rank is None else rank
        if rank >= num_replicas or rank < 0:
            raise ValueError(
                f"Invalid rank {rank}, rank should be in [0, {num_replicas - 1}]"
            )
        self.dataset = dataset
        self.num_replicas = num_replicas
        self.rank = rank
        self.epoch = 0
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed

        dataset_len = len(dataset)
        if self.drop_last and dataset_len % num_replicas != 0:
            self.num_samples = math.ceil((dataset_len - num_replicas) / num_replicas)
        else:
            self.num_samples = math.ceil(dataset_len / num_replicas)
        self.total_size = self.num_samples * num_replicas

        self.item_complexity_map = {
            i: complexity_fn(dataset[i]) for i in range(dataset_len)
        }
        self.ordered_indices = sorted(
            self.item_complexity_map, key=self.item_complexity_map.__getitem__
        )
        if not 0.0 <= random_level <= 1.0:
            raise ValueError(
                f"Invalid random level {random_level}, should be in [0.0, 1.0]"
            )
        complexities = list(self.item_complexity_map.values())
        self.random_number = int(
            (max(complexities) - min(complexities)) * random_level + 1
        )

    def _chunks_wrap_padding(self, indices: List[int]) -> List[List[int]]:
        """Successive ``num_replicas``-sized chunks, wrapping around to fill
        exactly ``num_samples`` chunks (reference :155-166)."""
        n = self.num_replicas
        num_chunks = max(1, self.num_samples)
        out, cur = [], []
        for i in range(num_chunks * n):
            cur.append(indices[i % len(indices)])
            if len(cur) == n:
                out.append(cur)
                cur = []
        return out

    def shuffle_chunks(self):
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            # random_number == 1 means noise drawn from [0, 1) == always 0:
            # skip the pointless perturb+resort and reuse the sorted order
            if self.random_number > 1:
                noise = rng.integers(
                    0, self.random_number, len(self.item_complexity_map)
                )
                perturbed = {
                    k: v + int(n)
                    for (k, v), n in zip(self.item_complexity_map.items(), noise)
                }
                ordered = sorted(perturbed, key=perturbed.__getitem__)
            else:
                ordered = self.ordered_indices
            index_chunks = self._chunks_wrap_padding(ordered)
            chunk_indices = rng.permutation(len(index_chunks)).tolist()
        else:
            index_chunks = self._chunks_wrap_padding(self.ordered_indices)
            chunk_indices = list(range(len(index_chunks)))

        if not self.drop_last:
            padding_size = self.num_samples - len(chunk_indices)
            if padding_size > 0:
                if padding_size <= len(chunk_indices):
                    chunk_indices += chunk_indices[:padding_size]
                else:
                    chunk_indices += (
                        chunk_indices * math.ceil(padding_size / len(chunk_indices))
                    )[:padding_size]
        else:
            chunk_indices = chunk_indices[: self.num_samples]
        assert len(chunk_indices) == self.num_samples
        return index_chunks, chunk_indices

    def __iter__(self) -> Iterator[int]:
        index_chunks, chunk_indices = self.shuffle_chunks()
        indices = [index_chunks[i][self.rank] for i in chunk_indices]
        assert len(indices) == self.num_samples
        return iter(indices)

    def __len__(self) -> int:
        return self.num_samples

    def set_epoch(self, epoch: int) -> None:
        """Call before each epoch so shuffling differs across epochs but
        agrees across ranks."""
        self.epoch = epoch


class LoadBalancingDistributedBatchSampler:
    """Yields variable-sized batches from a load-balancing sampler.

    ``batch_fn(indices) -> list of batches`` lets the user pack
    variable-length samples into token-budgeted batches; ranks are padded (or
    truncated with ``drop_last``) to the same number of batches so the SPMD
    step count agrees (reference :232-324).
    """

    def __init__(
        self,
        sampler: LoadBalancingDistributedSampler,
        batch_fn: Callable[[List[int]], List[List[int]]],
        drop_last: bool = False,
    ) -> None:
        if not isinstance(sampler, LoadBalancingDistributedSampler):
            raise ValueError(
                "sampler should be of LoadBalancingDistributedSampler type."
            )
        if sampler.drop_last:
            raise ValueError("drop_last of sampler should be False")
        self.sampler = sampler
        self.batch_fn = batch_fn
        self.drop_last = drop_last
        self.num_replicas = sampler.num_replicas
        self.rank = sampler.rank
        self.generate_batches()

    def generate_batches(self) -> None:
        index_chunks, chunk_indices = self.sampler.shuffle_chunks()
        batches = []
        for rank in range(self.num_replicas):
            sub_indices = [index_chunks[i][rank] for i in chunk_indices]
            batches.append(self.batch_fn(sub_indices))

        self.total_batch = (
            max(len(b) for b in batches)
            if not self.drop_last
            else min(len(b) for b in batches)
        )
        # cycle-pad: every rank must yield exactly total_batch batches or the
        # SPMD step counts diverge and a collective hangs (a rank with fewer
        # than half the max count needs more than one lap of its own batches)
        self.padded_batches = [
            [batch[i % len(batch)] for i in range(self.total_batch)]
            if batch else []
            for batch in batches
        ]

    def __iter__(self):
        return iter(self.padded_batches[self.rank])

    def __len__(self) -> int:
        return self.total_batch

    def set_epoch(self, epoch: int) -> None:
        """Re-shuffle and re-pack for a new epoch (rank-consistent)."""
        self.sampler.set_epoch(epoch)
        self.generate_batches()
