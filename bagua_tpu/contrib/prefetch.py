"""Device prefetching: overlap host→device transfer with compute.

Additive input-pipeline piece (the reference leans on torch DataLoader's
worker processes + pinned-memory prefetch; on TPU the analogous win is
keeping the next batch's H2D transfer in flight while the current step
runs).  ``prefetch_to_device`` wraps any host batch iterator and keeps
``size`` batches resident on device, already laid out with the trainer's
batch sharding — so ``train_step`` never waits on the transfer and never
re-lays-out the input.

JAX dispatch is asynchronous: ``device_put`` returns immediately and the
transfer proceeds in the background, so a one-element lookahead buffer is
usually enough.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional

__all__ = ["prefetch_to_device"]


def prefetch_to_device(
    iterable: Iterable,
    trainer=None,
    size: int = 2,
    mesh=None,
    spec=None,
) -> Iterator:
    """Yield batches from ``iterable`` with ``size`` batches pre-transferred.

    Args:
        iterable: host-side batch iterator (pytrees of arrays).
        trainer: a :class:`~bagua_tpu.core.backend.BaguaTrainer` — batches
            are placed with ``trainer.shard_batch`` (validates shard counts
            and uses the step's input sharding).  Mutually exclusive with
            ``mesh``/``spec``.
        size: lookahead depth (≥ 1).
        mesh / spec: explicit mesh + PartitionSpec placement, for use
            without a trainer.
    """
    # validate eagerly (a generator body would defer errors to first next())
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    if trainer is not None and (mesh is not None or spec is not None):
        raise ValueError("pass trainer OR mesh/spec, not both")

    if trainer is not None:
        place = trainer.shard_batch
    elif mesh is not None and spec is not None:
        from ..parallel.mesh import make_global_array

        def place(batch):
            import jax

            return jax.tree.map(
                lambda x: make_global_array(mesh, spec, x), batch
            )
    else:
        raise ValueError("pass a trainer, or both mesh and spec")

    def gen():
        queue: collections.deque = collections.deque()
        it = iter(iterable)

        def fill():
            while len(queue) < size:
                try:
                    queue.append(place(next(it)))
                except StopIteration:
                    return

        fill()
        while queue:
            yield queue.popleft()
            fill()

    return gen()
