"""Finding record + the shrink-only baseline.

A finding is fingerprinted by ``(rule, path, text)`` where ``text`` is the
stripped source line — stable under unrelated edits that shift line numbers,
unlike a ``(path, line)`` key, so the committed baseline doesn't churn.
Matching is multiset-style: two identical copy-pasted violations need two
baseline entries, and fixing one shrinks the baseline by one.

The baseline is SHRINK-ONLY by construction: the CLI fails both on findings
missing from the baseline (new violations) and on baseline entries that no
longer fire (stale entries must be pruned — run ``--write-baseline``), so the
only way to grow it is to hand-edit the committed file, which review sees.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

BASELINE_DEFAULT = ".bagua-lint-baseline.json"


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``path:line: [rule] message`` plus a fix hint."""

    rule: str
    path: str       # repo-relative posix path ("<jaxpr>" for trace findings)
    line: int       # 1-based; 0 when the finding has no source anchor
    message: str
    hint: str = ""
    text: str = ""  # stripped source line at ``line`` (baseline fingerprint)

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.text)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{loc}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "text": f.text}
        for f in sorted(findings, key=lambda f: (f.path, f.rule, f.text))
    ]
    with open(path, "w") as fh:
        json.dump(
            {
                "comment": (
                    "bagua-lint baseline: deliberately deferred pre-existing "
                    "violations.  SHRINK-ONLY — CI fails when an entry goes "
                    "stale (fix merged: prune it with --write-baseline) and "
                    "any new finding must be fixed or suppressed inline, "
                    "never added here without review."
                ),
                "version": 1,
                "findings": entries,
            },
            fh,
            indent=2,
        )
        fh.write("\n")


def load_baseline(path: str) -> Counter:
    """Baseline as a multiset of fingerprints."""
    with open(path) as fh:
        data = json.load(fh)
    return Counter(
        (e["rule"], e["path"], e["text"]) for e in data.get("findings", [])
    )


def split_by_baseline(
    findings: Sequence[Finding], baseline: Counter
) -> Tuple[List[Finding], List[Finding], List[Tuple[str, str, str]]]:
    """-> (new_findings, baselined_findings, stale_baseline_keys)."""
    budget = Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = [k for k, n in budget.items() if n > 0 for _ in range(n)]
    return new, old, stale
