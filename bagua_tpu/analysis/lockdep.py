"""Runtime lockdep witness (bagua-lint v2).

The static concurrency engine's acquisition-order graph is built from
source; this shim validates it against reality.  When ``BAGUA_LOCKDEP=on``,
:func:`maybe_install` patches the ``threading.Lock``/``RLock`` factories so
every lock *created from bagua_tpu code* is wrapped with an instrumented
proxy keyed by its creation site ``(path, lineno)`` — the same identity the
static model gives module-level and ``self.*`` locks, so runtime and static
graphs join on it.  Locks created by the stdlib, jax, or anything else get
the real primitive back untouched.

Each thread keeps its held-lock stack; every acquisition records the
ordered edges (held-site -> acquired-site).  If the reverse edge was ever
observed — two threads taking the same pair in opposite orders, a live
deadlock window — the inversion is recorded with both witnesses.  At
process exit the witness (edges, inversions, per-site counts) is written as
JSON to ``BAGUA_LOCKDEP_OUT``.

``scripts/ci.sh`` runs the chaos smoke drill with the shim on and feeds the
witness back through ``bagua-lint --witness``: :func:`cross_check` gates
zero runtime inversions (``lockdep-runtime-inversion``) and that every
witnessed edge between statically-known locks exists in the static graph
(``lockdep-unmodeled-edge``) — i.e. the static engine saw every ordering
the real run exercised.

Install ordering matters: ``bagua_tpu/__init__`` calls
:func:`maybe_install` immediately after the env module loads, BEFORE the
communication/telemetry/obs imports that create the package's module-level
locks — so a plain ``BAGUA_LOCKDEP=on python script.py`` witnesses all of
them.  This module is stdlib-only and import-light for the same reason.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

_Site = Tuple[str, int]

#: set once by install(); never uninstalled (the wrapper delegates, so a
#: stale shim is only overhead, never a behavior change)
_STATE: Optional["_LockdepState"] = None

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class _LockdepState:
    def __init__(self, pkg_dir: str, out_path: str):
        self.pkg_dir = pkg_dir
        self.out_path = out_path
        # internal bookkeeping lock: a REAL lock, never instrumented
        self.mu = _REAL_LOCK()
        #: (from_site, to_site) -> acquisition count
        self.edges: Dict[Tuple[_Site, _Site], int] = {}
        #: site -> acquisition count
        self.sites: Dict[_Site, int] = {}
        #: observed opposite-order pairs, with the thread names involved
        self.inversions: List[Dict] = []
        self._tls = threading.local()

    def held_stack(self) -> List[_Site]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def note_acquired(self, site: _Site) -> None:
        stack = self.held_stack()
        with self.mu:
            self.sites[site] = self.sites.get(site, 0) + 1
            for held in stack:
                if held == site:
                    continue  # reentrant re-acquire, not an ordering edge
                edge = (held, site)
                first = edge not in self.edges
                self.edges[edge] = self.edges.get(edge, 0) + 1
                if first and (site, held) in self.edges:
                    self.inversions.append({
                        "a": list(held), "b": list(site),
                        "thread": threading.current_thread().name,
                    })
        stack.append(site)

    def note_released(self, site: _Site) -> None:
        stack = self.held_stack()
        # remove the LAST occurrence (locks release innermost-first, and a
        # reentrant lock can appear more than once)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                break

    def witness(self) -> Dict:
        with self.mu:
            return {
                "version": 1,
                "edges": [
                    {"from": list(a), "to": list(b), "count": n}
                    for (a, b), n in sorted(self.edges.items())
                ],
                "inversions": list(self.inversions),
                "sites": [
                    {"site": list(s), "count": n}
                    for s, n in sorted(self.sites.items())
                ],
            }

    def dump(self) -> None:
        try:
            payload = json.dumps(self.witness(), indent=1, sort_keys=True)
            tmp = f"{self.out_path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(payload)
            os.replace(tmp, self.out_path)
        except OSError:
            pass  # diagnostics must never take the process down


class _InstrumentedLock:
    """Proxy over a real Lock/RLock recording acquisition order.  Only the
    primitive-lock surface is proxied (acquire/release/locked/context
    manager) — enough for every lock this package creates."""

    __slots__ = ("_real", "_site", "_state")

    def __init__(self, real, site: _Site, state: _LockdepState):
        self._real = real
        self._site = site
        self._state = state

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._state.note_acquired(self._site)
        return got

    def release(self):
        self._real.release()
        self._state.note_released(self._site)

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockdep {self._site[0]}:{self._site[1]} {self._real!r}>"


def _creation_site(state: _LockdepState) -> Optional[_Site]:
    """(pkg-relative path, lineno) of the frame creating the lock, if that
    frame is bagua_tpu code (excluding this module)."""
    try:
        frame = sys._getframe(2)
    except ValueError:
        return None
    fname = frame.f_code.co_filename
    if not fname.startswith(state.pkg_dir) or \
            fname == os.path.abspath(__file__):
        return None
    rel = os.path.relpath(fname, os.path.dirname(state.pkg_dir))
    return (rel.replace(os.sep, "/"), frame.f_lineno)


def _lock_factory():
    state = _STATE
    real = _REAL_LOCK()
    if state is None:
        return real
    site = _creation_site(state)
    if site is None:
        return real
    return _InstrumentedLock(real, site, state)


def _rlock_factory():
    state = _STATE
    real = _REAL_RLOCK()
    if state is None:
        return real
    site = _creation_site(state)
    if site is None:
        return real
    return _InstrumentedLock(real, site, state)


def install(out_path: Optional[str] = None) -> bool:
    """Patch the lock factories and register the exit dump.  Idempotent;
    returns whether the shim is (now) active."""
    global _STATE
    if _STATE is not None:
        return True
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _STATE = _LockdepState(
        pkg_dir=pkg_dir,
        out_path=out_path or "bagua_lockdep_witness.json",
    )
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    atexit.register(_STATE.dump)
    return True


def maybe_install() -> bool:
    """Install iff ``BAGUA_LOCKDEP=on`` (via the env registry).  Called
    from ``bagua_tpu/__init__`` right after the env module loads so the
    package's own module-level locks are created through the shim."""
    if _STATE is not None:
        return True
    from .. import env

    if env.get_lockdep_mode() != "on":
        return False
    return install(env.get_lockdep_out() or None)


def current_witness() -> Optional[Dict]:
    """The live witness dict, or None when the shim is not installed."""
    return _STATE.witness() if _STATE is not None else None


def load_witness(path: str) -> Dict:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


# ---- static cross-check ----------------------------------------------------


def cross_check(witness: Dict, static_graph: Dict) -> List["Finding"]:
    """Gate the runtime witness against the static acquisition graph:
    zero runtime inversions, and every witnessed edge between locks the
    static model knows must be a static edge (else the static engine's
    graph is missing a real ordering and its inversion verdicts are not
    trustworthy)."""
    from .findings import Finding

    findings: List[Finding] = []
    site_to_lock: Dict[_Site, str] = {
        tuple(site): lock_id
        for site, lock_id in static_graph["locks"].items()
    }
    static_edges = {
        (a, b) for (a, b) in static_graph["edges"]
    }

    for inv in witness.get("inversions", []):
        a, b = tuple(inv["a"]), tuple(inv["b"])
        findings.append(Finding(
            rule="lockdep-runtime-inversion",
            path=a[0], line=a[1],
            message=f"locks created at {a[0]}:{a[1]} and {b[0]}:{b[1]} "
                    f"were acquired in BOTH orders at runtime (thread "
                    f"{inv.get('thread', '?')}): a live deadlock window "
                    "the chaos smoke actually exercised",
            hint="impose one acquisition order for this lock pair",
            text="",
        ))

    for edge in witness.get("edges", []):
        a, b = tuple(edge["from"]), tuple(edge["to"])
        lock_a, lock_b = site_to_lock.get(a), site_to_lock.get(b)
        if lock_a is None or lock_b is None:
            continue  # lock the static model does not catalog: not a gate
        if lock_a == lock_b:
            continue
        if (lock_a, lock_b) not in static_edges:
            findings.append(Finding(
                rule="lockdep-unmodeled-edge",
                path=a[0], line=a[1],
                message=f"runtime took {lock_b} while holding {lock_a} "
                        f"({edge['count']}x), but the static acquisition "
                        "graph has no such edge: the concurrency engine "
                        "is blind to a real ordering",
                hint="teach analysis/concurrency.py to resolve the call "
                     "path that creates this edge (or file the lock "
                     "under the right owner)",
                text="",
            ))
    return findings


# rule catalog entries for --list-rules / docs
from .ast_rules import Rule  # noqa: E402  (after the stdlib-only core)

LOCKDEP_RULES: List[Rule] = [
    Rule(
        id="lockdep-runtime-inversion",
        summary="the runtime witness observed a lock pair acquired in "
                "both orders",
        rationale="Unlike the static rule this is not an approximation: "
                  "two real threads actually interleaved the pair both "
                  "ways during the chaos smoke, so the deadlock needs "
                  "only scheduling luck.",
        hint="impose one acquisition order for this lock pair",
    ),
    Rule(
        id="lockdep-unmodeled-edge",
        summary="a witnessed acquisition-order edge between known locks "
                "is missing from the static graph",
        rationale="The static inversion verdict is only as good as its "
                  "edge set; a real edge the model cannot derive means "
                  "a blind spot every static 'no cycle' claim inherits.",
        hint="extend the concurrency engine's call resolution to cover "
             "the path that creates this edge",
    ),
]
