"""Step-cache-key coherence prover (bagua-lint v2).

The trainer caches compiled step functions by ``BaguaTrainer._step_key()``
(core/backend.py): every knob that changes the TRACED program must appear in
that key, or a knob flip silently reuses a stale compiled step — the wrong
program running at full speed.  PR 17's drive-found bug was exactly this
class: ``BAGUA_TOPK_RATIO`` was read once at import time by the codec
singleton, so a value set before trainer construction never reached the key
and the compiled payload shapes froze at the registry default.

This engine proves key coherence statically.  It enumerates the knob
sources that can change the traced program *after* trainer construction:

* **env accessors** reached by the step-construction closure (environment
  variables can flip between steps — tests and the autotune service do);
* **trainer attributes mutated by the autotune recommendation path**
  (``_apply_recommendation`` and the methods it calls) that the closure
  reads.  Constructor-frozen attributes are trace-invariant by construction
  — the step cache lives on the trainer instance, so a value fixed at
  ``__init__`` can never go stale — and are exempt without annotation.

It then extracts the key composition from ``_step_key`` (expanding the
helper methods it calls, e.g. ``_overlap_active``) and reports
``trace-knob-not-keyed`` for every knob source that reaches traced-step
construction without riding the key.  Knobs that genuinely do not alter the
traced program (host-side wiring the closure over-approximates into scope)
carry an explicit annotation::

    self.thing = env.get_thing()  # bagua: trace-invariant[get_thing] -- why

The annotation names an env accessor, the raw ``BAGUA_*`` variable, or the
attribute; like lint suppressions, the ``-- reason`` is mandatory
(``bad-trace-invariant`` otherwise).  The anchor class is located
structurally — the class defining both ``_step_key`` and ``_make_step_fn``
— so the engine runs unchanged on synthetic fixtures.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .ast_rules import Rule, _dotted
from .findings import Finding
from .concurrency import (
    _METHOD_STOPLIST,
    FuncInfo,
    Program,
    build_program,
)
from .suppressions import is_suppressed

#: ``# bagua: trace-invariant[name] -- reason``
_ANNOT_RE = re.compile(
    r"#\s*bagua:\s*trace-invariant\[([^\]]*)\]\s*(?:--\s*(\S.*))?"
)

#: the typed read primitives of the env registry: a module function whose
#: body calls one of these with a BAGUA_* literal is an env accessor
_ENV_PRIMITIVES = frozenset({
    "env_int", "env_float", "env_bool", "env_enum", "env_str",
    "env_seconds_or_off", "_raw",
})

#: modules the step-construction closure does NOT follow into: the
#: observability/coordination planes are host-side by construction (their
#: env knobs shape exporters and watchdogs, never the traced program), and
#: following them would drag every BAGUA_OBS_* accessor into scope
_PRUNE_SEGMENTS = (
    "/obs/", "/elastic/", "/serve/", "/service/",
    "telemetry.py", "watchdog.py", "autopilot",
)

#: expansion cap for unresolved attribute calls — a method name defined on
#: more than this many classes is too ambiguous to follow
_FALLBACK_FANOUT_CAP = 8


def _pruned(path: str) -> bool:
    return any(seg in path or path.endswith(seg.lstrip("/"))
               for seg in _PRUNE_SEGMENTS)


# ---- annotations -----------------------------------------------------------


def collect_annotations(
    p: Program,
) -> Tuple[Set[str], List[Finding]]:
    """Scan every module for trace-invariant annotations.  Returns the set
    of annotated names and the malformed-annotation findings."""
    names: Set[str] = set()
    problems: List[Finding] = []
    for path, mod in p.modules.items():
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(mod.source).readline))
        except (tokenize.TokenizeError, SyntaxError, IndentationError):
            continue
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ANNOT_RE.search(tok.string)
            if not m:
                continue
            lineno, line = tok.start[0], tok.line.rstrip("\n")
            declared = {n.strip() for n in m.group(1).split(",")
                        if n.strip()}
            reason = (m.group(2) or "").strip()
            if not declared or not reason:
                problems.append(Finding(
                    rule="bad-trace-invariant", path=path, line=lineno,
                    message="malformed trace-invariant: need at least one "
                            "knob name and a `-- reason`",
                    hint="write `# bagua: trace-invariant[name] -- why "
                         "this knob cannot change the traced program`",
                    text=line.strip(),
                ))
                continue
            names.update(declared)
    return names, problems


# ---- env accessor discovery ------------------------------------------------


def _env_accessors(p: Program) -> Dict[str, str]:
    """qualname of accessor function -> BAGUA_* variable it reads."""
    out: Dict[str, str] = {}
    for q, fn in p.funcs.items():
        if fn.cls is not None or q != f"{fn.path}::{fn.name}":
            continue
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d.split(".")[-1] not in _ENV_PRIMITIVES:
                continue
            for arg in node.args[:1]:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value.startswith("BAGUA_"):
                    out[q] = arg.value
    return out


# ---- anchor class ----------------------------------------------------------


def _find_anchor(p: Program) -> Optional[Tuple[str, str]]:
    """(module path, class name) of the class defining both ``_step_key``
    and ``_make_step_fn``."""
    for path, mod in p.modules.items():
        for cls, methods in mod.class_methods.items():
            if "_step_key" in methods and "_make_step_fn" in methods:
                return path, cls
    return None


def _class_closure(p: Program, path: str, cls: str,
                   start: str) -> Set[str]:
    """Transitive same-class method closure from one method (used to
    expand ``_step_key``'s helpers and ``_apply_recommendation``'s)."""
    prefix = f"{path}::{cls}."
    seen: Set[str] = set()
    stack = [start]
    while stack:
        q = stack.pop()
        if q in seen or q not in p.funcs:
            continue
        seen.add(q)
        for callee in p.callees.get(q, ()):
            if callee.startswith(prefix):
                stack.append(callee)
    return seen


def _self_attr_reads(fn: FuncInfo) -> Set[str]:
    """Dotted ``self.X`` / ``self.X.Y`` attribute paths loaded in a
    method (depth 2)."""
    out: Set[str] = set()
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Attribute):
            continue
        parts: List[str] = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id == "self" and parts:
            parts.reverse()
            out.add(parts[0])
            if len(parts) >= 2:
                out.add(".".join(parts[:2]))
    return out


def _self_attr_writes(fn: FuncInfo) -> Set[Tuple[str, int]]:
    """(dotted attr path, line) for ``self.X[.Y] = ...`` assignments,
    including ``setattr(self, "X", ...)`` with a literal name."""
    out: Set[Tuple[str, int]] = set()
    for node in ast.walk(fn.node):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Call) and \
                _dotted(node.func) == "setattr" and \
                len(node.args) >= 2 and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id == "self" and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            out.add((node.args[1].value, node.lineno))
            continue
        for t in targets:
            parts: List[str] = []
            cur: ast.AST = t
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name) and cur.id == "self" and parts:
                parts.reverse()
                out.add((".".join(parts[:2]), t.lineno))
    return out


# ---- the step-construction closure -----------------------------------------


def _construction_closure(
    p: Program, start: str,
) -> Dict[str, Tuple[str, int, str]]:
    """BFS from ``_make_step_fn`` over the call graph, with the
    trace-engine extras: unresolved attribute calls expand to every
    same-named method (capped), and pruned host-side modules are not
    followed.  Returns {qualname: (witness path, line, chain)}."""
    seen: Dict[str, Tuple[str, int, str]] = {}
    fn0 = p.funcs[start]
    queue: List[Tuple[str, str]] = [(start, fn0.name)]
    seen[start] = (fn0.path, fn0.line, fn0.name)
    while queue:
        q, chain = queue.pop(0)
        fn = p.funcs[q]
        for ev in fn.events:
            if ev.kind != "call":
                continue
            targets = list(ev.targets)
            if not targets and ev.desc and \
                    ev.desc not in _METHOD_STOPLIST and len(ev.desc) >= 4:
                hits = p.method_index.get(ev.desc, [])
                if 1 <= len(hits) <= _FALLBACK_FANOUT_CAP:
                    targets = hits
            for t in targets:
                if t in seen or t not in p.funcs:
                    continue
                tf = p.funcs[t]
                if _pruned(tf.path):
                    continue
                link = f"{chain} -> {tf.name}"
                seen[t] = (fn.path, ev.line, link)
                queue.append((t, link))
    return seen


# ---- engine ----------------------------------------------------------------


def run_trace_coherence(
    paths: Optional[Iterable[str]] = None,
    rel_to: Optional[str] = None,
    program: Optional[Program] = None,
    sources: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    if program is None:
        program = build_program(paths, rel_to=rel_to, sources=sources)
    findings = _raw_trace_findings(program)
    out: List[Finding] = []
    for f in findings:
        if not is_suppressed(f, program.suppressions.get(f.path, {})):
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def _raw_trace_findings(p: Program) -> List[Finding]:
    annotated, findings = collect_annotations(p)
    anchor = _find_anchor(p)
    if anchor is None:
        return findings
    path, cls = anchor
    prefix = f"{path}::{cls}."
    accessors = _env_accessors(p)

    # -- the key composition.  Attribute coverage comes from _step_key and
    # its same-class helpers; env coverage is INTERPROCEDURAL (the same BFS
    # as the construction closure) because a knob can ride the key through
    # a helper's value — e.g. armed fault-spec signatures derived from the
    # BAGUA_FAULT_PLAN read inside faults/inject.
    key_attrs: Set[str] = set()
    key_env: Set[str] = set()
    for q in _class_closure(p, path, cls, f"{prefix}_step_key"):
        key_attrs |= _self_attr_reads(p.funcs[q])
    for q in _construction_closure(p, f"{prefix}_step_key"):
        fn = p.funcs.get(q)
        if fn is None:
            continue
        for ev in fn.events:
            if ev.kind == "call":
                key_env.update(t for t in ev.targets if t in accessors)

    # -- the step-construction closure
    closure = _construction_closure(p, f"{prefix}_make_step_fn")

    # env accessors the closure reaches
    env_hits: Dict[str, Tuple[str, int, str]] = {}
    for q, (wpath, wline, chain) in closure.items():
        for ev in p.funcs[q].events:
            if ev.kind != "call":
                continue
            for t in ev.targets:
                if t in accessors and t not in env_hits:
                    env_hits[t] = (p.funcs[q].path, ev.line,
                                   f"{chain} -> {p.funcs[t].name}")

    rule = _rule("trace-knob-not-keyed")
    for acc, (wpath, wline, chain) in sorted(env_hits.items()):
        if acc in key_env:
            continue
        var = accessors[acc]
        acc_name = p.funcs[acc].name
        if {var, acc_name} & annotated:
            continue
        findings.append(Finding(
            rule=rule.id, path=wpath, line=wline,
            message=f"{var} (via {acc_name}) feeds traced-step "
                    f"construction ({chain}) but does not ride "
                    "_step_key: an env flip reuses a stale compiled "
                    "step",
            hint=rule.hint,
            text=_line_text(p, wpath, wline),
        ))

    # -- mutable trainer attrs: the autotune recommendation path
    rec = f"{prefix}_apply_recommendation"
    mutable: Dict[str, Tuple[str, int]] = {}
    if rec in p.funcs:
        for q in _class_closure(p, path, cls, rec):
            for attr, line in _self_attr_writes(p.funcs[q]):
                mutable.setdefault(attr, (p.funcs[q].path, line))

    # attrs the construction closure reads (anchor-class methods only)
    closure_attrs: Set[str] = set()
    for q in closure:
        if q.startswith(prefix):
            closure_attrs |= _self_attr_reads(p.funcs[q])

    for attr, (wpath, wline) in sorted(mutable.items()):
        base = attr.split(".")[0]
        if attr not in closure_attrs and base not in closure_attrs:
            continue  # mutated but never read during step construction
        if attr in key_attrs or (("." in attr) and base in key_attrs):
            continue
        if {attr, base} & annotated:
            continue
        findings.append(Finding(
            rule=rule.id, path=wpath, line=wline,
            message=f"self.{attr} is mutated by the autotune "
                    "recommendation path and read during traced-step "
                    "construction but does not ride _step_key: the "
                    "recommendation silently reuses a stale compiled "
                    "step",
            hint=rule.hint,
            text=_line_text(p, wpath, wline),
        ))

    return findings


def _line_text(p: Program, path: str, line: int) -> str:
    mod = p.modules.get(path)
    if mod is None:
        return ""
    lines = mod.source.splitlines()
    return lines[line - 1].strip() if 1 <= line <= len(lines) else ""


def _rule(rule_id: str) -> Rule:
    for r in TRACE_RULES:
        if r.id == rule_id:
            return r
    raise KeyError(rule_id)


TRACE_RULES: List[Rule] = [
    Rule(
        id="trace-knob-not-keyed",
        summary="a knob (env accessor or autotune-mutable trainer attr) "
                "feeds traced-step construction but is absent from "
                "_step_key",
        rationale="The step cache returns a compiled program for the key; "
                  "a knob that shapes the trace without riding the key "
                  "means a flip reuses a stale program — the PR 17 "
                  "BAGUA_TOPK_RATIO freeze, where changed payload shapes "
                  "never retraced.",
        hint="add the knob (or the value derived from it) to _step_key, "
             "or annotate the read site `# bagua: trace-invariant[name] "
             "-- reason` if it provably cannot alter the traced program",
    ),
    Rule(
        id="bad-trace-invariant",
        summary="malformed trace-invariant annotation (missing knob name "
                "or `-- reason`)",
        rationale="An unexplained invariant claim is indistinguishable "
                  "from silencing the prover; the reason is the review "
                  "surface.",
        hint="write `# bagua: trace-invariant[name] -- why this knob "
             "cannot change the traced program`",
    ),
]
