"""``bagua-lint`` CLI: ``python -m bagua_tpu.analysis [paths...]``.

Runs the selected engines (``--engine ast,jaxpr,concurrency,trace`` —
default all) over the given paths (default: the installed ``bagua_tpu``
package), compares against the shrink-only baseline, and exits non-zero on
any unsuppressed, unbaselined finding — the CI gate wired into
``scripts/ci.sh``:

* ``ast`` — per-module hot-path hygiene rules;
* ``jaxpr`` — the collective-consistency sweep over the algorithm families;
* ``concurrency`` — the whole-program host-concurrency race detector
  (lock-order inversions, unguarded shared writes, lock-held IO, …);
* ``trace`` — the step-cache-key coherence prover.

``--witness FILE`` additionally cross-checks a runtime lockdep witness
(produced by a ``BAGUA_LOCKDEP=on`` run) against the static acquisition
graph: zero runtime inversions and no witnessed edge the static model
misses.

The jaxpr sweep needs a device mesh; the CLI forces the same 8-way virtual
CPU mesh the test harness uses (``xla_force_host_platform_device_count``),
so results are deterministic on any machine, TPU or not.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .ast_rules import RULES, run_ast_rules
from .findings import (
    BASELINE_DEFAULT,
    Finding,
    load_baseline,
    save_baseline,
    split_by_baseline,
)


def _ensure_cpu_sim() -> None:
    """Pin the 8-device cpu-sim mesh BEFORE any jax backend initializes
    (same mechanism as tests/conftest.py and the launcher's dryrun)."""
    os.environ.setdefault("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    from .. import env

    env.sanitize_cpu_sim_env(os.environ)


def _default_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg]


_ENGINES = ("ast", "jaxpr", "concurrency", "trace")


def _parse_engines(spec: str) -> List[str]:
    names = [e.strip() for e in spec.split(",") if e.strip()]
    if "all" in names:
        return list(_ENGINES)
    bad = [e for e in names if e not in _ENGINES]
    if bad:
        raise SystemExit(
            f"bagua-lint: unknown engine(s) {', '.join(bad)} "
            f"(choose from {', '.join(_ENGINES)}, or 'all')"
        )
    return names


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "python -m bagua_tpu.analysis",
        description="bagua-lint: jaxpr collective-consistency checker + "
                    "AST hot-path analyzer",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: bagua_tpu/)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: ./{BASELINE_DEFAULT} "
                         "when present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline with the current findings "
                         "(shrink-only workflow: run after fixing entries)")
    ap.add_argument("--engine", default="all",
                    help="comma-separated engines to run: "
                         f"{','.join(_ENGINES)} or 'all' (default)")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the jaxpr consistency sweep (alias for "
                         "removing 'jaxpr' from --engine)")
    ap.add_argument("--jaxpr-only", action="store_true",
                    help="run only the jaxpr consistency sweep (alias for "
                         "--engine jaxpr)")
    ap.add_argument("--witness", default=None, metavar="FILE",
                    help="runtime lockdep witness JSON (from a "
                         "BAGUA_LOCKDEP=on run) to cross-check against "
                         "the static lock graph")
    ap.add_argument("--families", default=None,
                    help="comma-separated algorithm families for the jaxpr "
                         "sweep; a ':hier' suffix traces the hierarchical "
                         "two-level construction on a 2-slice mesh "
                         "(default: gradient_allreduce,zero,bytegrad plus "
                         "their :hier variants)")
    ap.add_argument("--accum-steps", default=None,
                    help="comma-separated accum_steps for the sweep "
                         "(default: 1,4)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no per-trace progress")
    args = ap.parse_args(argv)

    if args.list_rules:
        from .concurrency import CONCURRENCY_RULES
        from .lockdep import LOCKDEP_RULES
        from .trace_coherence import TRACE_RULES

        for title, rules in (
            ("ast", RULES),
            ("concurrency", CONCURRENCY_RULES),
            ("trace", TRACE_RULES),
            ("lockdep witness", LOCKDEP_RULES),
        ):
            print(f"-- {title} --")
            for r in rules:
                print(f"{r.id}: {r.summary}")
                print(f"    why:  {r.rationale}")
                print(f"    hint: {r.hint}")
        print("-- jaxpr --")
        print("cond-collective-divergence: cond/switch branches issue "
              "different collective sequences (jaxpr checker)")
        print("unbound-mesh-axis: collective axis not bound on the declared "
              "mesh (jaxpr checker)")
        print("overlap-serialized-divergence: overlap and serialized step "
              "constructions emit different collective multisets "
              "(jaxpr checker)")
        return 0

    engines = _parse_engines(args.engine)
    if args.jaxpr_only:
        engines = ["jaxpr"]
    if args.no_jaxpr:
        engines = [e for e in engines if e != "jaxpr"]

    findings: List[Finding] = []
    paths = args.paths or _default_paths()

    if "ast" in engines:
        findings.extend(run_ast_rules(paths))

    program = None
    if "concurrency" in engines or "trace" in engines or args.witness:
        from .concurrency import build_program

        program = build_program(paths)

    if "concurrency" in engines:
        from .concurrency import run_concurrency_rules

        findings.extend(run_concurrency_rules(program=program))

    if "trace" in engines:
        from .trace_coherence import run_trace_coherence

        findings.extend(run_trace_coherence(program=program))

    if args.witness:
        from .concurrency import static_lock_graph
        from .lockdep import cross_check, load_witness

        findings.extend(
            cross_check(load_witness(args.witness),
                        static_lock_graph(program))
        )

    if "jaxpr" in engines:
        _ensure_cpu_sim()
        from .jaxpr_check import (
            DEFAULT_ACCUM_STEPS,
            DEFAULT_FAMILIES,
            run_jaxpr_checks,
        )

        families = (
            tuple(f for f in args.families.split(",") if f)
            if args.families else DEFAULT_FAMILIES
        )
        accum = (
            tuple(int(a) for a in args.accum_steps.split(",") if a)
            if args.accum_steps else DEFAULT_ACCUM_STEPS
        )
        jaxpr_findings, reports = run_jaxpr_checks(families, accum)
        findings.extend(jaxpr_findings)
        if not args.quiet:
            for rep in reports:
                status = "OK " if rep.get("equal") else "FAIL"
                ser = rep["serialized"]["total_wire_bytes"]
                ovl = rep["overlap"]["total_wire_bytes"]
                n = len(rep["serialized"]["collectives"])
                print(
                    f"jaxpr[{status}] {rep['family']} "
                    f"accum={rep['accum_steps']}: {n} collectives, "
                    f"wire bytes serialized={ser} overlap={ovl}"
                )
                for row in rep["serialized"]["buckets"]:
                    print(
                        f"    bucket {row['bucket']}: flat "
                        f"{row['flat_bytes']} B -> {row['wire_bytes']} B on "
                        f"the wire across {len(row['collectives'])} "
                        "collectives"
                    )

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(BASELINE_DEFAULT):
        baseline_path = BASELINE_DEFAULT

    if args.write_baseline:
        out = baseline_path or BASELINE_DEFAULT
        save_baseline(out, findings)
        print(f"wrote {len(findings)} baseline entries to {out}")
        return 0

    stale: List = []
    baselined: List[Finding] = []
    if baseline_path:
        new, baselined, stale = split_by_baseline(
            findings, load_baseline(baseline_path)
        )
    else:
        new = findings

    for f in new:
        print(f.render())

    print(
        f"bagua-lint: {len(new)} finding(s)"
        + (f", {len(baselined)} baselined" if baselined else "")
        + (f", {len(stale)} STALE baseline entr(y/ies)" if stale else "")
    )
    if stale:
        for k in stale:
            print(f"  stale baseline entry (violation fixed — prune it): {k}")
        print(f"  shrink the baseline: python -m bagua_tpu.analysis "
              f"--write-baseline --baseline {baseline_path}")
    return 1 if (new or stale) else 0
