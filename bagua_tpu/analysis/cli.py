"""``bagua-lint`` CLI: ``python -m bagua_tpu.analysis [paths...]``.

Runs the AST rule engine over the given paths (default: the installed
``bagua_tpu`` package) and the jaxpr collective-consistency sweep over the
algorithm families, compares against the shrink-only baseline, and exits
non-zero on any unsuppressed, unbaselined finding — the CI gate wired into
``scripts/ci.sh``.

The jaxpr sweep needs a device mesh; the CLI forces the same 8-way virtual
CPU mesh the test harness uses (``xla_force_host_platform_device_count``),
so results are deterministic on any machine, TPU or not.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .ast_rules import RULES, run_ast_rules
from .findings import (
    BASELINE_DEFAULT,
    Finding,
    load_baseline,
    save_baseline,
    split_by_baseline,
)


def _ensure_cpu_sim() -> None:
    """Pin the 8-device cpu-sim mesh BEFORE any jax backend initializes
    (same mechanism as tests/conftest.py and the launcher's dryrun)."""
    os.environ.setdefault("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    from .. import env

    env.sanitize_cpu_sim_env(os.environ)


def _default_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "python -m bagua_tpu.analysis",
        description="bagua-lint: jaxpr collective-consistency checker + "
                    "AST hot-path analyzer",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: bagua_tpu/)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: ./{BASELINE_DEFAULT} "
                         "when present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline with the current findings "
                         "(shrink-only workflow: run after fixing entries)")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the jaxpr consistency sweep (AST rules only)")
    ap.add_argument("--jaxpr-only", action="store_true",
                    help="run only the jaxpr consistency sweep")
    ap.add_argument("--families", default=None,
                    help="comma-separated algorithm families for the jaxpr "
                         "sweep; a ':hier' suffix traces the hierarchical "
                         "two-level construction on a 2-slice mesh "
                         "(default: gradient_allreduce,zero,bytegrad plus "
                         "their :hier variants)")
    ap.add_argument("--accum-steps", default=None,
                    help="comma-separated accum_steps for the sweep "
                         "(default: 1,4)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no per-trace progress")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.id}: {r.summary}")
            print(f"    why:  {r.rationale}")
            print(f"    hint: {r.hint}")
        print("cond-collective-divergence: cond/switch branches issue "
              "different collective sequences (jaxpr checker)")
        print("unbound-mesh-axis: collective axis not bound on the declared "
              "mesh (jaxpr checker)")
        print("overlap-serialized-divergence: overlap and serialized step "
              "constructions emit different collective multisets "
              "(jaxpr checker)")
        return 0

    findings: List[Finding] = []

    if not args.jaxpr_only:
        paths = args.paths or _default_paths()
        findings.extend(run_ast_rules(paths))

    if not args.no_jaxpr:
        _ensure_cpu_sim()
        from .jaxpr_check import (
            DEFAULT_ACCUM_STEPS,
            DEFAULT_FAMILIES,
            run_jaxpr_checks,
        )

        families = (
            tuple(f for f in args.families.split(",") if f)
            if args.families else DEFAULT_FAMILIES
        )
        accum = (
            tuple(int(a) for a in args.accum_steps.split(",") if a)
            if args.accum_steps else DEFAULT_ACCUM_STEPS
        )
        jaxpr_findings, reports = run_jaxpr_checks(families, accum)
        findings.extend(jaxpr_findings)
        if not args.quiet:
            for rep in reports:
                status = "OK " if rep.get("equal") else "FAIL"
                ser = rep["serialized"]["total_wire_bytes"]
                ovl = rep["overlap"]["total_wire_bytes"]
                n = len(rep["serialized"]["collectives"])
                print(
                    f"jaxpr[{status}] {rep['family']} "
                    f"accum={rep['accum_steps']}: {n} collectives, "
                    f"wire bytes serialized={ser} overlap={ovl}"
                )
                for row in rep["serialized"]["buckets"]:
                    print(
                        f"    bucket {row['bucket']}: flat "
                        f"{row['flat_bytes']} B -> {row['wire_bytes']} B on "
                        f"the wire across {len(row['collectives'])} "
                        "collectives"
                    )

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(BASELINE_DEFAULT):
        baseline_path = BASELINE_DEFAULT

    if args.write_baseline:
        out = baseline_path or BASELINE_DEFAULT
        save_baseline(out, findings)
        print(f"wrote {len(findings)} baseline entries to {out}")
        return 0

    stale: List = []
    baselined: List[Finding] = []
    if baseline_path:
        new, baselined, stale = split_by_baseline(
            findings, load_baseline(baseline_path)
        )
    else:
        new = findings

    for f in new:
        print(f.render())

    print(
        f"bagua-lint: {len(new)} finding(s)"
        + (f", {len(baselined)} baselined" if baselined else "")
        + (f", {len(stale)} STALE baseline entr(y/ies)" if stale else "")
    )
    if stale:
        for k in stale:
            print(f"  stale baseline entry (violation fixed — prune it): {k}")
        print(f"  shrink the baseline: python -m bagua_tpu.analysis "
              f"--write-baseline --baseline {baseline_path}")
    return 1 if (new or stale) else 0
