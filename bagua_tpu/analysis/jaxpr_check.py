"""jaxpr collective-consistency checker.

Traces a step construction to its ``ClosedJaxpr`` (via
``BaguaTrainer.trace_step`` — abstract eval only, nothing compiles or runs)
and extracts every collective primitive, recursing through nested jaxprs
(``pjit``/``shard_map``/``scan``/``while``/``cond``/``custom_*``).  Three
checks, in the MPI-Checker tradition of static collective matching:

1. **axis binding** — every collective's axis name must be an axis of the
   declared mesh; an unbound name is a guaranteed trace/compile failure at
   best and a wrong-communicator reduction at worst.
2. **branch agreement** — each ``lax.cond``/``switch`` eqn's branches must
   issue the *same sequence* of collective signatures (primitive, axes,
   shape, dtype).  Under SPMD a per-rank predicate with divergent branch
   collectives is a deadlock: rank A enters a psum that rank B never posts.
   (Branch-varying non-collective compute — including ``ppermute``
   permutation tables, which move data but always post — is fine.)
3. **construction equivalence** — the overlap-streamed and serialized
   constructions of the same algorithm must emit the same MULTISET of
   collective signatures, with per-bucket byte accounting: PR 2's "one
   implementation, the paths cannot drift" claim as a checked invariant.

jax names the ``psum_scatter`` primitive ``reduce_scatter`` in jaxprs; the
extractor canonicalizes to the user-facing name.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from .findings import Finding

#: jaxpr primitive name -> canonical collective name
COLLECTIVE_PRIMS = {
    "psum": "psum",
    "psum_scatter": "psum_scatter",
    "reduce_scatter": "psum_scatter",
    "all_gather": "all_gather",
    "ppermute": "ppermute",
    "pshuffle": "ppermute",
    "all_to_all": "all_to_all",
    "pmax": "pmax",
    "pmin": "pmin",
    "pbroadcast": "pbroadcast",
}

#: families the CLI sweep proves overlap-vs-serialized equivalence for.
#: A ``:hier`` suffix traces the family's HIERARCHICAL two-level
#: construction on a 2-slice x 4-chip ('inter','intra') mesh (ISSUE 11) —
#: intra reduce-scatter, inter allreduce on the 1/intra shard, intra
#: allgather — so the consistency checks (axis binding, cond agreement,
#: overlap-vs-serialized multiset equality) cover the tiered collectives
#: too.  ``:hier-<codec>`` (and ``:hier-compressed`` = the family's
#: native/default codec) forces the COMPRESSED ring construction on the
#: DCN tier (``compress_inter=<codec>``, ISSUE 15), so the sweep also
#: certifies the quantized ppermute payloads — u8/int8/fp8 hop arrays and
#: their f32 sidecars — emit identical multisets streamed vs serialized.
#: ``bytegrad:hier`` IS the compressed construction since ISSUE 15 (its
#: DCN tier rides the minmax ring natively, and ``hier-compressed``
#: traces the identical program — the spelling stays supported for
#: ad-hoc CLI runs but is not swept twice); the forced int8/fp8 configs
#: cover the knob-forced path on the exact family.
#: ``hier-onebit_ef`` (ISSUE 17) sweeps the STATEFUL bit-packed codec:
#: the step threads the error-feedback residual through algo_state, so
#: multiset equality additionally proves the residual plumbing emits no
#: mode-dependent collectives.  (topk is not swept by default: its kk<=2
#: f32 value arrays on tiny test buckets collide with the sidecar
#: heuristic in ``_bucket_accounting`` — run it ad hoc via the CLI.)
DEFAULT_FAMILIES = ("gradient_allreduce", "zero", "bytegrad",
                    "gradient_allreduce:hier", "zero:hier", "bytegrad:hier",
                    "gradient_allreduce:hier-int8",
                    "gradient_allreduce:hier-fp8_e4m3",
                    "gradient_allreduce:hier-fp8_e5m2",
                    "gradient_allreduce:hier-onebit_ef",
                    "bytegrad:hier-onebit_ef")
DEFAULT_ACCUM_STEPS = (1, 4)


@dataclass(frozen=True)
class Collective:
    """One collective call site's signature, as SPMD matching sees it."""

    prim: str
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str

    @property
    def nbytes(self) -> int:
        import numpy as np

        n = 1
        for d in self.shape:
            n *= int(d)
        return n * np.dtype(self.dtype).itemsize

    def render(self) -> str:
        shape = "x".join(map(str, self.shape)) or "scalar"
        return (f"{self.prim}[{','.join(self.axes)}] "
                f"{shape}:{self.dtype} ({self.nbytes} B)")


def _collective_axes(params: Dict[str, Any]) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes if isinstance(a, (str,)))


def _sub_jaxprs(eqn) -> Iterator[Tuple[str, Any]]:
    """(param_name, Jaxpr) for every nested jaxpr in an eqn's params."""
    for k, v in eqn.params.items():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            inner = getattr(item, "jaxpr", item)  # ClosedJaxpr -> Jaxpr
            if hasattr(inner, "eqns"):
                yield k, inner


def _eqn_collective(eqn) -> Optional[Collective]:
    name = COLLECTIVE_PRIMS.get(eqn.primitive.name)
    if name is None:
        return None
    # signature on the PRIMARY operand: what must agree across ranks for
    # the collective to match (multi-operand psums yield one per operand)
    aval = eqn.invars[0].aval
    return Collective(
        prim=name,
        axes=_collective_axes(eqn.params),
        shape=tuple(int(d) for d in aval.shape),
        dtype=str(aval.dtype),
    )


def iter_collectives(
    jaxpr,
    on_branching: Optional[Callable] = None,
) -> Iterator[Collective]:
    """DFS over ``jaxpr`` (a ``Jaxpr`` or ``ClosedJaxpr``) yielding
    collectives in program order.  ``on_branching(eqn, branch_seqs)`` is
    invoked for every ``cond``/``switch`` eqn with the per-branch collective
    sequences (branch collectives are ALSO yielded, first branch only, so a
    multiset over a consistent program counts each site once)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        c = _eqn_collective(eqn)
        if c is not None:
            for v in eqn.invars:
                aval = getattr(v, "aval", None)
                if aval is None or not hasattr(aval, "shape"):
                    continue
                yield Collective(
                    prim=c.prim,
                    axes=c.axes,
                    shape=tuple(int(d) for d in aval.shape),
                    dtype=str(aval.dtype),
                )
            continue
        if eqn.primitive.name == "cond":  # lax.cond AND lax.switch
            branches = [
                list(iter_collectives(b, on_branching))
                for b in eqn.params["branches"]
            ]
            if on_branching is not None:
                on_branching(eqn, branches)
            if branches:
                for c in branches[0]:
                    yield c
            continue
        for _, sub in _sub_jaxprs(eqn):
            yield from iter_collectives(sub, on_branching)


def collect(jaxpr) -> Tuple[List[Collective], List[Finding]]:
    """All collectives in program order + branch-divergence findings."""
    findings: List[Finding] = []

    def on_branching(eqn, branch_seqs):
        sigs = [tuple(seq) for seq in branch_seqs]
        if len(set(sigs)) > 1:
            desc = " | ".join(
                f"branch {i}: "
                + (", ".join(c.render() for c in seq) or "(no collectives)")
                for i, seq in enumerate(sigs)
            )
            findings.append(Finding(
                rule="cond-collective-divergence",
                path="<jaxpr>",
                line=0,
                message=(
                    "cond/switch branches issue different collective "
                    f"sequences — SPMD divergence deadlocks: {desc}"
                ),
                hint="hoist the collective out of the cond, or make every "
                     "branch post the identical collective sequence",
                text=desc,
            ))

    seq = list(iter_collectives(jaxpr, on_branching))
    return seq, findings


def check_axis_binding(
    collectives: Sequence[Collective], mesh_axes: Sequence[str],
    context: str = "",
) -> List[Finding]:
    known = set(mesh_axes)
    findings = []
    for c in collectives:
        missing = [a for a in c.axes if a not in known]
        if missing:
            findings.append(Finding(
                rule="unbound-mesh-axis",
                path="<jaxpr>",
                line=0,
                message=(
                    f"{context + ': ' if context else ''}{c.render()} uses "
                    f"axis {missing} not bound on the mesh "
                    f"(axes: {sorted(known)})"
                ),
                hint="declare the axis on the trainer mesh or fix the "
                     "collective's axis_name",
                text=f"{context}:{c.prim}:{','.join(missing)}",
            ))
    return findings


# ---- construction equivalence (overlap vs serialized) --------------------


def multiset(collectives: Sequence[Collective]) -> Counter:
    return Counter(collectives)


def diff_multisets(a: Counter, b: Counter) -> str:
    lines = []
    for c in sorted(set(a) | set(b), key=lambda c: (c.prim, c.shape)):
        na, nb = a.get(c, 0), b.get(c, 0)
        if na != nb:
            lines.append(f"  {c.render()}: serialized x{na}, overlap x{nb}")
    return "\n".join(lines)


def _candidate_codecs(trainer):
    """The VARIABLE-PAYLOAD codecs this trainer could put on a wire —
    resolved from the per-link-class knobs and the algorithm's family
    defaults.  Uniform codecs (u8/int8/fp8: one payload element per input
    element) are excluded: their hop numels already sit in the
    full-precision size set."""
    from ..compression.codecs import get_codec

    names = set()
    for knob in (getattr(trainer, "compress_intra", None),
                 getattr(trainer, "compress_inter", None)):
        if knob not in (None, "auto", "off"):
            names.add(knob)
    algo = getattr(trainer, "algorithm", None)
    for attr in ("wire_codec_dcn", "wire_codec_flat"):
        name = getattr(algo, attr, None)
        if name:
            names.add(name)
    out = []
    for name in sorted(names):
        try:
            codec = get_codec(name)
        except Exception:
            continue
        if getattr(codec, "variable_payload", False):
            out.append(codec)
    return out


def _bucket_accounting(trainer, collectives: Sequence[Collective]) -> List[dict]:
    """Per-bucket byte accounting: which collectives carried each bucket's
    flat buffer (full-flat or 1/world chunk payloads, by numel match).
    Each collective is attributed to exactly ONE bucket — same-sized buckets
    split their group's matches evenly — so summing the rows never exceeds
    the trace's total wire bytes."""
    import numpy as np

    world = trainer.world_size

    def numels_of(bucket) -> Tuple[int, ...]:
        padded = bucket.padded_numel
        sizes = {padded}
        if padded % world == 0:
            sizes.add(padded // world)
        intra = getattr(trainer, "_intra", None)
        inter = getattr(trainer, "_inter", None)
        if intra is not None and inter is not None:
            # hierarchical two-level payloads: the intra-padded flat (the
            # decomposition zero-pads buckets the intra world does not
            # divide) and its 1/intra shard (the DCN-stage operand)
            ni = intra.nranks()
            ne = inter.nranks()
            p2 = -(-padded // ni) * ni
            sizes.update({p2, p2 // ni})
            # compressed-ring hop payloads (ISSUE 15): the DCN ring's
            # reduce-scatter hops carry 1/ne blocks of the shard (the
            # allgather phase forwards the whole quantized shard per hop,
            # already covered by p2 // ni above)
            shard = p2 // ni
            pe = -(-shard // ne) * ne
            sizes.update({pe, pe // ne})
        # variable-payload codecs (onebit_ef's lane-padded bit-pack, topk's
        # index/value pairs): the traced hop operand's numel is a FUNCTION
        # of the chunk numel, not equal to it — fold every candidate
        # codec's payload_numel of every full-precision size into the
        # match key so attribution stays honest when the wire is sparse.
        for codec in _candidate_codecs(trainer):
            sizes.update(codec.payload_numel(s) for s in tuple(sizes))
        return tuple(sorted(sizes))

    buckets = list(trainer._plan.buckets)
    # the codecs' f32 sidecar arrays (mn/mx or scale, 1-2 scalars per
    # hop) ride the same ppermute hops as their payload — shape (1,) in
    # the reduce-scatter phase, 0-d in the allgather phase (the encoded
    # chunk's parts are indexed down before forwarding).  Scalar psums
    # (the loss reduction) are not ppermutes, so the prim filter keeps
    # them out.  Sidecars are accounted at the trace level (every
    # bucket's hops emit them identically) rather than attributed per
    # bucket, where same-size collisions would be arbitrary.
    sidecars = [
        c for c in collectives
        if c.prim == "ppermute" and c.dtype == "float32"
        and int(np.prod(c.shape or (1,))) <= 2
    ]
    sidecar_set = set(id(c) for c in sidecars)
    # matches per size-group, then an even share per member bucket
    group_sizes = Counter(numels_of(b) for b in buckets)
    group_matches: Dict[Tuple[int, ...], List[Collective]] = {
        key: [
            c for c in collectives
            if int(np.prod(c.shape or (1,))) in key
            and id(c) not in sidecar_set
        ]
        for key in group_sizes
    }
    taken = Counter()
    rows = []
    for i, bucket in enumerate(buckets):
        key = numels_of(bucket)
        pool, n = group_matches[key], group_sizes[key]
        share = len(pool) // n + (1 if taken[key] < len(pool) % n else 0)
        start = sum(
            len(pool) // n + (1 if j < len(pool) % n else 0)
            for j in range(taken[key])
        )
        matched = pool[start:start + share]
        taken[key] += 1
        rows.append({
            "bucket": i,
            "padded_numel": int(bucket.padded_numel),
            "flat_bytes": int(
                bucket.padded_numel * np.dtype(bucket.dtype).itemsize
            ),
            "collectives": [c.render() for c in matched],
            "wire_bytes": int(sum(c.nbytes for c in matched)),
        })
    if sidecars:
        rows.append({
            "bucket": "codec_sidecars",
            "padded_numel": 0,
            "flat_bytes": 0,
            "collectives": [c.render() for c in sidecars],
            "wire_bytes": int(sum(c.nbytes for c in sidecars)),
        })
    return rows


def check_equivalence(
    family: str,
    accum_steps: int,
    trace_fn: Callable[[str], Tuple[Any, Any]],
) -> Tuple[List[Finding], dict]:
    """Trace both constructions of one family (``trace_fn(overlap_mode) ->
    (trainer, jaxpr)``) and require collective-multiset equality."""
    findings: List[Finding] = []
    report: dict = {"family": family, "accum_steps": accum_steps}
    seqs: Dict[str, List[Collective]] = {}
    for mode in ("off", "on"):
        trainer, jaxpr = trace_fn(mode)
        seq, branch_findings = collect(jaxpr)
        findings.extend(branch_findings)
        findings.extend(check_axis_binding(
            seq, trainer.mesh.axis_names,
            context=f"{family}/accum{accum_steps}/overlap={mode}",
        ))
        seqs[mode] = seq
        key = "serialized" if mode == "off" else "overlap"
        report[key] = {
            "collectives": [c.render() for c in seq],
            "total_wire_bytes": int(sum(c.nbytes for c in seq)),
            "buckets": _bucket_accounting(trainer, seq),
        }
    ser, ovl = multiset(seqs["off"]), multiset(seqs["on"])
    report["equal"] = ser == ovl
    if ser != ovl:
        findings.append(Finding(
            rule="overlap-serialized-divergence",
            path="<jaxpr>",
            line=0,
            message=(
                f"{family} (accum_steps={accum_steps}): overlap and "
                "serialized constructions emit different collective "
                f"multisets:\n{diff_multisets(ser, ovl)}"
            ),
            hint="both paths must ride Algorithm.reduce_bucket_grad — one "
                 "implementation, so they cannot drift",
            text=f"{family}:accum{accum_steps}",
        ))
    return findings, report


# ---- family harness ------------------------------------------------------


def _mlp_fixture(key_scale: float = 0.02):
    """Tiny deterministic MLP: enough params for several buckets at a small
    bucket size, divisible shapes for the 8-way cpu-sim mesh."""
    import numpy as np
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    dims = [8, 32, 32, 4]
    params = {}
    for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"w{i}"] = jnp.asarray(
            rng.randn(a, b).astype(np.float32) * key_scale)
        params[f"b{i}"] = jnp.zeros((b,), jnp.float32)

    def loss_fn(p, batch):
        x, y = batch["x"], batch["y"]
        h = x
        for i in range(len(dims) - 1):
            h = h @ p[f"w{i}"] + p[f"b{i}"]
            if i < len(dims) - 2:
                h = jnp.tanh(h)
        return jnp.mean((h - y) ** 2)

    batch = {
        "x": jnp.asarray(rng.randn(32, dims[0]).astype(np.float32)),
        "y": jnp.asarray(rng.randn(32, dims[-1]).astype(np.float32)),
    }
    return params, batch, loss_fn


def make_family_tracer(
    family: str, accum_steps: int, bucket_bytes: int = 2048
) -> Callable[[str], Tuple[Any, Any]]:
    """``trace_fn(overlap_mode) -> (trainer, ClosedJaxpr)`` for one
    algorithm family's real step builder on the ambient (cpu-sim) mesh —
    or, for a ``family:hier`` spec, the hierarchical two-level construction
    on a 2-slice x 4-chip ``('inter','intra')`` mesh.  ``family:hier-X``
    additionally forces the DCN codec policy: ``X`` a codec name sets
    ``compress_inter=X``; ``X = "compressed"`` keeps ``auto`` (the
    family's own wire codec — ByteGrad's native compressed ring)."""
    import optax

    from ..core.backend import BaguaTrainer

    base_family, _, variant = family.partition(":")
    hierarchical = variant.startswith("hier")
    compress_inter = None
    if hierarchical and variant != "hier":
        suffix = variant[len("hier-"):] if variant.startswith("hier-") else ""
        if suffix == "compressed":
            compress_inter = "auto"  # the family's native wire codec
        elif suffix:
            from ..compression.codecs import get_codec

            get_codec(suffix)  # fail fast on a typo'd spec
            compress_inter = suffix
        else:
            raise ValueError(f"unknown family variant {family!r}")
    elif variant and not hierarchical:
        raise ValueError(f"unknown family variant {family!r}")

    def build(overlap: str):
        from .. import algorithms

        params, batch, loss_fn = _mlp_fixture()
        if base_family == "gradient_allreduce":
            algo = algorithms.GradientAllReduceAlgorithm(
                hierarchical=hierarchical)
            optimizer = optax.sgd(1e-2)
        elif base_family == "bytegrad":
            algo = algorithms.ByteGradAlgorithm(hierarchical=hierarchical)
            optimizer = optax.sgd(1e-2)
        elif base_family == "zero":
            algo = algorithms.ZeroOptimizerAlgorithm(
                optax.adam(1e-3), hierarchical=hierarchical)
            optimizer = None
        else:
            raise ValueError(f"unknown family {family!r}")
        mesh = None
        if hierarchical:
            import jax

            from ..parallel.mesh import build_mesh

            n = len(jax.devices())
            mesh = build_mesh({"inter": 2, "intra": n // 2})
        trainer = BaguaTrainer(
            loss_fn,
            optimizer,
            algo,
            mesh=mesh,
            bucket_bytes=bucket_bytes,
            accum_steps=accum_steps,
            overlap=overlap,
            autotune=False,
            compress_inter=compress_inter,
        )
        state = trainer.init(params)
        return trainer, state, batch

    def trace_fn(overlap: str):
        trainer, state, batch = build(overlap)
        return trainer, trainer.trace_step(state, batch)

    return trace_fn


def run_jaxpr_checks(
    families: Sequence[str] = DEFAULT_FAMILIES,
    accum_steps: Sequence[int] = DEFAULT_ACCUM_STEPS,
) -> Tuple[List[Finding], List[dict]]:
    """The CLI/CI sweep: overlap-vs-serialized equivalence (plus axis and
    cond-branch consistency on every trace) for each family x accum."""
    findings: List[Finding] = []
    reports: List[dict] = []
    for family in families:
        for accum in accum_steps:
            f, report = check_equivalence(
                family, accum, make_family_tracer(family, accum)
            )
            findings.extend(f)
            reports.append(report)
    return findings, reports
