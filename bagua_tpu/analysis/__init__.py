"""bagua-lint: static analysis for collective-consistency and hot-path hygiene.

Bagua's premise (arXiv 2107.01499) is decoupling *what/when to communicate*
from *how* — which in this JAX rebuild means several independently-evolving
constructions of the same algorithm step (serialized, overlap-streamed,
chunked-ring).  A silent divergence in collective order, mesh-axis usage, or
``cond``-branch comm is an SPMD deadlock or a wrong-gradient bug that no
single-process test can see.  Following the MPI-Checker line of work (static
matching of collective call sites, Droste et al., LLVM-HPC 2015), this
subsystem catches that hazard class statically:

* :mod:`.jaxpr_check` — traces each algorithm family's step function through
  the trainer's abstract-eval hook (``BaguaTrainer.trace_step``), extracts
  the collective primitives, and verifies mesh-axis binding, ``cond``/
  ``switch`` branch agreement, and overlap-vs-serialized collective-multiset
  equality with per-bucket byte accounting.
* :mod:`.ast_rules` — an AST rule engine over the package source: host-sync
  calls in traced code, raw ``BAGUA_*`` env reads outside the registry,
  tracer leakage onto ``self``, nondeterministic Python RNG in traced code,
  copy-pasted helper lambdas, and torch imports.
* :mod:`.concurrency` — a whole-program host-concurrency model (thread
  roots, lock acquisition graph, shared mutable state): lock-order
  inversions, unguarded shared writes, IO under contended locks,
  signal-unsafe locking, non-reentrant re-acquisition.
* :mod:`.trace_coherence` — the step-cache-key coherence prover: every env
  knob or autotune-mutable trainer attribute that shapes the traced step
  must ride ``BaguaTrainer._step_key`` (or carry an explicit
  ``# bagua: trace-invariant[name] -- reason`` annotation).
* :mod:`.lockdep` — an opt-in (``BAGUA_LOCKDEP=on``) runtime witness that
  records real lock acquisition orders and is cross-checked against the
  static graph by ``bagua-lint --witness``.

Run as a CLI (``python -m bagua_tpu.analysis bagua_tpu/`` — the CI gate,
see ``scripts/ci.sh``) or through pytest (``tests/test_analysis.py``).
Findings carry ``path:line`` + rule id + a fix hint; suppress with
``# bagua: lint-ignore[rule-id] -- reason``; pre-existing violations live in
the shrink-only baseline ``.bagua-lint-baseline.json``.

This module stays import-light (no jax): the AST engine must run anywhere.
The jaxpr checker imports jax lazily.
"""

from .findings import Finding, load_baseline, save_baseline  # noqa: F401
from .ast_rules import RULES, run_ast_rules  # noqa: F401
from .suppressions import KNOWN_RULE_IDS, parse_suppressions  # noqa: F401
from .concurrency import (  # noqa: F401
    CONCURRENCY_RULES,
    build_program,
    run_concurrency_rules,
    static_lock_graph,
)
from .trace_coherence import TRACE_RULES, run_trace_coherence  # noqa: F401
from .lockdep import LOCKDEP_RULES, cross_check, load_witness  # noqa: F401

__all__ = [
    "Finding",
    "RULES",
    "CONCURRENCY_RULES",
    "TRACE_RULES",
    "LOCKDEP_RULES",
    "KNOWN_RULE_IDS",
    "run_ast_rules",
    "run_concurrency_rules",
    "run_trace_coherence",
    "build_program",
    "static_lock_graph",
    "cross_check",
    "load_witness",
    "parse_suppressions",
    "load_baseline",
    "save_baseline",
]
