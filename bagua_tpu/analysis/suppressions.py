"""``# bagua: lint-ignore[rule-id] -- reason`` suppression comments.

A trailing suppression covers its own line; a standalone suppression comment
covers the next non-blank, non-comment source line (so long flagged lines can
keep the suppression above them).  Multiple rule ids are comma-separated;
``*`` suppresses every rule.  The ``-- reason`` is required: an unexplained
suppression is itself reported (rule ``bad-suppression``) so "shut it up"
can't happen silently.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, FrozenSet, List, Tuple

from .findings import Finding

_SUPPRESS_RE = re.compile(
    r"#\s*bagua:\s*lint-ignore\[([^\]]*)\]\s*(?:--\s*(\S.*))?"
)


def parse_suppressions(
    path: str, source: str
) -> Tuple[Dict[int, FrozenSet[str]], List[Finding]]:
    """-> ({line: suppressed rule ids}, malformed-suppression findings)."""
    by_line: Dict[int, set] = {}
    problems: List[Finding] = []
    pending: List[Tuple[int, set]] = []  # standalone comments awaiting code

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return {}, []

    for tok in tokens:
        if tok.type in (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                        tokenize.DEDENT, tokenize.ENCODING,
                        tokenize.ENDMARKER):
            continue
        row = tok.start[0]
        if tok.type == tokenize.COMMENT:
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not rules or not reason:
                problems.append(Finding(
                    rule="bad-suppression",
                    path=path,
                    line=row,
                    message=(
                        "malformed lint-ignore: need at least one rule id "
                        "and a `-- reason`"
                    ),
                    hint="write `# bagua: lint-ignore[rule-id] -- why`",
                    text=tok.line.strip(),
                ))
                continue
            if tok.line[: tok.start[1]].strip():
                # trailing comment: covers its own line
                by_line.setdefault(row, set()).update(rules)
            else:
                # standalone: covers the next source line
                pending.append((row, rules))
        else:
            # first real token on a line consumes pending suppressions
            for _, rules in pending:
                by_line.setdefault(row, set()).update(rules)
            pending = []

    return {k: frozenset(v) for k, v in by_line.items()}, problems


def is_suppressed(
    finding: Finding, suppressions: Dict[int, FrozenSet[str]]
) -> bool:
    rules = suppressions.get(finding.line)
    return bool(rules) and (finding.rule in rules or "*" in rules)
