"""``# bagua: lint-ignore[rule-id] -- reason`` suppression comments.

A trailing suppression covers its own line; a standalone suppression comment
covers the next non-blank, non-comment source line (so long flagged lines can
keep the suppression above them).  Multiple rule ids are comma-separated;
``*`` suppresses every rule.  The ``-- reason`` is required: an unexplained
suppression is itself reported (rule ``bad-suppression``) so "shut it up"
can't happen silently.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, FrozenSet, List, Tuple

from .findings import Finding

_SUPPRESS_RE = re.compile(
    r"#\s*bagua:\s*lint-ignore\[([^\]]*)\]\s*(?:--\s*(\S.*))?"
)

#: every rule id any engine can emit (AST, jaxpr, concurrency, trace
#: coherence, lockdep witness) plus the ``*`` wildcard.  A suppression
#: naming anything else is dead weight — usually a typo'd or renamed rule
#: silently suppressing nothing — and is reported as ``bad-suppression``.
#: Kept as a literal so this module stays dependency-free;
#: ``tests/test_analysis.py`` asserts it equals the union of the engine
#: catalogs.
KNOWN_RULE_IDS: FrozenSet[str] = frozenset({
    "*",
    # ast_rules
    "host-sync-in-trace", "raw-env-read", "tracer-leak", "py-rng-in-trace",
    "dup-lambda", "per-step-reflatten", "unregistered-counter",
    "torch-import",
    # jaxpr_check
    "cond-collective-divergence", "unbound-mesh-axis",
    "overlap-serialized-divergence",
    # concurrency
    "lock-order-inversion", "unguarded-shared-write", "lock-held-io",
    "signal-unsafe-lock", "non-reentrant-reacquire",
    # trace_coherence
    "trace-knob-not-keyed", "bad-trace-invariant",
    # lockdep
    "lockdep-runtime-inversion", "lockdep-unmodeled-edge",
    # the suppression machinery's own rule
    "bad-suppression",
})


def parse_suppressions(
    path: str, source: str
) -> Tuple[Dict[int, FrozenSet[str]], List[Finding]]:
    """-> ({line: suppressed rule ids}, malformed-suppression findings)."""
    by_line: Dict[int, set] = {}
    problems: List[Finding] = []
    pending: List[Tuple[int, set]] = []  # standalone comments awaiting code

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return {}, []

    for tok in tokens:
        if tok.type in (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                        tokenize.DEDENT, tokenize.ENCODING,
                        tokenize.ENDMARKER):
            continue
        row = tok.start[0]
        if tok.type == tokenize.COMMENT:
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not rules or not reason:
                problems.append(Finding(
                    rule="bad-suppression",
                    path=path,
                    line=row,
                    message=(
                        "malformed lint-ignore: need at least one rule id "
                        "and a `-- reason`"
                    ),
                    hint="write `# bagua: lint-ignore[rule-id] -- why`",
                    text=tok.line.strip(),
                ))
                continue
            unknown = rules - KNOWN_RULE_IDS
            if unknown:
                problems.append(Finding(
                    rule="bad-suppression",
                    path=path,
                    line=row,
                    message=(
                        "lint-ignore names unknown rule id(s) "
                        f"{', '.join(sorted(unknown))}: the suppression "
                        "suppresses nothing"
                    ),
                    hint="use ids from `python -m bagua_tpu.analysis "
                         "--list-rules` (or `*`)",
                    text=tok.line.strip(),
                ))
                rules -= unknown
                if not rules:
                    continue
            if tok.line[: tok.start[1]].strip():
                # trailing comment: covers its own line
                by_line.setdefault(row, set()).update(rules)
            else:
                # standalone: covers the next source line
                pending.append((row, rules))
        else:
            # first real token on a line consumes pending suppressions
            for _, rules in pending:
                by_line.setdefault(row, set()).update(rules)
            pending = []

    return {k: frozenset(v) for k, v in by_line.items()}, problems


def is_suppressed(
    finding: Finding, suppressions: Dict[int, FrozenSet[str]]
) -> bool:
    rules = suppressions.get(finding.line)
    return bool(rules) and (finding.rule in rules or "*" in rules)
