"""Whole-program host-concurrency engine (bagua-lint v2).

Bagua's design runs communication and observability off the training loop on
background workers (arXiv 2107.01499) — in this rebuild that is a dozen
``threading.Thread`` spawns and ~30 locks across the exporter, HTTP server,
watchdog, heartbeat, flight recorder, and AOT-harvest daemons.  Every one of
the hand-fixed concurrency bugs in CHANGES.md (the SIGTERM handler dumping
through a non-reentrant lock, accounting stalling the dispatch path under the
plan lock) is an instance of a statically checkable hazard class, so this
engine checks them mechanically:

* ``lock-order-inversion`` — a cycle in the interprocedural lock-acquisition
  graph (two threads taking the same locks in opposite orders deadlock).
* ``unguarded-shared-write`` — a module global or instance attribute written
  from two or more thread roots with no single lock common to every write.
* ``lock-held-io`` — blocking IO (file/socket/subprocess/``time.sleep``)
  performed while holding a lock that other thread roots contend on through
  an IO-free region (the PR 7 class: accounting wedging the dispatch path).
* ``signal-unsafe-lock`` — a lock acquisition reachable from a signal
  handler (the handler interrupts arbitrary code, including the owner of
  that very lock: a self-deadlock no test reliably reproduces).
* ``non-reentrant-reacquire`` — re-acquiring a held non-reentrant
  ``threading.Lock``, directly or through a callee (instant deadlock).

Unlike :mod:`.ast_rules` (per-module, syntactic) this engine builds a
whole-program model: module-level and ``self.*`` lock objects, module-level
singleton instances, thread roots (``Thread(target=...)``, signal handlers),
and a call graph with a fixpoint over transitive lock/IO summaries — so a
lock taken three calls below a ``with`` block still creates an edge, with
the witness chain in the finding message.  The model is deliberately
conservative where Python defeats static resolution (attribute calls fall
back to globally-unique method names behind a stoplist); suppress the
residue with ``# bagua: lint-ignore[rule-id] -- reason``.

The runtime half of this engine is :mod:`.lockdep`: an opt-in shim that
records REAL acquisition orders during the CI chaos smoke and cross-checks
them against :func:`static_lock_graph`, so the static edges are validated
rather than speculative.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .ast_rules import Rule, _dotted, iter_py_files
from .findings import Finding
from .suppressions import is_suppressed, parse_suppressions

# ---- lock / thread vocabulary ---------------------------------------------

#: constructor dotted names that create a lock object
_LOCK_CTORS = {
    "threading.Lock": False,
    "threading.RLock": True,
    "Lock": False,
    "RLock": True,
    # a Condition IS a lock (it wraps an RLock by default and supports the
    # same context-manager protocol); writes under ``with self._cond:`` are
    # guarded writes.  ``.wait()`` releases the lock while blocking, and is
    # deliberately absent from _IO_CALLS, so condition waits don't surface
    # as lock-held-io false positives.
    "threading.Condition": True,
    "Condition": True,
}

#: constructor dotted names that spawn a background thread; the ``target``
#: becomes a thread root, NOT a call edge (it runs concurrently)
_THREAD_CTORS = ("threading.Thread", "Thread", "threading.Timer", "Timer")

#: dotted call names that block on IO (or block outright) — the payload of
#: ``lock-held-io``.  Logging is deliberately absent: flagging every
#: ``logger.warning`` under a lock would drown the signal.
_IO_CALLS = {
    "time.sleep",
    "open",
    "os.makedirs", "os.replace", "os.rename", "os.remove", "os.unlink",
    "os.fsync", "os.listdir", "os.scandir",
    "shutil.rmtree", "shutil.copy", "shutil.copytree", "shutil.move",
    "subprocess.run", "subprocess.Popen", "subprocess.check_call",
    "subprocess.check_output", "subprocess.call",
    "socket.socket", "socket.create_connection",
    "urllib.request.urlopen",
}

#: attribute-call suffixes that are IO on any plausible receiver here
_IO_METHOD_SUFFIXES = ("sendall", "recv", "accept", "makefile")

#: method names too common for the globally-unique-name call fallback —
#: resolving these across classes would fabricate call edges
_METHOD_STOPLIST = frozenset({
    "run", "start", "join", "get", "put", "set", "add", "pop", "append",
    "extend", "clear", "close", "open", "read", "write", "send", "recv",
    "update", "copy", "items", "keys", "values", "acquire", "release",
    "wait", "notify", "notify_all", "is_set", "fire", "reset", "stop",
    "flush", "submit", "result", "cancel", "info", "debug", "warning",
    "error", "exception", "critical", "log", "register", "encode",
    "decode", "strip", "split", "startswith", "endswith", "format",
    "lower", "upper", "setdefault", "mkdir", "exists", "dump", "load",
    "loads", "dumps", "sleep", "name", "render", "check", "match",
    "search", "sub", "group", "count", "index", "sort", "reverse",
    "insert", "remove", "snapshot", "signature", "init", "step",
})

#: the implicit foreground root: anything callable from user/training code
MAIN_ROOT = "main"


# ---- model dataclasses -----------------------------------------------------


@dataclass(frozen=True)
class LockDef:
    """One lock object: a module-level ``NAME = threading.Lock()`` or a
    ``self.attr = threading.Lock()`` shared by every instance of a class.
    ``site`` is the (path, lineno) of the ``Lock()`` call itself — the same
    frame the runtime :mod:`.lockdep` shim keys its witness on."""

    lock_id: str            # "path::NAME" or "path::Class.attr"
    path: str
    line: int
    reentrant: bool

    @property
    def site(self) -> Tuple[str, int]:
        return (self.path, self.line)


@dataclass
class _Event:
    """One acquisition / IO / call event inside a function body, with the
    lexically-held lock set at that point."""

    kind: str               # "acquire" | "io" | "call"
    line: int
    held: Tuple[str, ...]   # lock_ids held lexically (outermost first)
    lock_id: Optional[str] = None       # acquire
    region: bool = False                # acquire via `with` (lexical region)
    desc: Optional[str] = None          # io: dotted call name
    targets: Tuple[str, ...] = ()       # call: resolved callee qualnames


@dataclass
class FuncInfo:
    qualname: str           # "path::name" / "path::Class.name" / nested "a.b"
    path: str
    name: str
    cls: Optional[str]
    node: ast.AST
    line: int
    events: List[_Event] = field(default_factory=list)
    #: (attr-or-global key, line, held) for shared-state writes
    writes: List[Tuple[str, int, Tuple[str, ...]]] = field(
        default_factory=list)


@dataclass
class _Module:
    path: str
    tree: ast.Module
    source: str
    #: module-level lock names -> LockDef
    locks: Dict[str, LockDef] = field(default_factory=dict)
    #: class name -> {attr: LockDef} for self.attr locks
    class_locks: Dict[str, Dict[str, LockDef]] = field(default_factory=dict)
    #: class name -> set of method names
    class_methods: Dict[str, Set[str]] = field(default_factory=dict)
    #: module-level `x = ClassName(...)` -> class key ("path::Class")
    instances: Dict[str, str] = field(default_factory=dict)
    #: module-level names whose assignment RHS instantiates classes:
    #: name -> [__init__ qualnames] (the import-time-singleton edge)
    ctor_vars: Dict[str, List[str]] = field(default_factory=dict)
    #: local name -> ("module", modpath) or ("name", modpath, origname)
    imports: Dict[str, Tuple] = field(default_factory=dict)
    #: module function names (top level)
    functions: Set[str] = field(default_factory=set)
    classes: Set[str] = field(default_factory=set)


@dataclass
class Program:
    """The resolved whole-program model shared by the concurrency and
    trace-coherence engines."""

    modules: Dict[str, _Module] = field(default_factory=dict)
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    locks: Dict[str, LockDef] = field(default_factory=dict)
    #: method name -> [qualnames] across every class (unique-name fallback)
    method_index: Dict[str, List[str]] = field(default_factory=dict)
    #: thread roots: root label -> target qualname
    thread_roots: Dict[str, str] = field(default_factory=dict)
    #: signal-handler roots: root label -> handler qualname, with the
    #: registration site for the finding anchor
    signal_roots: Dict[str, Tuple[str, str, int]] = field(
        default_factory=dict)
    #: per-path suppression maps (parsed once)
    suppressions: Dict[str, Dict[int, FrozenSet[str]]] = field(
        default_factory=dict)
    suppression_problems: List[Finding] = field(default_factory=list)

    # summaries (filled by _summarize)
    callees: Dict[str, Set[str]] = field(default_factory=dict)
    #: transitive lock acquisitions: qualname -> {lock_id: witness chain}
    acquired: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: transitive IO: qualname -> witness chain (or absent)
    io: Dict[str, str] = field(default_factory=dict)
    #: roots reaching each function (bg labels + MAIN_ROOT)
    roots: Dict[str, Set[str]] = field(default_factory=dict)


# ---- module scan -----------------------------------------------------------


def _module_key(path: str) -> str:
    """Import key for cross-module resolution: posix path sans .py."""
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p


def _scan_module(path: str, source: str) -> Optional[_Module]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    mod = _Module(path=path, tree=tree, source=source)

    # imports anywhere in the module (function-level deferred imports are
    # idiomatic here for cycle-breaking); first binding of a name wins so a
    # top-level import is never shadowed by a different nested one
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                mod.imports.setdefault(
                    local, ("module", alias.name.replace(".", "/")))
        elif isinstance(node, ast.ImportFrom):
            src = _resolve_from_import(path, node)
            if src is None:
                continue
            for alias in node.names:
                local = alias.asname or alias.name
                mod.imports.setdefault(local, ("name", src, alias.name))

    for node in tree.body:
        if isinstance(node, ast.FunctionDef) or \
                isinstance(node, ast.AsyncFunctionDef):
            mod.functions.add(node.name)
        elif isinstance(node, ast.ClassDef):
            mod.classes.add(node.name)
            mod.class_methods[node.name] = {
                n.name for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if isinstance(node.value, ast.Call):
                ctor = _dotted(node.value.func)
                if ctor in _LOCK_CTORS:
                    mod.locks[name] = LockDef(
                        lock_id=f"{path}::{name}", path=path,
                        line=node.value.lineno,
                        reentrant=_LOCK_CTORS[ctor],
                    )
    return mod


def _resolve_from_import(path: str, node: ast.ImportFrom) -> Optional[str]:
    """``from ..x import y`` in ``pkg/a/b.py`` -> "pkg/x" (posix module
    key); absolute imports pass through as dotted->slashed."""
    if node.level == 0:
        return node.module.replace(".", "/") if node.module else None
    base = _module_key(path).split("/")
    # level=1 strips the module name itself, each extra level one package
    base = base[: len(base) - node.level]
    if node.module:
        base += node.module.split(".")
    return "/".join(base) if base else None


# ---- function-body scan ----------------------------------------------------


class _Scope:
    """Name-resolution scope for one function: enclosing nested defs, the
    class (if a method), and the module."""

    def __init__(self, program: "Program", mod: _Module,
                 cls: Optional[str], nested: Dict[str, str]):
        self.program = program
        self.mod = mod
        self.cls = cls
        self.nested = nested  # local def name -> qualname


class _Builder:
    def __init__(self, paths: Iterable[str], rel_to: Optional[str] = None):
        self.program = Program()
        base = os.path.abspath(rel_to or os.getcwd())
        self._files: List[Tuple[str, str]] = []
        for fp in iter_py_files(paths):
            rel = os.path.relpath(os.path.abspath(fp), base)
            rel = rel.replace(os.sep, "/")
            with open(fp, encoding="utf-8") as fh:
                self._files.append((rel, fh.read()))

    def add_source(self, path: str, source: str) -> None:
        self._files.append((path, source))

    # -- pass 1: modules, locks, classes, imports
    def build(self) -> Program:
        p = self.program
        by_key: Dict[str, _Module] = {}
        for path, source in self._files:
            mod = _scan_module(path, source)
            if mod is None:
                continue
            p.modules[path] = mod
            by_key[_module_key(path)] = mod
            sup, problems = parse_suppressions(path, source)
            p.suppressions[path] = sup
            p.suppression_problems.extend(problems)
        self._by_key = by_key

        # class-level locks + instance map need imports resolved first
        for mod in p.modules.values():
            self._scan_class_locks(mod)
            self._scan_module_instances(mod)
        for mod in p.modules.values():
            for lock in mod.locks.values():
                p.locks[lock.lock_id] = lock
            for attr_locks in mod.class_locks.values():
                for lock in attr_locks.values():
                    p.locks[lock.lock_id] = lock

        # method index for the unique-name fallback
        for mod in p.modules.values():
            for cls, methods in mod.class_methods.items():
                for m in methods:
                    p.method_index.setdefault(m, []).append(
                        f"{mod.path}::{cls}.{m}")

        # -- pass 2: function bodies
        for mod in p.modules.values():
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_function(mod, None, node, f"{mod.path}::")
                elif isinstance(node, ast.ClassDef):
                    # request-handler classes run their handle methods on
                    # server threads (socketserver.ThreadingTCPServer /
                    # ThreadingHTTPServer): those methods are thread roots
                    handler_base = any(
                        "Handler" in (_dotted(b) or "")
                        for b in node.bases
                    )
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self._scan_function(
                                mod, node.name, sub,
                                f"{mod.path}::{node.name}.")
                            if handler_base and (
                                sub.name == "handle"
                                or sub.name.startswith("do_")
                            ):
                                q = f"{mod.path}::{node.name}.{sub.name}"
                                p.thread_roots[f"thread:{q}"] = q
        _summarize(p)
        return p

    def _scan_class_locks(self, mod: _Module) -> None:
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            locks: Dict[str, LockDef] = {}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and isinstance(sub.value, ast.Call)
                    ):
                        ctor = _dotted(sub.value.func)
                        if ctor in _LOCK_CTORS:
                            locks[t.attr] = LockDef(
                                lock_id=f"{mod.path}::{node.name}.{t.attr}",
                                path=mod.path, line=sub.value.lineno,
                                reentrant=_LOCK_CTORS[ctor],
                            )
            if locks:
                mod.class_locks[node.name] = locks

    def _scan_module_instances(self, mod: _Module) -> None:
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            ctors: List[str] = []
            for call in ast.walk(node.value):
                if not isinstance(call, ast.Call):
                    continue
                cls_key = self._resolve_class(mod, call.func)
                if cls_key:
                    ctors.append(f"{cls_key}.__init__")
                    if isinstance(node.value, ast.Call) and call is node.value:
                        mod.instances[name] = cls_key
            if ctors:
                mod.ctor_vars[name] = ctors

    def _resolve_class(self, mod: _Module, func: ast.AST) -> Optional[str]:
        """Resolve a constructor expression to "path::Class" if the class
        is defined in a parsed module."""
        d = _dotted(func)
        if not d:
            return None
        head, _, rest = d.partition(".")
        if not rest and head in mod.classes:
            return f"{mod.path}::{head}"
        imp = mod.imports.get(head)
        if imp is None:
            return None
        if imp[0] == "name" and not rest:
            target = self._by_key.get(imp[1])
            if target and imp[2] in target.classes:
                return f"{target.path}::{imp[2]}"
        elif imp[0] == "module" and rest and "." not in rest:
            target = self._by_key.get(imp[1])
            if target and rest in target.classes:
                return f"{target.path}::{rest}"
        return None

    # -- function scanning

    def _scan_function(self, mod: _Module, cls: Optional[str],
                       node: ast.AST, prefix: str,
                       nested_scope: Optional[Dict[str, str]] = None) -> None:
        qualname = f"{prefix}{node.name}"
        fn = FuncInfo(qualname=qualname, path=mod.path, name=node.name,
                      cls=cls, node=node, line=node.lineno)
        self.program.funcs[qualname] = fn
        nested: Dict[str, str] = dict(nested_scope or {})
        # pre-register nested defs so forward references resolve
        for sub in node.body:
            for inner in ast.walk(sub):
                if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.setdefault(inner.name, f"{qualname}.{inner.name}")
        scope = _Scope(self.program, mod, cls, nested)
        globals_declared: Set[str] = {
            g for sub in ast.walk(node) if isinstance(sub, ast.Global)
            for g in sub.names
        }
        self._scan_body(fn, scope, node.body, (), globals_declared)
        # nested defs get their own FuncInfo (fresh held set — they run when
        # called, not where defined); calls to them resolve via `nested`
        for sub in node.body:
            for inner in ast.walk(sub):
                if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and nested.get(inner.name) == \
                        f"{qualname}.{inner.name}":
                    self._scan_function(mod, cls, inner, f"{qualname}.",
                                        nested)

    def _scan_body(self, fn: FuncInfo, scope: _Scope, body: List[ast.stmt],
                   held: Tuple[str, ...], globals_declared: Set[str]) -> None:
        for stmt in body:
            self._scan_stmt(fn, scope, stmt, held, globals_declared)

    def _scan_stmt(self, fn: FuncInfo, scope: _Scope, stmt: ast.stmt,
                   held: Tuple[str, ...], globals_declared: Set[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # scanned separately with a fresh held set
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                self._scan_expr(fn, scope, item.context_expr, new_held,
                                globals_declared)
                lock = self._resolve_lock(scope, item.context_expr)
                if lock is not None:
                    fn.events.append(_Event(
                        kind="acquire", line=item.context_expr.lineno,
                        held=new_held, lock_id=lock.lock_id, region=True))
                    new_held = new_held + (lock.lock_id,)
            self._scan_body(fn, scope, stmt.body, new_held, globals_declared)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                key = self._write_key(scope, t, globals_declared)
                if key and fn.name != "__init__":
                    fn.writes.append((key, stmt.lineno, held))
            value = getattr(stmt, "value", None)
            if value is not None:
                self._scan_expr(fn, scope, value, held, globals_declared)
            return
        # generic statement: scan child statements/expressions with the
        # same held set (if/for/try/while bodies keep the lock)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._scan_stmt(fn, scope, child, held, globals_declared)
            elif isinstance(child, ast.expr):
                self._scan_expr(fn, scope, child, held, globals_declared)
            elif isinstance(child, (ast.excepthandler,)):
                self._scan_body(fn, scope, child.body, held,
                                globals_declared)

    def _scan_expr(self, fn: FuncInfo, scope: _Scope, expr: ast.expr,
                   held: Tuple[str, ...], globals_declared: Set[str]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_call(fn, scope, node, held)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in scope.mod.ctor_vars:
                # reading a module var whose assignment instantiates
                # classes: the import-time-singleton edge (get_codec's
                # CODECS lookup reaches TopKCodec.__init__)
                fn.events.append(_Event(
                    kind="call", line=node.lineno, held=held,
                    targets=tuple(scope.mod.ctor_vars[node.id])))

    def _scan_call(self, fn: FuncInfo, scope: _Scope, call: ast.Call,
                   held: Tuple[str, ...]) -> None:
        dotted = _dotted(call.func)

        # lock method events: L.acquire() is an acquisition event (no
        # lexical region — conservative), L.release() is ignored
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "acquire":
            lock = self._resolve_lock(scope, call.func.value)
            if lock is not None:
                fn.events.append(_Event(
                    kind="acquire", line=call.lineno, held=held,
                    lock_id=lock.lock_id, region=False))
                return

        # thread spawn: target is a root, not a call edge
        if dotted in _THREAD_CTORS:
            target = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and dotted in ("threading.Timer", "Timer") \
                    and len(call.args) >= 2:
                target = call.args[1]
            if target is not None:
                tq = self._resolve_targets(scope, target)
                for q in tq:
                    self.program.thread_roots[f"thread:{q}"] = q
            return

        # signal handler registration
        if dotted in ("signal.signal",) and len(call.args) >= 2:
            for q in self._resolve_targets(scope, call.args[1]):
                self.program.signal_roots[f"signal:{q}"] = (
                    q, fn.path, call.lineno)
            return

        # atexit runs on the main thread: a plain call edge
        if dotted in ("atexit.register",) and call.args:
            targets = self._resolve_targets(scope, call.args[0])
            if targets:
                fn.events.append(_Event(
                    kind="call", line=call.lineno, held=held,
                    targets=tuple(targets)))
            return

        # IO?
        if dotted in _IO_CALLS or (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _IO_METHOD_SUFFIXES
        ):
            fn.events.append(_Event(
                kind="io", line=call.lineno, held=held,
                desc=dotted or call.func.attr))
            return

        # `type(x)()` re-instantiation: edges to every __init__ in the
        # defining module (get_codec's fresh-instance fix)
        if isinstance(call.func, ast.Call) and \
                _dotted(call.func.func) == "type":
            targets = [
                f"{scope.mod.path}::{c}.__init__"
                for c in sorted(scope.mod.classes)
                if "__init__" in scope.mod.class_methods.get(c, ())
            ]
            if targets:
                fn.events.append(_Event(
                    kind="call", line=call.lineno, held=held,
                    targets=tuple(targets)))
            return

        targets = self._resolve_targets(scope, call.func)
        if targets:
            fn.events.append(_Event(
                kind="call", line=call.lineno, held=held,
                targets=tuple(targets)))
        elif isinstance(call.func, ast.Attribute) and \
                not call.func.attr.startswith("__"):
            # unresolved attribute call: keep the method name so engines
            # that tolerate over-approximation (trace-coherence) can
            # expand it to every same-named method
            fn.events.append(_Event(
                kind="call", line=call.lineno, held=held,
                desc=call.func.attr))

    # -- resolution helpers

    def _resolve_lock(self, scope: _Scope, expr: ast.AST) -> \
            Optional[LockDef]:
        if isinstance(expr, ast.Name):
            lock = scope.mod.locks.get(expr.id)
            if lock is not None:
                return lock
            imp = scope.mod.imports.get(expr.id)
            if imp and imp[0] == "name":
                target = self._by_key.get(imp[1])
                if target:
                    return target.locks.get(imp[2])
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and scope.cls:
                    return scope.mod.class_locks.get(
                        scope.cls, {}).get(expr.attr)
                cls_key = self._resolve_instance(scope, base.id)
                if cls_key:
                    mpath, _, cname = cls_key.partition("::")
                    target = self.program.modules.get(mpath)
                    if target:
                        return target.class_locks.get(cname, {}).get(
                            expr.attr)
                target = self._imported_module(scope, base.id)
                if target is not None:
                    return target.locks.get(expr.attr)
        return None

    def _resolve_instance(self, scope: _Scope, name: str) -> Optional[str]:
        cls_key = scope.mod.instances.get(name)
        if cls_key:
            return cls_key
        imp = scope.mod.imports.get(name)
        if imp and imp[0] == "name":
            target = self._by_key.get(imp[1])
            if target:
                return target.instances.get(imp[2])
        return None

    def _resolve_targets(self, scope: _Scope, expr: ast.AST) -> List[str]:
        """Resolve a callable expression to function qualnames."""
        if isinstance(expr, ast.Lambda):
            return []  # lambda bodies are scanned inline by _scan_expr
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in scope.nested:
                return [scope.nested[name]]
            if name in scope.mod.functions:
                return [f"{scope.mod.path}::{name}"]
            cls_key = self._resolve_class(scope.mod, expr)
            if cls_key:
                mpath, _, cname = cls_key.partition("::")
                mod = self.program.modules.get(mpath)
                if mod and "__init__" in mod.class_methods.get(cname, ()):
                    return [f"{cls_key}.__init__"]
                return []
            imp = scope.mod.imports.get(name)
            if imp and imp[0] == "name":
                target = self._by_key.get(imp[1])
                if target and imp[2] in target.functions:
                    return [f"{target.path}::{imp[2]}"]
            return []
        if isinstance(expr, ast.Attribute):
            base, attr = expr.value, expr.attr
            if isinstance(base, ast.Name):
                if base.id == "self" and scope.cls:
                    if attr in scope.mod.class_methods.get(scope.cls, ()):
                        return [f"{scope.mod.path}::{scope.cls}.{attr}"]
                    return self._unique_method(attr)
                target = self._imported_module(scope, base.id)
                if target is not None and attr in target.functions:
                    return [f"{target.path}::{attr}"]
                cls_key = self._resolve_instance(scope, base.id)
                if cls_key:
                    mpath, _, cname = cls_key.partition("::")
                    mod = self.program.modules.get(mpath)
                    if mod and attr in mod.class_methods.get(cname, ()):
                        return [f"{cls_key}.{attr}"]
            return self._unique_method(attr)
        return []

    def _imported_module(self, scope: _Scope, name: str) -> \
            Optional[_Module]:
        """``import x.y as z`` and ``from pkg import mod`` both bind a
        module object to a local name."""
        imp = scope.mod.imports.get(name)
        if imp is None:
            return None
        if imp[0] == "module":
            return self._by_key.get(imp[1])
        return self._by_key.get(f"{imp[1]}/{imp[2]}")

    def _unique_method(self, attr: str) -> List[str]:
        if attr in _METHOD_STOPLIST or len(attr) < 4 or \
                attr.startswith("__"):
            return []
        hits = self.program.method_index.get(attr, [])
        return list(hits) if len(hits) == 1 else []

    def _write_key(self, scope: _Scope, target: ast.AST,
                   globals_declared: Set[str]) -> Optional[str]:
        """Shared-state key for an assignment target: a declared-global
        module variable or an instance attribute ("path::Class.attr")."""
        if isinstance(target, ast.Name) and target.id in globals_declared:
            return f"{scope.mod.path}::{target.id}"
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name):
            base = target.value.id
            if base == "self" and scope.cls:
                return f"{scope.mod.path}::{scope.cls}.{target.attr}"
            cls_key = self._resolve_instance(scope, base)
            if cls_key:
                return f"{cls_key}.{target.attr}"
        return None


# ---- summaries (fixpoint) --------------------------------------------------


def _summarize(p: Program) -> None:
    """Call graph + transitive lock/IO summaries + root reachability."""
    for q, fn in p.funcs.items():
        callees: Set[str] = set()
        for ev in fn.events:
            if ev.kind == "call":
                callees.update(t for t in ev.targets if t in p.funcs)
        p.callees[q] = callees

    # direct summaries
    acquired: Dict[str, Dict[str, str]] = {}
    io: Dict[str, str] = {}
    for q, fn in p.funcs.items():
        acq: Dict[str, str] = {}
        for ev in fn.events:
            if ev.kind == "acquire" and ev.lock_id is not None:
                acq.setdefault(
                    ev.lock_id, f"{fn.path}:{ev.line}")
            elif ev.kind == "io" and q not in io:
                io[q] = f"{ev.desc} at {fn.path}:{ev.line}"
        acquired[q] = acq

    # fixpoint over the call graph (cycles converge: sets only grow)
    changed = True
    while changed:
        changed = False
        for q in p.funcs:
            for callee in p.callees[q]:
                for lock_id, chain in acquired.get(callee, {}).items():
                    if lock_id not in acquired[q]:
                        acquired[q][lock_id] = \
                            f"{_short(callee)} -> {chain}"
                        changed = True
                if callee in io and q not in io:
                    io[q] = f"{_short(callee)} -> {io[callee]}"
                    changed = True
    p.acquired = acquired
    p.io = io

    # root reachability
    def closure(start: str) -> Set[str]:
        seen: Set[str] = set()
        stack = [start]
        while stack:
            cur = stack.pop()
            if cur in seen or cur not in p.funcs:
                continue
            seen.add(cur)
            stack.extend(p.callees.get(cur, ()))
        return seen

    bg_reach: Dict[str, Set[str]] = {}
    for label, target in {**p.thread_roots,
                          **{k: v[0] for k, v in p.signal_roots.items()}
                          }.items():
        bg_reach[label] = closure(target)
    bg_all: Set[str] = set().union(*bg_reach.values()) if bg_reach else set()

    main_seeds = {q for q in p.funcs if q not in bg_all}
    main_reach: Set[str] = set()
    stack = list(main_seeds)
    while stack:
        cur = stack.pop()
        if cur in main_reach or cur not in p.funcs:
            continue
        main_reach.add(cur)
        stack.extend(p.callees.get(cur, ()))

    for q in p.funcs:
        roots = {label for label, reach in bg_reach.items() if q in reach}
        if q in main_reach:
            roots.add(MAIN_ROOT)
        p.roots[q] = roots


def _short(qualname: str) -> str:
    return qualname.split("::", 1)[-1]


# ---- the lock graph + rules ------------------------------------------------


def static_lock_graph(p: Program) -> Dict:
    """The static acquisition-order graph the runtime lockdep witness is
    cross-checked against: ``locks`` maps creation sites to lock ids,
    ``edges`` is the set of ordered (held, acquired) pairs with witnesses."""
    edges: Dict[Tuple[str, str], str] = {}
    for q, fn in p.funcs.items():
        for ev in fn.events:
            inner: Dict[str, str] = {}
            if ev.kind == "acquire" and ev.lock_id is not None:
                inner[ev.lock_id] = f"{fn.path}:{ev.line}"
            elif ev.kind == "call":
                for t in ev.targets:
                    for lock_id, chain in p.acquired.get(t, {}).items():
                        inner.setdefault(
                            lock_id,
                            f"{fn.path}:{ev.line} via {_short(t)} -> "
                            f"{chain}")
            for held in ev.held:
                for lock_id, chain in inner.items():
                    edges.setdefault((held, lock_id),
                                     f"{_short(q)}: {chain}")
    return {
        "locks": {lock.site: lock_id for lock_id, lock in p.locks.items()},
        "edges": edges,
    }


def _lock_regions(p: Program) -> Dict[str, List[Dict]]:
    """Per lock: every acquisition site with whether its held region does
    IO (directly or transitively) and the roots of the acquiring function."""
    regions: Dict[str, List[Dict]] = {}
    for q, fn in p.funcs.items():
        # map lexical regions: events whose held-tuple contains the lock
        # happened inside its region
        for ev in fn.events:
            if ev.kind != "acquire" or ev.lock_id is None:
                continue
            region = {
                "func": q, "path": fn.path, "line": ev.line,
                "lexical": ev.region, "io": None, "roots": p.roots.get(
                    q, set()),
            }
            regions.setdefault(ev.lock_id, []).append(region)
        for ev in fn.events:
            if not ev.held:
                continue
            io_chain = None
            if ev.kind == "io":
                io_chain = f"{ev.desc} at {fn.path}:{ev.line}"
            elif ev.kind == "call":
                for t in ev.targets:
                    if t in p.io:
                        io_chain = (f"{_short(t)} -> {p.io[t]} "
                                    f"(called at {fn.path}:{ev.line})")
                        break
            if io_chain is None:
                continue
            for held in ev.held:
                for region in regions.get(held, []):
                    if region["func"] == q and region["io"] is None:
                        region["io"] = io_chain
    return regions


def _rule(rule_id: str) -> Rule:
    for r in CONCURRENCY_RULES:
        if r.id == rule_id:
            return r
    raise KeyError(rule_id)


def run_concurrency_rules(
    paths: Optional[Iterable[str]] = None,
    rel_to: Optional[str] = None,
    program: Optional[Program] = None,
    sources: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Build the whole-program model and run the five concurrency rules.
    ``sources`` maps path -> source for in-memory fixtures (tests)."""
    if program is None:
        builder = _Builder(paths or [], rel_to=rel_to)
        for path, source in (sources or {}).items():
            builder.add_source(path, source)
        program = builder.build()
    findings = _raw_concurrency_findings(program)
    out: List[Finding] = []
    for f in findings:
        if not is_suppressed(f, program.suppressions.get(f.path, {})):
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def build_program(
    paths: Optional[Iterable[str]] = None,
    rel_to: Optional[str] = None,
    sources: Optional[Dict[str, str]] = None,
) -> Program:
    builder = _Builder(paths or [], rel_to=rel_to)
    for path, source in (sources or {}).items():
        builder.add_source(path, source)
    return builder.build()


def _finding(rule: Rule, path: str, line: int, message: str,
             p: Program) -> Finding:
    mod = p.modules.get(path)
    text = ""
    if mod is not None:
        lines = mod.source.splitlines()
        if 1 <= line <= len(lines):
            text = lines[line - 1].strip()
    return Finding(rule=rule.id, path=path, line=line, message=message,
                   hint=rule.hint, text=text)


def _raw_concurrency_findings(p: Program) -> List[Finding]:
    findings: List[Finding] = []
    graph = static_lock_graph(p)
    edges: Dict[Tuple[str, str], str] = graph["edges"]

    # -- lock-order-inversion: cycles in the acquisition graph
    adj: Dict[str, Set[str]] = {}
    for (a, b), _ in edges.items():
        if a != b:
            adj.setdefault(a, set()).add(b)
    for scc in _tarjan(adj):
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        rule = _rule("lock-order-inversion")
        for (a, b), witness in sorted(edges.items()):
            if a in scc and b in scc and a != b:
                path, line = _witness_site(witness)
                findings.append(_finding(
                    rule, path, line,
                    f"lock acquisition cycle over {{{', '.join(cyc)}}}: "
                    f"this edge takes {_short(b)} while holding "
                    f"{_short(a)} ({witness}); another path takes them "
                    "in the opposite order", p))
        # anchor every edge of the cycle: fixing any one breaks it

    # -- non-reentrant-reacquire: A -> A with A non-reentrant
    rule = _rule("non-reentrant-reacquire")
    for (a, b), witness in sorted(edges.items()):
        if a == b and not p.locks[a].reentrant:
            path, line = _witness_site(witness)
            findings.append(_finding(
                rule, path, line,
                f"non-reentrant lock {_short(a)} re-acquired while held "
                f"({witness}): this deadlocks the holding thread", p))

    # -- signal-unsafe-lock
    rule = _rule("signal-unsafe-lock")
    for label, (handler, reg_path, reg_line) in sorted(
            p.signal_roots.items()):
        acq = p.acquired.get(handler, {})
        for lock_id, chain in sorted(acq.items()):
            findings.append(_finding(
                rule, reg_path, reg_line,
                f"signal handler {_short(handler)} acquires "
                f"{_short(lock_id)} ({chain}): the handler interrupts "
                "arbitrary code — including the current owner of that "
                "lock — so this can self-deadlock", p))

    # -- lock-held-io
    rule = _rule("lock-held-io")
    regions = _lock_regions(p)
    for lock_id, regs in sorted(regions.items()):
        roots: Set[str] = set()
        for r in regs:
            roots.update(r["roots"])
        if len(roots) < 2:
            continue
        has_io_free = any(r["io"] is None for r in regs)
        if not has_io_free:
            continue
        for r in regs:
            if r["io"] is None:
                continue
            findings.append(_finding(
                rule, r["path"], r["line"],
                f"blocking IO under {_short(lock_id)} "
                f"({r['io']}) while roots {{{', '.join(sorted(roots))}}} "
                "contend on an IO-free path through the same lock: the "
                "fast path wedges behind the IO", p))

    # -- unguarded-shared-write
    rule = _rule("unguarded-shared-write")
    by_key: Dict[str, List[Tuple[str, int, Tuple[str, ...], str]]] = {}
    for q, fn in p.funcs.items():
        for key, line, held in fn.writes:
            by_key.setdefault(key, []).append((fn.path, line, held, q))
    for key, writes in sorted(by_key.items()):
        roots = set()
        for _, _, _, q in writes:
            roots.update(p.roots.get(q, set()))
        if len(roots) < 2:
            continue
        common = set(writes[0][2])
        for _, _, held, _ in writes[1:]:
            common &= set(held)
        if common:
            continue
        path, line = writes[0][0], writes[0][1]
        sites = ", ".join(f"{pp}:{ll}" for pp, ll, _, _ in writes[:4])
        findings.append(_finding(
            rule, path, line,
            f"{_short(key)} written from roots "
            f"{{{', '.join(sorted(roots))}}} with no common lock "
            f"(write sites: {sites}): concurrent writers race", p))

    return findings


def _witness_site(witness: str) -> Tuple[str, int]:
    """Pull the first path:line out of a witness chain for anchoring."""
    for token in witness.replace(",", " ").split():
        if ":" in token and not token.endswith(":"):
            path, _, line = token.rpartition(":")
            if line.isdigit():
                return path, int(line)
    return "<unknown>", 0


def _tarjan(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Strongly connected components (iterative Tarjan)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]
    nodes = set(adj) | {b for bs in adj.values() for b in bs}

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


# ---- rule catalog ----------------------------------------------------------

CONCURRENCY_RULES: List[Rule] = [
    Rule(
        id="lock-order-inversion",
        summary="two locks acquired in opposite orders on different paths "
                "(cycle in the interprocedural acquisition graph)",
        rationale="Thread A holding L1 waiting for L2 while thread B holds "
                  "L2 waiting for L1 is a deadlock that only fires under "
                  "scheduling pressure — exactly the hang class the "
                  "watchdog exists for, except the watchdog's own dump "
                  "path can be a party to it.",
        hint="impose a global acquisition order (take the coarser lock "
             "first everywhere) or narrow one critical section so the "
             "nested acquisition moves outside the outer lock",
    ),
    Rule(
        id="unguarded-shared-write",
        summary="module global / instance attribute written from >=2 "
                "thread roots with no lock common to every write",
        rationale="Two writers with no common lock means lost updates and "
                  "torn compound state; these races surface as "
                  "once-a-week corrupted telemetry or a half-updated "
                  "watchdog deadline, never in unit tests.",
        hint="guard every write with one shared lock, or confine the "
             "variable to a single owning thread and pass changes "
             "through a queue",
    ),
    Rule(
        id="lock-held-io",
        summary="blocking IO (file/socket/subprocess/time.sleep) under a "
                "lock that other thread roots contend on via IO-free "
                "paths",
        rationale="The PR 7 class: a heartbeat/watchdog/step path blocks "
                  "on a lock whose holder is mid-IO — a slow disk or "
                  "socket turns into missed heartbeats and false-positive "
                  "hang verdicts.",
        hint="snapshot the shared state under the lock, release it, then "
             "do the IO on the snapshot (the flight recorder's "
             "copy-then-dump pattern)",
    ),
    Rule(
        id="signal-unsafe-lock",
        summary="lock acquisition reachable from a signal handler",
        rationale="Signal handlers run re-entrantly on the main thread at "
                  "an arbitrary bytecode boundary: if the interrupted "
                  "code holds the same non-reentrant lock the handler "
                  "wants, the process self-deadlocks (the pre-PR-7 "
                  "SIGTERM flight-dump hang).",
        hint="have the handler hand the work to a helper thread and "
             "bounded-join it (obs.recorder.maybe_install_signal_hook's "
             "pattern), or only set a flag the main loop polls",
    ),
    Rule(
        id="non-reentrant-reacquire",
        summary="a held non-reentrant threading.Lock re-acquired on the "
                "same path (directly or through a callee)",
        rationale="threading.Lock does not track ownership: re-acquiring "
                  "it from the holding thread blocks forever, and the "
                  "interprocedural variant (a helper that takes the lock "
                  "its caller already holds) is invisible in review.",
        hint="split the locked method into a public locking wrapper and a "
             "private _locked helper callers-with-the-lock use, or make "
             "the lock an RLock if re-entry is genuinely intended",
    ),
]
