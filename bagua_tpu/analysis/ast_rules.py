"""AST hot-path rule engine.

Rules walk each module's AST once, sharing a *traced-region* analysis: a
function is considered traced when it is (a) passed to / decorated with a JAX
tracing entry point (``jit``, ``shard_map``, ``lax.scan``/``cond``/
``switch``/``while_loop``/``fori_loop``/``map``, ``vmap``, ``pmap``,
``grad``, ``value_and_grad``, ``checkpoint``/``remat``, ``eval_shape``,
``make_jaxpr``), or (b) defined inside a traced function.  The analysis is
syntactic — a method called *from* a traced function is not marked (no
interprocedural call graph) — so the rules catch the direct step-construction
code, which is where this repo's hot paths live.

Each rule carries an id (the suppression / baseline key), a rationale, and a
fix hint; the catalog renders into ``docs/analysis.md``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, Iterator, List, Optional, Set

from .findings import Finding
from .suppressions import is_suppressed, parse_suppressions

#: call suffixes that start a trace (matched against the dotted callee name)
_TRACE_ENTRY_SUFFIXES = (
    "jit",
    "shard_map",
    "lax.scan",
    "lax.cond",
    "lax.switch",
    "lax.while_loop",
    "lax.fori_loop",
    "lax.map",
    "lax.associative_scan",
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "value_and_grad_aux",
    "checkpoint",
    "remat",
    "eval_shape",
    "make_jaxpr",
)


def _dotted(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` -> "jax.lax.scan"; None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_trace_entry(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    return any(
        dotted == s or dotted.endswith("." + s) for s in _TRACE_ENTRY_SUFFIXES
    )


@dataclass
class ModuleInfo:
    path: str          # repo-relative posix path
    source: str
    tree: ast.Module
    lines: List[str]
    #: every node lexically inside a traced function (identity set)
    traced_nodes: Set[int] = field(default_factory=set)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule.id,
            path=self.path,
            line=getattr(node, "lineno", 0),
            message=message,
            hint=rule.hint,
            text=self.line_text(getattr(node, "lineno", 0)),
        )

    def in_traced(self, node: ast.AST) -> bool:
        return id(node) in self.traced_nodes


def _mark_traced_regions(info: ModuleInfo) -> None:
    """Populate ``info.traced_nodes`` (two passes + closure over nesting)."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(info.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    roots: List[ast.AST] = []

    def _mark_callable_arg(arg: ast.AST) -> None:
        if isinstance(arg, ast.Lambda):
            roots.append(arg)
        elif isinstance(arg, ast.Name):
            roots.extend(defs_by_name.get(arg.id, ()))

    for node in ast.walk(info.tree):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if _is_trace_entry(dotted):
                for arg in node.args:
                    _mark_callable_arg(arg)
                for kw in node.keywords:
                    if kw.arg in (None, "mesh", "in_specs", "out_specs",
                                  "static_argnums", "donate_argnums",
                                  "axis_name", "length"):
                        continue
                    _mark_callable_arg(kw.value)
            elif dotted in ("partial", "functools.partial") and node.args:
                # partial(jax.jit, ...)(f) / @partial(jax.jit, ...)
                if _is_trace_entry(_dotted(node.args[0])):
                    for arg in node.args[1:]:
                        _mark_callable_arg(arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = _dotted(target)
                if _is_trace_entry(dotted):
                    roots.append(node)
                elif (
                    isinstance(dec, ast.Call)
                    and dotted in ("partial", "functools.partial")
                    and dec.args
                    and _is_trace_entry(_dotted(dec.args[0]))
                ):
                    roots.append(node)

    for root in roots:
        for sub in ast.walk(root):
            info.traced_nodes.add(id(sub))


# ---- rules ---------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    rationale: str
    hint: str

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


class HostSyncInTrace(Rule):
    """Host synchronization inside traced step code."""

    _NP_SYNC = ("np.asarray", "numpy.asarray", "onp.asarray",
                "np.array", "numpy.array", "onp.array")

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call) and info.in_traced(node)):
                continue
            dotted = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                yield info.finding(
                    self, node,
                    "`.block_until_ready()` inside traced code forces a "
                    "host sync at trace time",
                )
            elif dotted and (
                dotted == "jax.device_get"
                or dotted.endswith(".device_get")
            ):
                yield info.finding(
                    self, node,
                    "`jax.device_get` inside traced code pulls the value "
                    "to host, breaking the trace",
                )
            elif dotted in self._NP_SYNC:
                yield info.finding(
                    self, node,
                    f"`{dotted}` materializes a traced value on host; use "
                    "`jnp` inside traced code",
                )
            elif dotted == "float" and node.args:
                yield info.finding(
                    self, node,
                    "`float()` on a traced value is a host readback "
                    "(ConcretizationError at best, a sync at worst)",
                )


class RawEnvRead(Rule):
    """Ad-hoc ``BAGUA_*`` environment reads outside the registry."""

    def _bagua_const(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("BAGUA_"):
            return node.value
        return None

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        if info.path.replace(os.sep, "/").endswith("bagua_tpu/env.py"):
            return
        for node in ast.walk(info.tree):
            var = None
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted and (
                    dotted.endswith("environ.get") or dotted.endswith("getenv")
                ) and node.args:
                    var = self._bagua_const(node.args[0])
            elif isinstance(node, ast.Subscript):
                dotted = _dotted(node.value)
                if dotted and dotted.endswith("environ"):
                    var = self._bagua_const(node.slice)
            if var:
                yield info.finding(
                    self, node,
                    f"raw os.environ read of {var} outside the env registry",
                )


class TracerLeak(Rule):
    """Storing values on ``self`` from inside a traced function."""

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not info.in_traced(node):
                continue
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls"):
                    yield info.finding(
                        self, node,
                        f"assignment to `{t.value.id}.{t.attr}` inside "
                        "traced code leaks a tracer into host state",
                    )


class PyRngInTrace(Rule):
    """Nondeterministic Python/NumPy RNG inside traced code."""

    _PREFIXES = ("random.", "np.random.", "numpy.random.", "onp.random.")

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call) and info.in_traced(node)):
                continue
            dotted = _dotted(node.func)
            if dotted and dotted.startswith(self._PREFIXES):
                yield info.finding(
                    self, node,
                    f"`{dotted}` in traced code bakes ONE sample into the "
                    "compiled program (and differs across ranks)",
                )


class DupLambda(Rule):
    """Copy-pasted helper lambdas within one module."""

    #: minimum identical copies before the duplication is worth a finding
    MIN_COPIES = 3

    def _shape(self, node: ast.Lambda) -> Optional[str]:
        # normalize argument names positionally so `lambda t: f(t)` and
        # `lambda u: f(u)` dedupe; trivial lambdas (no call in the body)
        # are idiom, not duplication
        if not any(isinstance(n, ast.Call) for n in ast.walk(node.body)):
            return None
        clone = ast.parse(ast.unparse(node), mode="eval").body
        rename = {
            a.arg: f"_a{i}" for i, a in enumerate(clone.args.args)
        }
        for sub in ast.walk(clone):
            if isinstance(sub, ast.Name) and sub.id in rename:
                sub.id = rename[sub.id]
            elif isinstance(sub, ast.arg) and sub.arg in rename:
                sub.arg = rename[sub.arg]
        return ast.dump(clone)

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        # outermost lambdas only: a duplicated outer lambda would otherwise
        # drag its inner lambdas into their own duplicate groups, double-
        # reporting every site
        nested: Set[int] = set()
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Lambda):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Lambda) and sub is not node:
                        nested.add(id(sub))
        groups: Dict[str, List[ast.Lambda]] = {}
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Lambda) and id(node) not in nested:
                shape = self._shape(node)
                if shape:
                    groups.setdefault(shape, []).append(node)
        for nodes in groups.values():
            if len(nodes) < self.MIN_COPIES:
                continue
            first = min(n.lineno for n in nodes)
            for node in nodes:
                yield info.finding(
                    self, node,
                    f"lambda duplicated {len(nodes)}x in this module "
                    f"(first at line {first})",
                )


class PerStepReflatten(Rule):
    """Per-step pytree re-flattening inside traced step code.

    The flat-resident layout exists so the hot step never re-packs leaves
    into flat buffers; a traced function that BOTH walks a pytree's leaves
    (``tree_leaves`` / ``tree_flatten`` / ``flatten_tree``) AND
    ``concatenate``s the result is re-paying exactly that cost on every
    step — the pre-fix ``fused_optimizer.update_fn`` pattern.  Optimizer
    ``update_fn``/``init_fn`` pairs wrapped into an
    ``optax.GradientTransformation`` run inside the jitted train step by
    construction, so they count as traced step code here even though no
    ``jit`` call touches them syntactically."""

    _FLATTEN_SUFFIXES = ("tree_leaves", "tree_flatten", "flatten_tree")
    _CONCAT_SUFFIXES = ("concatenate",)

    def _mark_transform_fns(self, info: ModuleInfo) -> Set[int]:
        """Nodes of functions passed to an ``optax.GradientTransformation``
        (or ``FusedTransformation``) constructor — optimizer stages that
        trace inside the step."""
        defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(info.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
        marked: Set[int] = set()
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            if not dotted.endswith(("GradientTransformation",
                                    "FusedTransformation")):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                roots: List[ast.AST] = []
                if isinstance(arg, ast.Lambda):
                    roots.append(arg)
                elif isinstance(arg, ast.Name):
                    roots.extend(defs_by_name.get(arg.id, ()))
                for root in roots:
                    for sub in ast.walk(root):
                        marked.add(id(sub))
        return marked

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        transform_nodes = self._mark_transform_fns(info)

        def in_step_code(node: ast.AST) -> bool:
            return info.in_traced(node) or id(node) in transform_nodes

        # per enclosing function: does it both flatten a tree and
        # concatenate?  (one function = one traced stage; pairing across
        # functions would flag the legitimate standalone helpers)
        fn_types = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        for f in ast.walk(info.tree):
            if not isinstance(f, fn_types):
                continue
            inner: Set[int] = set()
            for sub in ast.walk(f):
                if sub is not f and isinstance(sub, fn_types):
                    # nested defs get their own pass
                    inner.update(id(s) for s in ast.walk(sub))
            flattens: List[ast.Call] = []
            concats: List[ast.Call] = []
            for node in ast.walk(f):
                if id(node) in inner or not isinstance(node, ast.Call):
                    continue
                if not in_step_code(node):
                    continue
                dotted = _dotted(node.func) or ""
                if dotted.endswith(self._FLATTEN_SUFFIXES):
                    flattens.append(node)
                elif dotted.endswith(self._CONCAT_SUFFIXES):
                    concats.append(node)
            if flattens and concats:
                yield info.finding(
                    self, concats[0],
                    "traced step code flattens a pytree (line "
                    f"{flattens[0].lineno}) and concatenates per step — "
                    "the repack the flat-resident layout exists to remove",
                )


class UnregisteredCounter(Rule):
    """Telemetry counter/gauge names must be declared in
    ``bagua_tpu.obs.export.METRIC_REGISTRY``.

    Checks ``<...>counters.incr/set_gauge`` call sites (plus literal-keyed
    ``incr_many`` dicts).  Literal names are matched exactly; f-string
    names (``f"faults/{point}/fired"``) are matched as a pattern — some
    registered name must fit the template; non-literal names are skipped
    (unresolvable statically).  The registry import is lazy and
    import-light (no jax), so the engine still runs without a device."""

    _METHODS = ("incr", "set_gauge", "incr_many")

    @staticmethod
    def _is_counters_call(node: ast.Call) -> bool:
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in UnregisteredCounter._METHODS):
            return False
        recv = _dotted(f.value)
        return bool(recv) and (recv == "counters"
                               or recv.endswith(".counters")
                               or recv.endswith("_counters"))

    @staticmethod
    def _name_exprs(node: ast.Call):
        if not node.args:
            return
        arg = node.args[0]
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "incr_many":
            if isinstance(arg, ast.Dict):
                for key in arg.keys:
                    if key is not None:
                        yield key
            return
        yield arg

    def _check_name(self, expr: ast.AST):
        """(metric-name-or-pattern, unregistered?) — None to skip."""
        from ..obs.export import any_registered_matches, is_registered

        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value, not is_registered(expr.value)
        if isinstance(expr, ast.JoinedStr):
            parts: List[str] = []
            for v in expr.values:
                if isinstance(v, ast.Constant):
                    parts.append(re.escape(str(v.value)))
                else:  # FormattedValue: any non-empty fragment
                    parts.append(".+")
            pattern = "".join(parts)
            return pattern, not any_registered_matches(pattern)
        return None

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call)
                    and self._is_counters_call(node)):
                continue
            for expr in self._name_exprs(node):
                checked = self._check_name(expr)
                if checked is None:
                    continue
                name, unregistered = checked
                if unregistered:
                    yield info.finding(
                        self, node,
                        f"counter name {name!r} is not declared in "
                        "obs.export.METRIC_REGISTRY",
                    )


class TorchImport(Rule):
    """No torch imports in the TPU package (ci.sh's historical gate)."""

    def visit(self, info: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(info.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module and node.module.split(".")[0] == "torch":
                    yield info.finding(
                        self, node, "torch import in the TPU package"
                    )
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] == "torch":
                        yield info.finding(
                            self, node, "torch import in the TPU package"
                        )


RULES: List[Rule] = [
    HostSyncInTrace(
        id="host-sync-in-trace",
        summary="host-sync call (`block_until_ready`, `np.asarray`, "
                "`jax.device_get`, `float()`) inside jit/scan-traced code",
        rationale="Host syncs inside a traced step either fail at trace time "
                  "(ConcretizationError) or silently serialize dispatch, "
                  "defeating the overlap scheduler the step exists to feed.",
        hint="keep host readbacks outside the step; use `jnp` ops or "
             "`jax.debug.*` inside traces",
    ),
    RawEnvRead(
        id="raw-env-read",
        summary="`os.environ` read of a `BAGUA_*` name outside `env.py`",
        rationale="Scattered env reads drift from the documented defaults "
                  "and types; the registry in `bagua_tpu.env` is the single "
                  "source of truth (and generates docs/env_vars.md).",
        hint="declare the variable in `env.ENV_REGISTRY` and read it "
             "through an `env.*` accessor",
    ),
    TracerLeak(
        id="tracer-leak",
        summary="assignment to `self.*` from inside a traced function",
        rationale="A tracer stored on a host object outlives its trace; "
                  "the next use raises `UnexpectedTracerError` or — worse — "
                  "silently freezes a stale constant into later compiles.",
        hint="return the value through the traced function's outputs "
             "instead of stashing it on the instance",
    ),
    PyRngInTrace(
        id="py-rng-in-trace",
        summary="Python/NumPy RNG call inside traced code",
        rationale="`random.*`/`np.random.*` run at TRACE time: one sample is "
                  "baked into the compiled program forever, and each rank "
                  "bakes a different one — silent SPMD divergence.",
        hint="thread a `jax.random` key through the step instead",
    ),
    DupLambda(
        id="dup-lambda",
        summary="identical helper lambda copy-pasted 3+ times in a module",
        rationale="Copy-pasted traced helpers drift independently (one gets "
                  "a fix, its clones keep the bug) — the exact failure mode "
                  "behind the five `stack = lambda t: ...` copies this rule "
                  "was built on.",
        hint="hoist one module-level helper and call it everywhere",
    ),
    PerStepReflatten(
        id="per-step-reflatten",
        summary="traced step code re-flattens a pytree "
                "(`tree_leaves`/`tree_flatten`/`flatten_tree` + "
                "`concatenate`) every step",
        rationale="Re-packing leaves into flat buffers inside the traced "
                  "step re-pays, every step, exactly the round trip the "
                  "flat-resident layout removed (the measured ~7% ZeRO "
                  "leaf->flat->leaf cost) — the pre-fix "
                  "`fused_optimizer.update_fn` per-dtype concat pattern.  "
                  "Optimizer fns wrapped in `optax.GradientTransformation` "
                  "trace inside the step, so they count as step code.",
        hint="keep the state bucket-flat across steps "
             "(`flat_resident=`/ctx.bucket_flats) instead of re-packing "
             "per step; for optimizers, let the trainer unwrap "
             "`fuse_optimizer` onto the resident flats",
    ),
    UnregisteredCounter(
        id="unregistered-counter",
        summary="`counters.incr`/`set_gauge` with a name not declared in "
                "obs.export.METRIC_REGISTRY",
        rationale="A typo'd metric name silently forks a counter nobody "
                  "reads (the drill gates and the fleet fence then count "
                  "against the wrong key); the registry is the single "
                  "source of truth for metric names, kinds, and docs — "
                  "the counter analog of env.ENV_REGISTRY.",
        hint="declare the name in bagua_tpu.obs.export.METRIC_REGISTRY "
             "(kind + doc) or fix the spelling to a registered name",
    ),
    TorchImport(
        id="torch-import",
        summary="torch import inside bagua_tpu",
        rationale="The package is a from-scratch JAX rebuild; a torch import "
                  "is always an accident (and an instant ImportError on "
                  "TPU images).",
        hint="port the call to jax/jnp or move it to a contrib example",
    ),
]


# ---- engine --------------------------------------------------------------


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def analyze_source(
    path: str, source: str, rules: Optional[List[Rule]] = None
) -> List[Finding]:
    """Run the rules over one module's source.  Returns ACTIVE findings
    (suppressions already applied; malformed suppressions reported)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(
            rule="syntax-error", path=path, line=e.lineno or 0,
            message=f"cannot parse: {e.msg}", text="",
        )]
    info = ModuleInfo(
        path=path, source=source, tree=tree, lines=source.splitlines()
    )
    _mark_traced_regions(info)
    suppressions, problems = parse_suppressions(path, source)
    findings: List[Finding] = list(problems)
    for rule in (RULES if rules is None else rules):
        for f in rule.visit(info):
            if not is_suppressed(f, suppressions):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_ast_rules(
    paths: Iterable[str],
    rules: Optional[List[Rule]] = None,
    rel_to: Optional[str] = None,
) -> List[Finding]:
    """Run the engine over files/directories; paths in findings are made
    relative to ``rel_to`` (default: cwd) and posix-normalized."""
    base = os.path.abspath(rel_to or os.getcwd())
    findings: List[Finding] = []
    for fp in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fp), base)
        rel = rel.replace(os.sep, "/")
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(analyze_source(rel, source, rules))
    return findings
