"""Process launcher with gang restart.

Counterpart of /root/reference/bagua/distributed/run.py (torchelastic wrapper:
Bagua flags + env injection :360-398,578-600, elastic_launch with gang-restart
semantics :116-129,603-628) and the legacy subprocess launcher ``launch.py``.

TPU shape: one JAX process per host drives all local chips, so
``--nproc_per_node`` defaults to 1 (it exists for CPU-simulation runs and
hosts with multiple isolated accelerator sets).  Rendezvous is the JAX
coordination service (``BAGUA_COORDINATOR_ADDR`` consumed by
``bagua_tpu.init_process_group``) instead of a c10d store.  Elastic behavior
is the honest XLA equivalent of torchelastic's: ANY worker failure kills the
whole gang and restarts it (same world size) up to ``--max_restarts``, and
workers resume from the latest checkpoint
(:mod:`bagua_tpu.checkpoint`) — in-flight world-size *resizing* is impossible
under XLA's static SPMD compilation, so MIN:MAX nnodes syntax is rejected
rather than silently accepted.

Multi-node gang restart (reference run.py:116-129 restarts the whole
multi-node gang via the c10d rendezvous): each node's launcher coordinates
through a tiny KV store (node 0 hosts a :class:`TCPStoreServer` on
``--restart_coordinator_port``).  A node observing a local worker failure
publishes a per-attempt failure flag; every launcher polls it, kills its
own gang, joins a per-attempt ready barrier, and respawns together — so
survivors never sit wedged in collectives while one node restarts alone.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import time
from typing import List

logger = logging.getLogger("bagua_tpu.launcher")


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        "python -m bagua_tpu.distributed.run",
        description="bagua_tpu launcher (reference: bagua.distributed.run)",
    )
    p.add_argument("--nnodes", type=str, default="1",
                   help="number of nodes (fixed; MIN:MAX is rejected — XLA "
                        "cannot resize in flight, restart with a new value)")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="JAX processes per node (default 1: one process "
                        "drives all local chips)")
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29400)
    p.add_argument("--max_restarts", type=int, default=None,
                   help="gang restarts after a worker failure (default 3 "
                        "single-node, 0 multi-node; multi-node restarts are "
                        "coordinated through the restart KV store)")
    p.add_argument("--monitor_interval", type=float, default=1.0)
    p.add_argument("--restart_coordinator_port", type=int, default=None,
                   help="KV-store port for coordinated multi-node restarts "
                        "(default master_port + 1; node 0 hosts it)")
    p.add_argument("--restart_barrier_timeout", type=float, default=300.0,
                   help="seconds to wait for every node at a restart barrier")
    # Bagua flags (reference run.py:360-398)
    p.add_argument("--bagua_service_port", type=int, default=29500)
    p.add_argument("--default_bucket_size", type=int, default=10 * 1024 ** 2)
    p.add_argument("--autotune_level", type=int, default=0)
    p.add_argument("--autotune_max_samples", type=int, default=60)
    p.add_argument("--autotune_sampling_confidence_time", type=float, default=5.0)
    p.add_argument("--autotune_warmup_time", type=float, default=30.0)
    p.add_argument("--is_output_autotune_log", action="store_true")
    p.add_argument("--autotune_algorithm", action="store_true",
                   help="let the autotuner search over algorithm families")
    p.add_argument("--simulate_cpu_devices", type=int, default=0,
                   help="force JAX onto N virtual CPU devices (testing)")
    p.add_argument("--no_python", action="store_true",
                   help="run training_script directly instead of "
                        "`python training_script`")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if ":" in args.nnodes:
        p.error("elastic MIN:MAX nnodes is not supported on TPU — world size "
                "is fixed per launch; restart the job to resize")
    args.nnodes_int = int(args.nnodes)
    if args.max_restarts is None:
        # multi-node default stays 0: coordinated restart requires every
        # node's launcher to be started with the same max_restarts > 0
        args.max_restarts = 3 if args.nnodes_int == 1 else 0
    if args.restart_coordinator_port is None:
        args.restart_coordinator_port = args.master_port + 1
    return args


def build_env(args, local_rank: int) -> dict:
    """Reference ``set_bagua_env`` (run.py:578-600) + rendezvous env."""
    env = dict(os.environ)
    world_size = args.nnodes_int * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    env.update(
        RANK=str(rank),
        WORLD_SIZE=str(world_size),
        LOCAL_RANK=str(local_rank),
        LOCAL_WORLD_SIZE=str(args.nproc_per_node),
        NODE_RANK=str(args.node_rank),
        MASTER_ADDR=args.master_addr,
        MASTER_PORT=str(args.master_port),
        BAGUA_SERVICE_PORT=str(args.bagua_service_port),
        BAGUA_DEFAULT_BUCKET_SIZE=str(args.default_bucket_size),
        BAGUA_AUTOTUNE=str(args.autotune_level),
        BAGUA_AUTOTUNE_MAX_SAMPLES=str(args.autotune_max_samples),
        BAGUA_AUTOTUNE_SAMPLING_CONFIDENCE_TIME_S=str(
            args.autotune_sampling_confidence_time),
        BAGUA_AUTOTUNE_WARMUP_TIME_S=str(args.autotune_warmup_time),
        BAGUA_IS_OUTPUT_AUTOTUNE_LOG=str(int(args.is_output_autotune_log)),
        BAGUA_AUTOTUNE_ALGORITHM=str(int(args.autotune_algorithm)),
        AUTO_TUNE_SERVER_ADDR=f"{args.master_addr}:{args.bagua_service_port}",
    )
    # Workers must inherit the launcher's import environment: the spawned
    # `python training_script` has the *script's* directory as sys.path[0],
    # so an un-installed bagua_tpu (or the user's own modules in cwd) would
    # not be importable.  torchelastic effectively does the same.
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    extra_paths = [os.getcwd(), pkg_parent]
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(
        dict.fromkeys(p for p in extra_paths + prev.split(os.pathsep) if p)
    )
    if world_size > 1:
        env["BAGUA_COORDINATOR_ADDR"] = f"{args.master_addr}:{args.master_port}"
    if args.simulate_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_PLATFORM_NAME"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.simulate_cpu_devices}"
        )
        from ..env import sanitize_cpu_sim_env

        sanitize_cpu_sim_env(env)
    return env


def spawn_gang(args) -> List[subprocess.Popen]:
    cmd_prefix = [] if args.no_python else [sys.executable, "-u"]
    procs = []
    for local_rank in range(args.nproc_per_node):
        cmd = cmd_prefix + [args.training_script] + args.training_script_args
        procs.append(subprocess.Popen(cmd, env=build_env(args, local_rank)))
    return procs


def kill_gang(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def monitor(args, procs: List[subprocess.Popen]) -> int:
    """Return exit code when all succeed; raise ``_GangFailure`` on any
    worker failure (reference gang semantics run.py:116-129)."""
    while True:
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            kill_gang(procs)
            raise _GangFailure(failed[0])
        if all(c == 0 for c in codes):
            return 0
        time.sleep(args.monitor_interval)


class _GangFailure(Exception):
    def __init__(self, code: int):
        super().__init__(f"worker failed with exit code {code}")
        self.code = code


def _connect_restart_store(args, timeout_s: float = 60.0):
    """Client to node 0's restart KV store, with connect retries (peers may
    start before the server is up)."""
    from ..contrib.utils.tcp_store import TCPStore

    deadline = time.time() + timeout_s
    while True:
        try:
            return TCPStore(args.master_addr, args.restart_coordinator_port,
                            timeout_s=timeout_s)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.5)


class _RestartStore:
    """Reconnecting client: a transient socket error (timeout, reset) must
    not permanently blind a node to remote failures — each op retries once
    on a fresh connection before giving up."""

    def __init__(self, args, connect_timeout_s: float = 60.0):
        self._args = args
        self._client = _connect_restart_store(args, connect_timeout_s)

    def _retry(self, op):
        try:
            return op(self._client)
        except (ConnectionError, OSError):
            self._client = _connect_restart_store(self._args, timeout_s=5.0)
            return op(self._client)

    def set(self, key, value):
        return self._retry(lambda c: c.set(key, value))

    def get(self, key):
        return self._retry(lambda c: c.get(key))

    def mget(self, keys):
        return self._retry(lambda c: c.mget(keys))


def _store_barrier(store, nnodes: int, prefix: str, timeout_s: float,
                   poll_s: float = 0.2) -> None:
    deadline = time.time() + timeout_s
    keys = [f"{prefix}/{r}" for r in range(nnodes)]
    while True:
        if all(v is not None for v in store.mget(keys)):
            return
        if time.time() > deadline:
            raise RuntimeError(
                f"restart barrier {prefix!r} timed out after {timeout_s:.0f}s "
                f"waiting for {nnodes} nodes"
            )
        time.sleep(poll_s)


def monitor_multinode(args, procs, store, attempt: int) -> int:
    """Like :func:`monitor`, but a failure ANYWHERE in the job surfaces
    here: local failures are published to the per-attempt fail flag, and
    the flag is polled so remote failures kill this node's gang too."""
    fail_key = f"restart/fail/{attempt}"
    store_down_since = None
    while True:
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            logger.warning("local worker failed (exit %d); publishing "
                           "fail flag for attempt %d", failed[0], attempt)
            try:
                store.set(fail_key, str(args.node_rank))
            except (ConnectionError, OSError):
                logger.warning("restart store unreachable while publishing")
            kill_gang(procs)
            raise _GangFailure(failed[0])
        remote = None
        # poll remote failures; after repeated store loss back off to one
        # probe per 30 s (the coordinator store is gone when node 0
        # finished or died — a wedge here still dies via the worker
        # watchdog -> local failure path)
        if (
            store_down_since is None
            or time.time() - store_down_since > 30.0
        ):
            try:
                remote = store.get(fail_key)
                if store_down_since is not None:
                    logger.info("restart store reachable again")
                store_down_since = None
            except (ConnectionError, OSError):
                if store_down_since is None:
                    logger.warning("restart store unreachable; monitoring "
                                   "locally (reprobe every 30 s)")
                store_down_since = time.time()
        if remote is not None:
            logger.warning("node %s reported failure; killing local gang",
                           remote.decode())
            kill_gang(procs)
            raise _GangFailure(1)
        if all(c == 0 for c in codes):
            return 0
        time.sleep(args.monitor_interval)


def run_multinode(args) -> int:
    """Coordinated multi-node gang restart (reference elastic_launch
    restarts the whole multi-node gang on any failure, run.py:116-129).
    Per attempt: ready barrier -> spawn -> monitor(+fail flag) -> on any
    failure everyone kills, re-barriers, respawns."""
    from ..contrib.utils.tcp_store import TCPStoreServer

    server = None
    if args.node_rank == 0:
        # bind on all interfaces so peer nodes can reach the store
        server = TCPStoreServer(host="0.0.0.0",
                                port=args.restart_coordinator_port)
    try:
        store = _RestartStore(args)
        attempt = 0
        while True:
            try:
                store.set(f"restart/ready/{attempt}/{args.node_rank}", b"1")
                _store_barrier(store, args.nnodes_int,
                               f"restart/ready/{attempt}",
                               args.restart_barrier_timeout)
            except (ConnectionError, OSError, RuntimeError) as e:
                # a peer exited the protocol (success or exhausted
                # restarts) and the store/barrier is gone: restarting
                # alone would wedge in collectives — give up cleanly
                logger.error(
                    "restart coordination lost at attempt %d (%s); "
                    "cannot restart without all nodes", attempt, e,
                )
                return 1
            procs = spawn_gang(args)
            try:
                rc = monitor_multinode(args, procs, store, attempt)
                # done barrier: node 0 must keep the store alive until
                # every node's monitor stopped polling it
                try:
                    store.set(f"restart/done/{args.node_rank}", b"1")
                    if server is not None:
                        _store_barrier(store, args.nnodes_int,
                                       "restart/done", timeout_s=30.0)
                except Exception:  # noqa: BLE001 - teardown is best-effort
                    pass
                return rc
            except _GangFailure as f:
                attempt += 1
                if attempt > args.max_restarts:
                    logger.error(
                        "gang failed (exit %d); max_restarts=%d exhausted",
                        f.code, args.max_restarts,
                    )
                    return f.code
                logger.warning(
                    "gang failed (exit %d); coordinated restart %d/%d",
                    f.code, attempt, args.max_restarts,
                )
            except KeyboardInterrupt:
                kill_gang(procs)
                return 130
    finally:
        if server is not None:
            server.stop()


def run(args) -> int:
    if args.nnodes_int > 1 and args.max_restarts > 0:
        return run_multinode(args)
    attempt = 0
    while True:
        procs = spawn_gang(args)
        try:
            return monitor(args, procs)
        except _GangFailure as f:
            attempt += 1
            if attempt > args.max_restarts:
                logger.error(
                    "worker failed (exit %d); max_restarts=%d exhausted",
                    f.code, args.max_restarts,
                )
                return f.code
            logger.warning(
                "worker failed (exit %d); gang restart %d/%d",
                f.code, attempt, args.max_restarts,
            )
        except KeyboardInterrupt:
            kill_gang(procs)
            return 130


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    return run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
