"""Process launcher with gang restart.

Counterpart of /root/reference/bagua/distributed/run.py (torchelastic wrapper:
Bagua flags + env injection :360-398,578-600, elastic_launch with gang-restart
semantics :116-129,603-628) and the legacy subprocess launcher ``launch.py``.

TPU shape: one JAX process per host drives all local chips, so
``--nproc_per_node`` defaults to 1 (it exists for CPU-simulation runs and
hosts with multiple isolated accelerator sets).  Rendezvous is the JAX
coordination service (``BAGUA_COORDINATOR_ADDR`` consumed by
``bagua_tpu.init_process_group``) instead of a c10d store.  Elastic behavior
is the honest XLA equivalent of torchelastic's: ANY worker failure kills the
whole gang and restarts it up to ``--max_restarts``, and workers resume from
the latest checkpoint (:mod:`bagua_tpu.checkpoint`).  In-flight world-size
*resizing* is impossible under XLA's static SPMD compilation, so elastic
``--nnodes MIN:MAX`` resizes at the only honest point — the restart
boundary: each attempt is a rendezvous round through
:mod:`bagua_tpu.elastic` that admits whoever re-registers within the join
window and respawns the gang at the renegotiated world size.

Multi-node gang restart (reference run.py:116-129 restarts the whole
multi-node gang via the c10d rendezvous): each node's launcher coordinates
through a tiny KV store (node 0 hosts a :class:`TCPStoreServer` on
``--restart_coordinator_port``).  Fixed-size jobs: a node observing a local
worker failure publishes a per-attempt failure flag; every launcher polls
it, kills its own gang, joins a per-attempt ready barrier, and respawns
together — so survivors never sit wedged in collectives while one node
restarts alone.  Elastic jobs replace the fixed-size barrier with the
membership subsystem: lease heartbeats detect silently lost nodes, standby
joins force coordinated resizes, and epoch-fenced keys keep zombies from a
previous attempt out of the current one.
"""

from __future__ import annotations

import argparse
import concurrent.futures as _futures
import logging
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from .. import env as _env

logger = logging.getLogger("bagua_tpu.launcher")

# Errors that mean "this store connection is dead, get a new one".
# TimeoutError needs BOTH spellings: the builtin (an OSError subclass
# since 3.10) and futures-style timeouts, which store clients can raise
# as a NON-OSError class on older interpreters — a timed-out socket is
# as dead as a reset one either way.
_STORE_RETRY_ERRORS = (
    ConnectionError, OSError, TimeoutError, _futures.TimeoutError,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        "python -m bagua_tpu.distributed.run",
        description="bagua_tpu launcher (reference: bagua.distributed.run)",
    )
    p.add_argument("--nnodes", type=str, default="1",
                   help="number of nodes: a fixed count, or MIN:MAX for "
                        "elastic mode — each restart attempt renegotiates "
                        "the world size to whoever rejoins within the join "
                        "window (resizing happens at restart boundaries; "
                        "XLA cannot resize a running world)")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="JAX processes per node (default 1: one process "
                        "drives all local chips)")
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29400)
    p.add_argument("--max_restarts", type=int, default=None,
                   help="gang restarts after a worker failure (default 3 "
                        "single-node, 0 multi-node; multi-node restarts are "
                        "coordinated through the restart KV store)")
    p.add_argument("--monitor_interval", type=float, default=1.0)
    p.add_argument("--restart_coordinator_port", type=int, default=None,
                   help="KV-store port for coordinated multi-node restarts "
                        "(default master_port + 1; node 0 hosts it)")
    p.add_argument("--restart_barrier_timeout", type=float, default=300.0,
                   help="seconds to wait for every node at a restart barrier "
                        "(elastic mode: rendezvous-round timeout)")
    p.add_argument("--join_window", type=float, default=None,
                   help="elastic: seconds a rendezvous round stays open for "
                        "nodes to (re)register (default "
                        "$BAGUA_ELASTIC_JOIN_WINDOW_S or 30); rounds close "
                        "early when every expected survivor is back")
    p.add_argument("--lease_ttl", type=float, default=None,
                   help="elastic: seconds without a heartbeat before a "
                        "node's lease expires and the gang regroups without "
                        "it (default $BAGUA_ELASTIC_LEASE_TTL_S or 15)")
    # Bagua flags (reference run.py:360-398)
    p.add_argument("--bagua_service_port", type=int, default=29500)
    p.add_argument("--default_bucket_size", type=int, default=10 * 1024 ** 2)
    p.add_argument("--autotune_level", type=int, default=0)
    p.add_argument("--autotune_max_samples", type=int, default=60)
    p.add_argument("--autotune_sampling_confidence_time", type=float, default=5.0)
    p.add_argument("--autotune_warmup_time", type=float, default=30.0)
    p.add_argument("--is_output_autotune_log", action="store_true")
    p.add_argument("--autotune_algorithm", action="store_true",
                   help="let the autotuner search over algorithm families")
    p.add_argument("--simulate_cpu_devices", type=int, default=0,
                   help="force JAX onto N virtual CPU devices (testing)")
    p.add_argument("--no_python", action="store_true",
                   help="run training_script directly instead of "
                        "`python training_script`")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if ":" in args.nnodes:
        lo, _, hi = args.nnodes.partition(":")
        try:
            args.min_nnodes, args.max_nnodes = int(lo), int(hi)
        except ValueError:
            p.error(f"--nnodes {args.nnodes!r}: expected N or MIN:MAX")
        if not 1 <= args.min_nnodes <= args.max_nnodes:
            p.error(f"--nnodes {args.nnodes!r}: need 1 <= MIN <= MAX")
        args.elastic = True
        args.nnodes_int = args.max_nnodes
        if not 0 <= args.node_rank < args.max_nnodes:
            p.error(f"--node_rank {args.node_rank} outside elastic id range "
                    f"[0, {args.max_nnodes}) — in elastic mode --node_rank "
                    "is the node's stable identity slot")
    else:
        args.elastic = False
        args.nnodes_int = int(args.nnodes)
        args.min_nnodes = args.max_nnodes = args.nnodes_int
    if args.join_window is None:
        args.join_window = _env.get_elastic_join_window_s()
    if args.lease_ttl is None:
        args.lease_ttl = _env.get_elastic_lease_ttl_s()
    if args.max_restarts is None:
        # multi-node fixed-size default stays 0: coordinated restart
        # requires every node's launcher to use the same max_restarts > 0.
        # Elastic mode IS the coordinated protocol, so it defaults on.
        args.max_restarts = 3 if (args.nnodes_int == 1 or args.elastic) else 0
    if args.restart_coordinator_port is None:
        args.restart_coordinator_port = args.master_port + 1
    return args


def _health_beacon_path(args, local_rank: Optional[int] = None) -> str:
    """Health beacon file: keyed by the restart-store port (one job) and
    the stable node id, so concurrent jobs on one host cannot cross-read
    each other's beacons.  One file PER local rank (``local_rank`` set):
    every worker writes only its own snapshot, so a shared file would be
    last-writer-wins and hide all but one worker's events from the fence;
    the heartbeat merges them via ``merged_health_source``."""
    import tempfile

    base = os.path.join(
        tempfile.gettempdir(),
        f"bagua_health_{args.restart_coordinator_port}_{args.node_rank}.json",
    )
    return base if local_rank is None else f"{base}.r{local_rank}"


def _health_beacon_paths(args) -> List[str]:
    """Every local worker's beacon file for this node."""
    return [
        _health_beacon_path(args, i) for i in range(args.nproc_per_node)
    ]


def build_env(args, local_rank: int, spec=None,
              quarantined_ckpt_paths=None) -> dict:
    """Reference ``set_bagua_env`` (run.py:578-600) + rendezvous env.

    ``spec`` (elastic mode): the round's renegotiated
    :class:`~bagua_tpu.elastic.membership.WorldSpec` — world size and this
    node's DENSE rank come from it instead of the fixed ``--nnodes`` /
    ``--node_rank``, and the ``BAGUA_ELASTIC_*`` block is injected so
    workers (and the watchdog's leave-intent path) can reach the
    membership registry."""
    env = dict(os.environ)
    if spec is None:
        nnodes, node_rank = args.nnodes_int, args.node_rank
    else:
        nnodes, node_rank = spec.nnodes, spec.rank_of(args.node_rank)
    world_size = nnodes * args.nproc_per_node
    rank = node_rank * args.nproc_per_node + local_rank
    env.update(
        RANK=str(rank),
        WORLD_SIZE=str(world_size),
        LOCAL_RANK=str(local_rank),
        LOCAL_WORLD_SIZE=str(args.nproc_per_node),
        NODE_RANK=str(node_rank),
        MASTER_ADDR=args.master_addr,
        MASTER_PORT=str(args.master_port),
        BAGUA_SERVICE_PORT=str(args.bagua_service_port),
        BAGUA_DEFAULT_BUCKET_SIZE=str(args.default_bucket_size),
        BAGUA_AUTOTUNE=str(args.autotune_level),
        BAGUA_AUTOTUNE_MAX_SAMPLES=str(args.autotune_max_samples),
        BAGUA_AUTOTUNE_SAMPLING_CONFIDENCE_TIME_S=str(
            args.autotune_sampling_confidence_time),
        BAGUA_AUTOTUNE_WARMUP_TIME_S=str(args.autotune_warmup_time),
        BAGUA_IS_OUTPUT_AUTOTUNE_LOG=str(int(args.is_output_autotune_log)),
        BAGUA_AUTOTUNE_ALGORITHM=str(int(args.autotune_algorithm)),
        AUTO_TUNE_SERVER_ADDR=f"{args.master_addr}:{args.bagua_service_port}",
    )
    # Workers must inherit the launcher's import environment: the spawned
    # `python training_script` has the *script's* directory as sys.path[0],
    # so an un-installed bagua_tpu (or the user's own modules in cwd) would
    # not be importable.  torchelastic effectively does the same.
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    extra_paths = [os.getcwd(), pkg_parent]
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(
        dict.fromkeys(p for p in extra_paths + prev.split(os.pathsep) if p)
    )
    if world_size > 1:
        env["BAGUA_COORDINATOR_ADDR"] = f"{args.master_addr}:{args.master_port}"
    else:
        # an elastic world renegotiated down to ONE node must not inherit a
        # stale coordinator address and wait for peers that are not coming
        env.pop("BAGUA_COORDINATOR_ADDR", None)
    if spec is not None:
        env.update(
            BAGUA_ELASTIC="1",
            BAGUA_ELASTIC_EPOCH=str(spec.epoch),
            BAGUA_ELASTIC_NODE_ID=str(args.node_rank),
            BAGUA_ELASTIC_STORE_ADDR=(
                f"{args.master_addr}:{args.restart_coordinator_port}"),
            BAGUA_ELASTIC_MIN_NNODES=str(spec.min_nnodes),
            BAGUA_ELASTIC_MAX_NNODES=str(spec.max_nnodes),
            # worker->launcher health channel: the trainer's grad-guard /
            # async-staleness events land in this worker's own beacon
            # file, and the launcher's lease heartbeat merges all local
            # beacons and carries them to the coordinator
            BAGUA_ELASTIC_HEALTH_FILE=_health_beacon_path(args, local_rank),
        )
    if quarantined_ckpt_paths:
        # autopilot storage-quarantine verdicts reach respawned workers at
        # the restart boundary: their checkpoint managers seed the
        # quarantine registry from this variable and redirect saves.
        # Newline-separated — os.pathsep would split gs:// URIs apart
        env["BAGUA_CKPT_QUARANTINED_PATHS"] = "\n".join(
            str(p) for p in quarantined_ckpt_paths
        )
    http_base = _env.get_obs_http_port()
    if http_base > 0:
        # HTTP status plane (docs/observability.md): the launcher keeps
        # the base port for itself (the coordinator's /fleet + /history);
        # each local worker gets a deterministic offset so one host's
        # processes never race each other onto the same port (a lost
        # race would still only degrade to an ephemeral port)
        env["BAGUA_OBS_HTTP_PORT"] = str(http_base + 1 + local_rank)
    if args.simulate_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_PLATFORM_NAME"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.simulate_cpu_devices}"
        )
        from ..env import sanitize_cpu_sim_env

        sanitize_cpu_sim_env(env)
    return env


def spawn_gang(args, spec=None,
               quarantined_ckpt_paths=None) -> List[subprocess.Popen]:
    cmd_prefix = [] if args.no_python else [sys.executable, "-u"]
    procs = []
    for local_rank in range(args.nproc_per_node):
        cmd = cmd_prefix + [args.training_script] + args.training_script_args
        procs.append(
            subprocess.Popen(cmd, env=build_env(
                args, local_rank, spec,
                quarantined_ckpt_paths=quarantined_ckpt_paths,
            ))
        )
    return procs


def kill_gang(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def monitor(args, procs: List[subprocess.Popen]) -> int:
    """Return exit code when all succeed; raise ``_GangFailure`` on any
    worker failure (reference gang semantics run.py:116-129)."""
    while True:
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            kill_gang(procs)
            raise _GangFailure(failed[0])
        if all(c == 0 for c in codes):
            return 0
        time.sleep(args.monitor_interval)


class _GangFailure(Exception):
    def __init__(self, code: int):
        super().__init__(f"worker failed with exit code {code}")
        self.code = code


def _store_endpoints(args):
    """Replicated restart-store endpoint list, or ``None`` (single-store
    mode).  ``BAGUA_RESTART_STORE_ENDPOINTS`` (comma-separated host:port,
    priority order — the boot primary first, standby replicas after) turns
    the restart KV store into a replicated group with client failover and
    standby-coordinator takeover (docs/robustness.md).  Unset, every code
    path below is the unchanged single-store launcher."""
    endpoints = _env.get_restart_store_endpoints()
    if not endpoints:
        return None
    from ..elastic.failover import parse_endpoints

    return parse_endpoints(endpoints)


def _connect_restart_store(args, timeout_s: float = 60.0):
    """Client to node 0's restart KV store, with connect retries (peers may
    start before the server is up).  Retries use jittered exponential
    backoff: after a gang restart every node reconnects at the same
    instant, and a fixed-interval poll keeps them in lockstep hammering
    node 0's accept queue — the jitter de-synchronizes the herd and the
    exponential cap bounds the total load.

    With ``BAGUA_RESTART_STORE_ENDPOINTS`` set this returns a
    :class:`~bagua_tpu.elastic.failover.FailoverStore` over the replica
    group instead — same op surface, but ops survive the primary dying."""
    import random

    from ..contrib.utils.tcp_store import TCPStore

    endpoints = _store_endpoints(args)
    if endpoints is not None:
        from ..elastic.failover import FailoverStore

        return FailoverStore(endpoints, connect_timeout_s=timeout_s)

    deadline = time.time() + timeout_s
    delay = 0.1
    attempts = 0
    while True:
        try:
            client = TCPStore(args.master_addr,
                              args.restart_coordinator_port,
                              timeout_s=timeout_s)
            if attempts:
                logger.info(
                    "restart store %s:%d reachable after %d retry(ies)",
                    args.master_addr, args.restart_coordinator_port,
                    attempts,
                )
            return client
        except OSError as e:
            attempts += 1
            remaining = deadline - time.time()
            if remaining <= 0:
                # surface the whole story, not just the LAST socket error:
                # how long we tried and how often, with the final failure
                # chained as __cause__ (ECONNREFUSED = server never came
                # up; EHOSTUNREACH = wrong --master-addr; ...)
                raise ConnectionError(
                    f"restart store {args.master_addr}:"
                    f"{args.restart_coordinator_port} unreachable after "
                    f"{attempts} attempt(s) over {timeout_s:.0f}s "
                    f"(last error: {type(e).__name__}: {e})"
                ) from e
            time.sleep(min(delay * (0.5 + random.random()), remaining))
            delay = min(delay * 2, 5.0)


def _store_connect_factory(args):
    """Connection factory for background store threads (lease keeper,
    heartbeats): each thread opens its OWN client — one connection per
    thread, never a socket shared across threads."""
    return lambda: _connect_restart_store(args, timeout_s=10.0)


class _RestartStore:
    """Reconnecting client: a transient socket error (timeout, reset) must
    not permanently blind a node to remote failures — each op retries once
    on a fresh connection before giving up, logging which op it retried.

    In replicated mode (``BAGUA_RESTART_STORE_ENDPOINTS``) the client is a
    :class:`~bagua_tpu.elastic.failover.FailoverStore`, which already owns
    retry, endpoint failover, the per-op deadline budget and the chaos
    hooks — the retry-once wrapper would double-fire the ``store.op``
    fault point, so ops pass straight through."""

    def __init__(self, args, connect_timeout_s: float = 60.0):
        self._args = args
        self._failover = _store_endpoints(args) is not None
        self._client = _connect_restart_store(args, connect_timeout_s)

    @property
    def generation(self) -> int:
        """Store generation the client last observed (0 single-store)."""
        return getattr(self._client, "generation", 0)

    def _retry(self, opname, op):
        from ..faults import inject as _inject

        if self._failover:
            return op(self._client)
        try:
            _inject.maybe_raise_store_error(opname)  # chaos: store.op flake
            return op(self._client)
        except _STORE_RETRY_ERRORS as e:
            logger.warning(
                "restart store %s failed (%s: %s); retrying on a fresh "
                "connection", opname, type(e).__name__, e,
            )
            self._client = _connect_restart_store(self._args, timeout_s=5.0)
            result = op(self._client)
            if isinstance(e, _inject.InjectedFault):
                _inject.record_recovery("store.op")
            return result

    def set(self, key, value):
        return self._retry(f"set({key!r})", lambda c: c.set(key, value))

    def get(self, key):
        return self._retry(f"get({key!r})", lambda c: c.get(key))

    def mget(self, keys):
        return self._retry(f"mget[{len(keys)}]", lambda c: c.mget(keys))


def _store_barrier(store, nnodes: int, prefix: str, timeout_s: float,
                   poll_s: float = 0.2) -> None:
    deadline = time.time() + timeout_s
    keys = [f"{prefix}/{r}" for r in range(nnodes)]
    while True:
        if all(v is not None for v in store.mget(keys)):
            return
        if time.time() > deadline:
            raise RuntimeError(
                f"restart barrier {prefix!r} timed out after {timeout_s:.0f}s "
                f"waiting for {nnodes} nodes"
            )
        time.sleep(poll_s)


def monitor_multinode(args, procs, store, attempt: int) -> int:
    """Like :func:`monitor`, but a failure ANYWHERE in the job surfaces
    here: local failures are published to the per-attempt fail flag, and
    the flag is polled so remote failures kill this node's gang too."""
    fail_key = f"restart/fail/{attempt}"
    store_down_since = None
    while True:
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            logger.warning("local worker failed (exit %d); publishing "
                           "fail flag for attempt %d", failed[0], attempt)
            try:
                store.set(fail_key, str(args.node_rank))
            except (ConnectionError, OSError):
                logger.warning("restart store unreachable while publishing")
            kill_gang(procs)
            raise _GangFailure(failed[0])
        remote = None
        # poll remote failures; after repeated store loss back off to one
        # probe per 30 s (the coordinator store is gone when node 0
        # finished or died — a wedge here still dies via the worker
        # watchdog -> local failure path)
        if (
            store_down_since is None
            or time.time() - store_down_since > 30.0
        ):
            try:
                remote = store.get(fail_key)
                if store_down_since is not None:
                    logger.info("restart store reachable again")
                store_down_since = None
            except (ConnectionError, OSError):
                if store_down_since is None:
                    logger.warning("restart store unreachable; monitoring "
                                   "locally (reprobe every 30 s)")
                store_down_since = time.time()
        if remote is not None:
            logger.warning("node %s reported failure; killing local gang",
                           remote.decode())
            kill_gang(procs)
            raise _GangFailure(1)
        if all(c == 0 for c in codes):
            return 0
        time.sleep(args.monitor_interval)


def run_multinode(args) -> int:
    """Coordinated multi-node gang restart (reference elastic_launch
    restarts the whole multi-node gang on any failure, run.py:116-129).
    Per attempt: ready barrier -> spawn -> monitor(+fail flag) -> on any
    failure everyone kills, re-barriers, respawns."""
    from ..contrib.utils.tcp_store import TCPStoreServer

    server = None
    if args.node_rank == 0:
        # bind on all interfaces so peer nodes can reach the store
        server = TCPStoreServer(host="0.0.0.0",
                                port=args.restart_coordinator_port)
    try:
        store = _RestartStore(args)
        attempt = 0
        while True:
            try:
                store.set(f"restart/ready/{attempt}/{args.node_rank}", b"1")
                _store_barrier(store, args.nnodes_int,
                               f"restart/ready/{attempt}",
                               args.restart_barrier_timeout)
            except (ConnectionError, OSError, RuntimeError) as e:
                # a peer exited the protocol (success or exhausted
                # restarts) and the store/barrier is gone: restarting
                # alone would wedge in collectives — give up cleanly
                logger.error(
                    "restart coordination lost at attempt %d (%s); "
                    "cannot restart without all nodes", attempt, e,
                )
                return 1
            procs = spawn_gang(args)
            try:
                rc = monitor_multinode(args, procs, store, attempt)
                # done barrier: node 0 must keep the store alive until
                # every node's monitor stopped polling it
                try:
                    store.set(f"restart/done/{args.node_rank}", b"1")
                    if server is not None:
                        _store_barrier(store, args.nnodes_int,
                                       "restart/done", timeout_s=30.0)
                except Exception:  # noqa: BLE001 - teardown is best-effort
                    pass
                return rc
            except _GangFailure as f:
                attempt += 1
                if attempt > args.max_restarts:
                    logger.error(
                        "gang failed (exit %d); max_restarts=%d exhausted",
                        f.code, args.max_restarts,
                    )
                    return f.code
                logger.warning(
                    "gang failed (exit %d); coordinated restart %d/%d",
                    f.code, attempt, args.max_restarts,
                )
            except KeyboardInterrupt:
                kill_gang(procs)
                return 130
    finally:
        if server is not None:
            server.stop()


class _GangStop(Exception):
    """An elastic attempt ended: somebody failed, left, lost its lease, or
    asked for a resize.  Carries enough to account for the event and to
    predict who rejoins at the next round."""

    def __init__(self, kind: str, node: int, reason: str, code: int = 1,
                 rejoin: bool = True, standby=(), nodes=None):
        super().__init__(f"{kind} (node {node}): {reason}")
        self.kind = kind
        self.node = int(node)
        self.reason = reason
        self.code = code
        self.rejoin = rejoin
        self.standby = list(standby)
        # every node the event covers (one lease poll can expire several)
        self.nodes = [int(n) for n in (nodes or [node])]


def publish_health_fence(client, epoch: int, tracker, unhealthy) -> str:
    """Convert chronically unhealthy members into the ``health_fenced``
    stop event (the same epoch/resize machinery lease expiry rides) and
    leave the post-mortem artifact: a flight-recorder dump naming the
    fenced nodes and the health payloads that condemned them — the exit
    path where the operator most needs the counters.  Returns the stop
    reason.  Shared by :func:`monitor_elastic` and the chaos fence drill,
    so the drilled path IS the production path."""
    from ..elastic import membership as mb
    from ..obs.recorder import dump_flight_record

    health = {int(n): tracker.health_of(n) for n in unhealthy}
    reason = (
        "heartbeat health payload over limit "
        f"(node(s) {unhealthy}: "
        + "; ".join(f"{n}={health[int(n)]}" for n in unhealthy) + ")"
    )
    client.publish_stop(
        epoch, mb.STOP_HEALTH, unhealthy[0], reason,
        rejoin=False, nodes=unhealthy,
    )
    dump_flight_record(
        "health_fence", reason=reason,
        extra={"nodes": [int(n) for n in unhealthy],
               "health": {str(n): h for n, h in health.items()}},
    )
    return reason


def _maybe_write_fleet_snapshot(spec, tracker, want_record=False,
                                historian=None, fleet_holder=None):
    """Coordinator-side fleet view: merge every member's latest heartbeat
    health payload into one ``bagua-obs-fleet-v1`` record; written to
    ``BAGUA_OBS_FLEET_OUT`` when set, and RETURNED — the autopilot
    (``want_record=True``) consumes the same record the snapshot file
    carries (one merge, one truth).  The telemetry historian (when on)
    ingests the record FIRST and augments it with per-rank ``trends`` —
    so the snapshot file, the autopilot's trend rules, and the HTTP
    plane's ``/fleet`` endpoint (fed via ``fleet_holder``) all see the
    identical trend-annotated record.  With no consumer at all the merge
    is skipped entirely (the pre-autopilot no-op monitor tick).
    Exception-free (None on failure) — the caller is the monitor loop."""
    out = _env.get_obs_fleet_out()
    if not out and not want_record and historian is None \
            and fleet_holder is None:
        return None
    try:
        from ..obs.export import build_fleet_record, write_fleet_snapshot

        record = build_fleet_record(
            spec.epoch,
            {nid: tracker.health_of(nid) for nid in spec.ranks},
        )
        if historian is not None:
            record = historian.ingest(record)
        if fleet_holder is not None:
            fleet_holder["record"] = record
        if out:
            write_fleet_snapshot(out, spec.epoch, record=record)
        return record
    except Exception as e:  # noqa: BLE001 - monitoring must not die on obs
        logger.debug("fleet snapshot not written: %s", e)
        return None


def publish_autopilot_stop(client, epoch: int, action, nodes) -> str:
    """Convert an autopilot ``fence``/``resize`` action into the
    ``health_fenced`` stop event — the SAME epoch/resize machinery lease
    expiry and the chronic-health fence ride (the fenced node's launcher
    exits, survivors regroup at n-1) — and leave the post-mortem artifact
    naming the action and its evidence.  Returns the stop reason.  Shared
    by :func:`monitor_elastic` and the chaos autopilot drills, so the
    drilled path IS the production path."""
    from ..elastic import membership as mb
    from ..obs.recorder import dump_flight_record

    reason = f"autopilot {action.kind} ({action.rule}): {action.reason}"
    client.publish_stop(
        epoch, mb.STOP_HEALTH, nodes[0], reason, rejoin=False, nodes=nodes,
    )
    dump_flight_record(
        "health_fence", reason=reason,
        extra={"nodes": [int(n) for n in nodes],
               "autopilot_action": action.to_json()},
    )
    return reason


def _build_coordinator_stack(args, store, client):
    """Everything the coordinator role needs beyond plain membership:
    rendezvous coordinator, autopilot engine, telemetry historian, the
    fleet-record holder and the HTTP status plane.  ONE builder shared by
    the boot-time coordinator and a promoted standby — the takeover path
    constructs the exact stack the primary ran, and because the engine and
    historian load their state from the (replicated) restart store at
    construction, cooldowns/rungs/quarantines and trend windows RESUME on
    the new coordinator instead of resetting.  Returns
    ``(coordinator, autopilot, historian, fleet_holder, http_server)``."""
    from ..elastic.coordinator import ElasticCoordinator

    coordinator = ElasticCoordinator(
        client, args.min_nnodes, args.max_nnodes,
        args.master_addr, args.master_port,
        join_window_s=args.join_window,
        timeout_s=args.restart_barrier_timeout,
    )
    autopilot = None
    if _env.get_autopilot_mode() != "off":
        # ONE engine across every epoch of this coordinator's life; its
        # policy state additionally persists through the restart store, so
        # a RELAUNCHED (or takeover-promoted) coordinator resumes with
        # cooldowns/rung/quarantines intact instead of re-firing a
        # cooled-down action
        from ..autopilot import AutopilotEngine, default_engine_actuators

        autopilot = AutopilotEngine(
            actuators=default_engine_actuators(
                autotune_addr=(f"{args.master_addr}:"
                               f"{args.bagua_service_port}"),
            ),
            store=store,
        )
        logger.info("fleet autopilot: %s mode", autopilot.config.mode)
    # fleet telemetry historian (docs/observability.md): ONE set of
    # time-series rings across every epoch, persisted through the restart
    # store so a relaunched coordinator keeps its trend windows instead of
    # re-earning them; a misconfigured knob degrades to "historian off"
    # with a warning, never a dead coordinator
    from ..obs.historian import maybe_build_historian

    historian = maybe_build_historian(store=store)
    if historian is not None:
        logger.info("telemetry historian: on (window %.0fs, "
                    "%d samples/series)", historian.window_s,
                    historian.capacity)
    fleet_holder = None
    http_server = None
    if _env.get_obs_http_port() > 0:
        # HTTP status plane: the coordinator serves the fleet routes
        # (/fleet from the latest monitor-tick merge, /history from the
        # historian) on top of the per-process ones; workers start their
        # own servers at bring-up on the build_env-offset ports.  On a
        # promoted standby whose launcher already runs the global server,
        # this re-attaches the fleet provider + historian to it — the
        # takeover's /fleet + /history re-open.
        from ..obs.http import maybe_start_global_http_server

        fleet_holder = {"record": None}
        http_server = maybe_start_global_http_server(
            fleet_provider=lambda: fleet_holder["record"],
            historian=historian,
        )
    return coordinator, autopilot, historian, fleet_holder, http_server


class _PromotionHandle:
    """Standby-launcher takeover state.

    Owns the :class:`~bagua_tpu.elastic.failover.StandbyCoordinatorWatch`
    (which runs the store election in the background) and, once the watch
    wins, finishes the launcher-side half of the takeover:

    1. build the full coordinator stack over the replicated store — the
       autopilot engine and historian constructors load their persisted
       state, so policy cooldowns and trend windows resume;
    2. start renewing the leadership lease under OUR node id;
    3. when promotion lands mid-epoch, hand back a
       :class:`~bagua_tpu.elastic.membership.LeaseTracker` for the current
       spec, RE-ARMED with a takeover grace window — a coordinator blip
       must not mass-expire every healthy worker lease (their heartbeats
       never stopped; it was the OBSERVER that went away)."""

    def __init__(self, args, store, client, watch):
        self.args = args
        self.store = store
        self.client = client
        self.watch = watch
        self.coordinator = None
        self.autopilot = None
        self.historian = None
        self.fleet_holder = None
        self.http_server = None
        self.keeper = None
        self.completed = False

    @property
    def pending(self) -> bool:
        """The watch won the store election; the launcher-side takeover
        has not happened yet."""
        return not self.completed and self.watch.promoted

    def complete(self, spec=None):
        """Finish the takeover.  Returns the re-armed lease tracker for
        ``spec`` (mid-epoch promotion), or None when promotion lands
        between epochs and the next ``run_round`` builds the world anew."""
        from ..elastic import membership as mb
        from ..elastic.failover import CoordinatorLeaseKeeper

        args = self.args
        (self.coordinator, self.autopilot, self.historian,
         self.fleet_holder, self.http_server) = _build_coordinator_stack(
            args, self.store, self.client)
        self.keeper = CoordinatorLeaseKeeper(
            _store_connect_factory(args),
            args.node_rank, _env.get_restart_coord_lease_ttl_s(),
            generation=self.watch.store.generation,
        ).start()
        self.completed = True
        logger.warning(
            "coordinator takeover complete: node %d now runs the "
            "coordinator (store generation %d)", args.node_rank,
            self.watch.store.generation,
        )
        if spec is None:
            return None
        tracker = mb.LeaseTracker(
            self.client, spec.epoch,
            [i for i in spec.ranks if i != args.node_rank],
            ttl_s=args.lease_ttl,
            fence_unhealthy_after=(
                _env.get_elastic_fence_unhealthy() or None
            ),
            observe_only_ids=[args.node_rank],
        )
        grace = _env.get_restart_takeover_grace_s() or 2.0 * args.lease_ttl
        tracker.rearm(grace)
        return tracker

    def stop(self) -> None:
        self.watch.stop()
        if self.keeper is not None:
            self.keeper.stop()


def monitor_elastic(args, procs, client, spec, coordinator, tracker,
                    autopilot=None, historian=None,
                    fleet_holder=None, promotion=None) -> int:
    """Monitor one elastic attempt.  Every launcher: watch local workers +
    the per-epoch stop flag.  The coordinator additionally: expire silent
    members' leases, scan for standby joiners (scale-up requests) — each
    converted into a stop event the whole gang observes — and, when the
    autopilot is on, feed every fleet snapshot to the policy engine and
    actuate its fence/resize verdicts through the same stop machinery."""
    from ..elastic import membership as mb

    epoch = spec.epoch
    store_down_since = None
    while True:
        if promotion is not None and promotion.pending:
            # the standby watch won the store election mid-epoch: become
            # the coordinator IN PLACE — same spec, same workers, fresh
            # tracker re-armed with the takeover grace so nobody healthy
            # gets expired while heartbeats re-converge on us
            tracker = promotion.complete(spec)
            coordinator = promotion.coordinator
            autopilot = promotion.autopilot
            historian = promotion.historian
            fleet_holder = promotion.fleet_holder
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            # a deliberate departure (watchdog exit) left a leave intent
            # under OUR id — report it as leave, not crash, so membership
            # telemetry can tell purposeful exits from silent failures
            kind, reason = mb.STOP_FAIL, f"worker exit {failed[0]}"
            try:
                leave = client.read_leave(epoch, args.node_rank)
                if leave:
                    kind, reason = mb.STOP_LEAVE, leave
                client.publish_stop(epoch, kind, args.node_rank, reason)
            except _STORE_RETRY_ERRORS:
                logger.warning("restart store unreachable while publishing")
            kill_gang(procs)
            raise _GangStop(kind, args.node_rank, reason, code=failed[0])
        if (
            store_down_since is None
            or time.time() - store_down_since > 30.0
        ):
            try:
                stop = client.read_stop(epoch)
                if store_down_since is not None:
                    logger.info("restart store reachable again")
                store_down_since = None
                if stop is not None:
                    logger.warning(
                        "stop event from node %s (%s: %s); killing local "
                        "gang", stop["node"], stop["kind"], stop["reason"],
                    )
                    kill_gang(procs)
                    raise _GangStop(
                        stop["kind"], stop["node"], stop["reason"],
                        rejoin=stop.get("rejoin", True),
                        nodes=stop.get("nodes"),
                    )
                if tracker is not None:
                    expired = tracker.poll()
                    if expired:
                        reason = (
                            f"no heartbeat for {args.lease_ttl:.0f}s "
                            f"(node(s) {expired})"
                        )
                        client.publish_stop(
                            epoch, mb.STOP_LEASE_EXPIRED, expired[0],
                            reason, rejoin=False, nodes=expired,
                        )
                        kill_gang(procs)
                        raise _GangStop(
                            mb.STOP_LEASE_EXPIRED, expired[0], reason,
                            rejoin=False, nodes=expired,
                        )
                    fleet_record = _maybe_write_fleet_snapshot(
                        spec, tracker, want_record=autopilot is not None,
                        historian=historian, fleet_holder=fleet_holder)
                    if autopilot is not None and fleet_record is not None:
                        # the policy engine evaluates the SAME merged view
                        # the snapshot file carries; it actuates the
                        # side-channel kinds (retune hints, quarantine)
                        # itself and hands control-flow kinds back —
                        # fence/resize must raise this loop's gang stop
                        for action in autopilot.observe_snapshot(
                                fleet_record):
                            if autopilot.config.mode != "act":
                                continue
                            if action.kind in ("fence", "resize"):
                                nodes = [int(n) for n in (action.target
                                                          or [])
                                         if int(n) in spec.ranks]
                                if not nodes:
                                    continue
                                reason = publish_autopilot_stop(
                                    client, epoch, action, nodes)
                                autopilot.note_actuated(action)
                                kill_gang(procs)
                                raise _GangStop(
                                    mb.STOP_HEALTH, nodes[0], reason,
                                    rejoin=False, nodes=nodes,
                                )
                    unhealthy = tracker.unhealthy_members()
                    if unhealthy:
                        reason = publish_health_fence(
                            client, epoch, tracker, unhealthy
                        )
                        kill_gang(procs)
                        raise _GangStop(
                            mb.STOP_HEALTH, unhealthy[0], reason,
                            rejoin=False, nodes=unhealthy,
                        )
                    standby = coordinator.standby_ids(spec)
                    if standby and spec.nnodes < spec.max_nnodes:
                        grow = standby[: spec.max_nnodes - spec.nnodes]
                        reason = f"standby node(s) {grow} joined; scaling up"
                        client.publish_stop(
                            epoch, mb.STOP_RESIZE, grow[0], reason)
                        kill_gang(procs)
                        raise _GangStop(
                            mb.STOP_RESIZE, grow[0], reason, standby=grow)
            except _STORE_RETRY_ERRORS:
                if store_down_since is None:
                    logger.warning("restart store unreachable; monitoring "
                                   "locally (reprobe every 30 s)")
                store_down_since = time.time()
        if all(c == 0 for c in codes):
            return 0
        time.sleep(args.monitor_interval)


def _dump_elastic_telemetry(transitions) -> None:
    """Write membership counters + the transition log where the operator
    (or a drill script) asked for them: $BAGUA_ELASTIC_TELEMETRY_OUT."""
    from ..telemetry import counters

    logger.info("elastic membership counters: %s", counters.snapshot())
    out = _env.get_elastic_telemetry_out()
    if not out:
        return
    try:
        import json

        with open(out, "w") as f:
            json.dump(
                {"counters": counters.snapshot(), "transitions": transitions},
                f, indent=1,
            )
    except OSError as e:
        logger.warning("could not write elastic telemetry to %s: %s", out, e)


def run_elastic(args) -> int:
    """Elastic multi-node launch (``--nnodes MIN:MAX``): every restart
    attempt is a rendezvous round through the elastic coordinator instead
    of a fixed-size barrier.  The store-hosting launcher (node id 0) runs
    the coordinator and is the fixed point — it cannot be resized away;
    every other node can die (lease expiry / crash → regroup at n-1) or
    appear (standby join → coordinated resize at the attempt boundary)."""
    from ..contrib.utils.tcp_store import TCPStoreServer
    from ..elastic import membership as mb
    from ..elastic.coordinator import (
        ExcludedFromRound,
        Halted,
        RendezvousTimeout,
        join_round,
        wait_for_next_epoch,
    )
    from ..telemetry import counters

    endpoints = _store_endpoints(args)
    server = None
    http_server = None
    keeper = None
    promotion = None
    if endpoints is None:
        is_coord = args.node_rank == 0
        if is_coord:
            server = TCPStoreServer(host="0.0.0.0",
                                    port=args.restart_coordinator_port)
    else:
        # replicated restart store (docs/robustness.md): the first
        # len(endpoints) node ids each host one store server — id 0 boots
        # as the primary, the rest as replication followers.  A RELAUNCHED
        # id 0 probes its peers first (_recover_from_peers): it adopts the
        # surviving replicated state and, if a takeover already moved the
        # primary role, starts demoted — leadership is a lease in the
        # store, not a property of the node id.
        if args.node_rank < len(endpoints):
            server = TCPStoreServer(
                host="0.0.0.0", port=endpoints[args.node_rank][1],
                peers=[e for i, e in enumerate(endpoints)
                       if i != args.node_rank],
                role="primary" if args.node_rank == 0 else "standby",
            )
        is_coord = args.node_rank == 0 and (server is None
                                            or server.is_primary)
    transitions: List[dict] = []
    stop_counter = {
        mb.STOP_FAIL: "elastic/failures",
        mb.STOP_LEASE_EXPIRED: "elastic/lease_expired",
        mb.STOP_LEAVE: "elastic/leaves",
        mb.STOP_RESIZE: "elastic/resizes",
        mb.STOP_HEALTH: "elastic/health_fenced",
    }
    try:
        store = _RestartStore(args)
        client = mb.MembershipClient(store, args.node_rank, args.max_nnodes)
        coordinator = None
        autopilot = None
        historian = None
        fleet_holder = None
        if is_coord:
            (coordinator, autopilot, historian, fleet_holder,
             http_server) = _build_coordinator_stack(args, store, client)
        if endpoints is not None:
            from ..elastic.failover import (
                CoordinatorLeaseKeeper,
                StandbyCoordinatorWatch,
            )

            coord_ttl = _env.get_restart_coord_lease_ttl_s()
            if is_coord:
                keeper = CoordinatorLeaseKeeper(
                    _store_connect_factory(args),
                    args.node_rank, coord_ttl,
                    generation=store.generation,
                ).start()
            elif server is not None:
                # standby coordinator: every follower-store host watches
                # the leadership lease from its own connection; the watch
                # wins the takeover in the STORE (generation fence), the
                # _PromotionHandle finishes the launcher side
                promotion = _PromotionHandle(
                    args, store, client,
                    StandbyCoordinatorWatch(
                        _connect_restart_store(args, timeout_s=60.0),
                        args.node_rank, args.node_rank, coord_ttl,
                    ).start(),
                )
        epoch = 0
        restarts_used = 0
        expect = None
        while True:
            if promotion is not None and promotion.completed \
                    and not is_coord:
                # takeover landed (mid-epoch in monitor_elastic, or while
                # waiting out a dead primary below): this launcher runs
                # every round from here on as the coordinator
                is_coord = True
                coordinator = promotion.coordinator
                autopilot = promotion.autopilot
                historian = promotion.historian
                fleet_holder = promotion.fleet_holder
                http_server = promotion.http_server
            try:
                from ..obs.spans import trace_span

                with trace_span("elastic/rendezvous", epoch=epoch,
                                role="coordinator" if is_coord else "member"):
                    if is_coord:
                        spec = coordinator.run_round(epoch, expect=expect)
                    elif promotion is None:
                        spec = join_round(
                            client, epoch,
                            timeout_s=args.restart_barrier_timeout,
                        )
                        epoch = spec.epoch
                    else:
                        # a standby-store host must not sit out the whole
                        # rendezvous timeout inside join_round: when the
                        # primary dies mid-rendezvous the watch promotes
                        # US, and only the promoted node can publish the
                        # epoch everyone (including us) is waiting for —
                        # so wait in short slices and surface promotion
                        deadline = time.monotonic() + \
                            args.restart_barrier_timeout
                        while True:
                            try:
                                spec = join_round(client, epoch,
                                                  timeout_s=5.0)
                                break
                            except RendezvousTimeout:
                                if promotion.pending or \
                                        time.monotonic() > deadline:
                                    raise
                        epoch = spec.epoch
            except ExcludedFromRound as e:
                logger.warning("%s", e)
                counters.incr("elastic/excluded")
                try:
                    epoch = wait_for_next_epoch(
                        client, e.epoch,
                        timeout_s=args.restart_barrier_timeout,
                    )
                except Halted as h:
                    return int(h.verdict.get("code", 1))
                except RendezvousTimeout as e2:
                    logger.error("standby wait ended: %s", e2)
                    return 1
                continue
            except Halted as h:
                logger.info("job already decided: %s", h)
                return int(h.verdict.get("code", 1))
            except (RendezvousTimeout, *_STORE_RETRY_ERRORS) as e:
                if promotion is not None and promotion.pending:
                    logger.warning(
                        "rendezvous interrupted at epoch %d (%s); this "
                        "standby was promoted — rerunning the round as "
                        "the coordinator", epoch, e,
                    )
                    promotion.complete()
                    continue
                logger.error("rendezvous failed at epoch %d: %s", epoch, e)
                if is_coord:
                    try:
                        client.publish_halt(1, f"rendezvous failed: {e}")
                    except Exception:  # noqa: BLE001 - teardown best-effort
                        pass
                return 1
            counters.incr("elastic/rounds")
            counters.set_gauge("elastic/world_nnodes", spec.nnodes)
            transitions.append({
                "epoch": spec.epoch, "nnodes": spec.nnodes,
                "members": sorted(spec.ranks),
            })
            logger.info(
                "elastic epoch %d: %d node(s), node id %d -> rank %d",
                spec.epoch, spec.nnodes, args.node_rank,
                spec.rank_of(args.node_rank),
            )
            # fresh attempt, fresh health: a stale beacon from the previous
            # epoch's workers would instantly re-report old events (and with
            # fencing armed, re-fence a node that just restarted clean)
            beacons = _health_beacon_paths(args)
            for beacon in beacons:
                try:
                    os.unlink(beacon)
                except OSError:
                    pass
            hb = mb.LeaseHeartbeat(
                _store_connect_factory(args),
                args.node_rank, spec.epoch,
                interval_s=max(0.5, args.lease_ttl / 5.0),
                max_nnodes=args.max_nnodes,
                # the launcher beats, the WORKERS train: their grad-guard /
                # async-staleness events ride per-rank beacon files, merged
                # into one node payload per beat
                health_source=mb.merged_health_source(beacons),
            ).start()
            tracker = None
            if is_coord:
                tracker = mb.LeaseTracker(
                    client, spec.epoch,
                    [i for i in spec.ranks if i != args.node_rank],
                    ttl_s=args.lease_ttl,
                    fence_unhealthy_after=(
                        _env.get_elastic_fence_unhealthy() or None
                    ),
                    # the coordinator can't lease-expire itself, but its
                    # own workers' health must still reach the fence
                    observe_only_ids=[args.node_rank],
                )
            # EVERY launcher (not just the coordinator's) reads the
            # act-mode engine's actuated storage-quarantine verdicts off
            # the shared restart store: the node whose workers write to
            # the rotting storage is usually NOT the coordinator node
            from ..autopilot.engine import read_actuated_quarantines

            procs = spawn_gang(
                args, spec,
                quarantined_ckpt_paths=read_actuated_quarantines(store),
            )
            try:
                rc = monitor_elastic(
                    args, procs, client, spec, coordinator, tracker,
                    autopilot=autopilot, historian=historian,
                    fleet_holder=fleet_holder, promotion=promotion)
                try:
                    client.publish_done(spec.epoch)
                    # a takeover during the FINAL epoch makes us the
                    # coordinator mid-monitor: the teardown duty moved too
                    if is_coord or (promotion is not None
                                    and promotion.completed):
                        # keep the store alive until every member's monitor
                        # stopped polling it, then post the verdict
                        deadline = time.time() + 30.0
                        members = list(spec.ranks)
                        while time.time() < deadline:
                            if len(client.done_ids(spec.epoch, members)) == \
                                    len(members):
                                break
                            time.sleep(0.2)
                        client.publish_halt(0, "success")
                except Exception:  # noqa: BLE001 - teardown is best-effort
                    pass
                return rc
            except _GangStop as s:
                counters.incr(stop_counter.get(s.kind, "elastic/failures"))
                transitions[-1]["stop"] = {
                    "kind": s.kind, "node": s.node, "reason": s.reason,
                }
                survivors = set(spec.ranks)
                if not s.rejoin:
                    survivors -= set(s.nodes)
                expect = survivors | set(s.standby)
                epoch = spec.epoch + 1
                if s.kind == mb.STOP_HEALTH and args.node_rank in s.nodes:
                    # this node was fenced for chronic bad health — exiting
                    # (instead of waiting as a standby) keeps it from
                    # bouncing back into the fleet it was just removed from;
                    # an operator restarts it deliberately after diagnosis
                    logger.error(
                        "this node was health-fenced at epoch %d (%s); "
                        "exiting", spec.epoch, s.reason,
                    )
                    # the fenced node's own post-mortem: its launcher
                    # counters flush through the flight recorder (the
                    # coordinator already dumped the fencing side)
                    from ..obs.recorder import dump_flight_record

                    dump_flight_record(
                        "health_fence",
                        reason=f"this node fenced: {s.reason}",
                        extra={"nodes": [int(n) for n in s.nodes]},
                    )
                    if is_coord:
                        # the membership store lives in this process, so
                        # fencing the coordinator halts the whole job:
                        # publish the verdict and give survivors a beat to
                        # read it before the store dies with us
                        try:
                            client.publish_halt(
                                4,
                                f"coordinator node health-fenced: {s.reason}",
                            )
                            time.sleep(3.0)
                        except Exception:  # noqa: BLE001 - teardown
                            pass
                    return 4
                if s.kind == mb.STOP_RESIZE:
                    logger.warning(
                        "coordinated resize at epoch %d (%s); regrouping "
                        "as epoch %d", spec.epoch, s.reason, epoch,
                    )
                    continue  # resizes are free: not a failure
                restarts_used += 1
                counters.incr("elastic/restarts")
                if restarts_used > args.max_restarts:
                    logger.error(
                        "gang stopped (%s); max_restarts=%d exhausted",
                        s.kind, args.max_restarts,
                    )
                    if is_coord:
                        try:
                            client.publish_halt(
                                s.code or 1, "max_restarts exhausted")
                        except Exception:  # noqa: BLE001
                            pass
                    return s.code or 1
                logger.warning(
                    "gang stopped at epoch %d (%s from node %d); elastic "
                    "restart %d/%d as epoch %d", spec.epoch, s.kind,
                    s.node, restarts_used, args.max_restarts, epoch,
                )
            except KeyboardInterrupt:
                try:
                    client.publish_leave(spec.epoch, "keyboard interrupt")
                    client.publish_stop(
                        spec.epoch, mb.STOP_LEAVE, args.node_rank,
                        "keyboard interrupt", rejoin=False,
                    )
                except Exception:  # noqa: BLE001 - dying anyway
                    pass
                kill_gang(procs)
                return 130
            finally:
                hb.stop()
    finally:
        _dump_elastic_telemetry(transitions)
        if keeper is not None:
            keeper.stop()
        if promotion is not None:
            promotion.stop()
            if http_server is None:
                # promoted mid-epoch and exited before the loop top
                # refreshed the local: the takeover's server still runs
                http_server = promotion.http_server
        if http_server is not None:
            http_server.stop()
        if server is not None:
            server.stop()


def run(args) -> int:
    if args.elastic:
        return run_elastic(args)
    if args.nnodes_int > 1 and args.max_restarts > 0:
        return run_multinode(args)
    attempt = 0
    while True:
        procs = spawn_gang(args)
        try:
            return monitor(args, procs)
        except _GangFailure as f:
            attempt += 1
            if attempt > args.max_restarts:
                logger.error(
                    "worker failed (exit %d); max_restarts=%d exhausted",
                    f.code, args.max_restarts,
                )
                return f.code
            logger.warning(
                "worker failed (exit %d); gang restart %d/%d",
                f.code, attempt, args.max_restarts,
            )
        except KeyboardInterrupt:
            kill_gang(procs)
            return 130


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    return run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
