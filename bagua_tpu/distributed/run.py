"""Process launcher with gang restart.

Counterpart of /root/reference/bagua/distributed/run.py (torchelastic wrapper:
Bagua flags + env injection :360-398,578-600, elastic_launch with gang-restart
semantics :116-129,603-628) and the legacy subprocess launcher ``launch.py``.

TPU shape: one JAX process per host drives all local chips, so
``--nproc_per_node`` defaults to 1 (it exists for CPU-simulation runs and
hosts with multiple isolated accelerator sets).  Rendezvous is the JAX
coordination service (``BAGUA_COORDINATOR_ADDR`` consumed by
``bagua_tpu.init_process_group``) instead of a c10d store.  Elastic behavior
is the honest XLA equivalent of torchelastic's: ANY worker failure kills the
whole gang and restarts it (same world size) up to ``--max_restarts``, and
workers resume from the latest checkpoint
(:mod:`bagua_tpu.checkpoint`) — in-flight world-size *resizing* is impossible
under XLA's static SPMD compilation, so MIN:MAX nnodes syntax is rejected
rather than silently accepted.  Gang restart is **single-node only**: this
launcher monitors its own subprocesses, so with ``--nnodes > 1`` restarts
must come from the cluster manager re-launching every node together
(``--max_restarts > 0`` is rejected there rather than silently node-local).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import time
from typing import List

logger = logging.getLogger("bagua_tpu.launcher")


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        "python -m bagua_tpu.distributed.run",
        description="bagua_tpu launcher (reference: bagua.distributed.run)",
    )
    p.add_argument("--nnodes", type=str, default="1",
                   help="number of nodes (fixed; MIN:MAX is rejected — XLA "
                        "cannot resize in flight, restart with a new value)")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="JAX processes per node (default 1: one process "
                        "drives all local chips)")
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29400)
    p.add_argument("--max_restarts", type=int, default=None,
                   help="gang restarts after a worker failure (default 3; "
                        "single-node only — multi-node defaults to 0)")
    p.add_argument("--monitor_interval", type=float, default=1.0)
    # Bagua flags (reference run.py:360-398)
    p.add_argument("--bagua_service_port", type=int, default=29500)
    p.add_argument("--default_bucket_size", type=int, default=10 * 1024 ** 2)
    p.add_argument("--autotune_level", type=int, default=0)
    p.add_argument("--autotune_max_samples", type=int, default=60)
    p.add_argument("--autotune_sampling_confidence_time", type=float, default=5.0)
    p.add_argument("--autotune_warmup_time", type=float, default=30.0)
    p.add_argument("--is_output_autotune_log", action="store_true")
    p.add_argument("--autotune_algorithm", action="store_true",
                   help="let the autotuner search over algorithm families")
    p.add_argument("--simulate_cpu_devices", type=int, default=0,
                   help="force JAX onto N virtual CPU devices (testing)")
    p.add_argument("--no_python", action="store_true",
                   help="run training_script directly instead of "
                        "`python training_script`")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if ":" in args.nnodes:
        p.error("elastic MIN:MAX nnodes is not supported on TPU — world size "
                "is fixed per launch; restart the job to resize")
    args.nnodes_int = int(args.nnodes)
    if args.nnodes_int > 1 and (args.max_restarts or 0) > 0:
        # Gang restart is node-local: this launcher only monitors its own
        # node's workers, so restarting them after a remote failure would
        # leave survivors hung in collectives and the restarted workers
        # unable to rejoin the JAX coordination service.  Multi-node
        # restart must come from the cluster manager re-launching every node.
        p.error("gang restart (--max_restarts > 0) only supports single-node "
                "launches; with --nnodes > 1 the cluster manager must "
                "restart all nodes together")
    if args.max_restarts is None:
        args.max_restarts = 3 if args.nnodes_int == 1 else 0
    return args


def build_env(args, local_rank: int) -> dict:
    """Reference ``set_bagua_env`` (run.py:578-600) + rendezvous env."""
    env = dict(os.environ)
    world_size = args.nnodes_int * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    env.update(
        RANK=str(rank),
        WORLD_SIZE=str(world_size),
        LOCAL_RANK=str(local_rank),
        LOCAL_WORLD_SIZE=str(args.nproc_per_node),
        NODE_RANK=str(args.node_rank),
        MASTER_ADDR=args.master_addr,
        MASTER_PORT=str(args.master_port),
        BAGUA_SERVICE_PORT=str(args.bagua_service_port),
        BAGUA_DEFAULT_BUCKET_SIZE=str(args.default_bucket_size),
        BAGUA_AUTOTUNE=str(args.autotune_level),
        BAGUA_AUTOTUNE_MAX_SAMPLES=str(args.autotune_max_samples),
        BAGUA_AUTOTUNE_SAMPLING_CONFIDENCE_TIME_S=str(
            args.autotune_sampling_confidence_time),
        BAGUA_AUTOTUNE_WARMUP_TIME_S=str(args.autotune_warmup_time),
        BAGUA_IS_OUTPUT_AUTOTUNE_LOG=str(int(args.is_output_autotune_log)),
        BAGUA_AUTOTUNE_ALGORITHM=str(int(args.autotune_algorithm)),
        AUTO_TUNE_SERVER_ADDR=f"{args.master_addr}:{args.bagua_service_port}",
    )
    # Workers must inherit the launcher's import environment: the spawned
    # `python training_script` has the *script's* directory as sys.path[0],
    # so an un-installed bagua_tpu (or the user's own modules in cwd) would
    # not be importable.  torchelastic effectively does the same.
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    extra_paths = [os.getcwd(), pkg_parent]
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(
        dict.fromkeys(p for p in extra_paths + prev.split(os.pathsep) if p)
    )
    if world_size > 1:
        env["BAGUA_COORDINATOR_ADDR"] = f"{args.master_addr}:{args.master_port}"
    if args.simulate_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_PLATFORM_NAME"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.simulate_cpu_devices}"
        )
        from ..env import sanitize_cpu_sim_env

        sanitize_cpu_sim_env(env)
    return env


def spawn_gang(args) -> List[subprocess.Popen]:
    cmd_prefix = [] if args.no_python else [sys.executable, "-u"]
    procs = []
    for local_rank in range(args.nproc_per_node):
        cmd = cmd_prefix + [args.training_script] + args.training_script_args
        procs.append(subprocess.Popen(cmd, env=build_env(args, local_rank)))
    return procs


def kill_gang(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def monitor(args, procs: List[subprocess.Popen]) -> int:
    """Return exit code when all succeed; raise ``_GangFailure`` on any
    worker failure (reference gang semantics run.py:116-129)."""
    while True:
        codes = [p.poll() for p in procs]
        failed = [c for c in codes if c not in (None, 0)]
        if failed:
            kill_gang(procs)
            raise _GangFailure(failed[0])
        if all(c == 0 for c in codes):
            return 0
        time.sleep(args.monitor_interval)


class _GangFailure(Exception):
    def __init__(self, code: int):
        super().__init__(f"worker failed with exit code {code}")
        self.code = code


def run(args) -> int:
    attempt = 0
    while True:
        procs = spawn_gang(args)
        try:
            return monitor(args, procs)
        except _GangFailure as f:
            attempt += 1
            if attempt > args.max_restarts:
                logger.error(
                    "worker failed (exit %d); max_restarts=%d exhausted",
                    f.code, args.max_restarts,
                )
                return f.code
            logger.warning(
                "worker failed (exit %d); gang restart %d/%d",
                f.code, attempt, args.max_restarts,
            )
        except KeyboardInterrupt:
            kill_gang(procs)
            return 130


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    return run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
