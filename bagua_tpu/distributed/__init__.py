"""Launchers (reference ``bagua/distributed/``)."""
