"""Model-parallel building blocks (reference ``bagua/torch_api/model_parallel``)."""
