"""Mixture-of-Experts with expert parallelism (reference
``bagua/torch_api/model_parallel/moe/``)."""

from .gating import top1_gating, top2_gating  # noqa: F401
from .layer import MoEMLP, moe_lm_loss_fn  # noqa: F401
