"""GShard top-1 / top-2 gating.

Counterpart of /root/reference/bagua/torch_api/model_parallel/moe/sharded_moe.py
(``top1gating`` :93, ``top2gating`` :168, capacity + load-balancing auxiliary
loss).  Re-derived from the GShard formulation (arXiv 2006.16668) rather than
ported: everything is dense one-hot einsum math — no sorting, no scatter —
so XLA lowers it to MXU-friendly matmuls with static shapes.

Shapes: ``logits`` is [tokens, n_experts]; returned ``dispatch`` is
[tokens, n_experts, capacity] (0/1), ``combine`` the same shape weighted by
the gate probability, and ``l_aux`` a scalar.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _positions_in_expert(mask: jax.Array) -> jax.Array:
    """For each (token, expert) with mask==1: how many earlier tokens chose
    this expert (its slot index in the expert's capacity buffer)."""
    return (jnp.cumsum(mask, axis=0) - 1) * mask


def _load_balancing_loss(probs: jax.Array, mask: jax.Array) -> jax.Array:
    """GShard aux loss: n_experts * Σ_e mean_t(probs_te) * mean_t(mask_te)."""
    n_experts = probs.shape[-1]
    density = mask.astype(jnp.float32).mean(axis=0)
    density_proxy = probs.mean(axis=0)
    return jnp.sum(density * density_proxy) * n_experts


def top1_gating(
    logits: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Switch-style top-1 routing with capacity dropping."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    n_experts = probs.shape[-1]
    index = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(index, n_experts, dtype=jnp.float32)
    l_aux = _load_balancing_loss(probs, mask)

    pos = _positions_in_expert(mask)
    keep = mask * (pos < capacity)
    gate = (probs * keep).sum(axis=-1)  # chosen prob; 0 for dropped tokens
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )
    combine = gate[:, None, None] * dispatch
    return dispatch, combine, l_aux


def topk_routing(
    logits: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dropless top-k routing: no capacity, no dispatch tensor.

    Returns (expert_idx [tokens, k], gate_weights [tokens, k], l_aux) with
    the same gate conventions as the capacity gates: top-1 keeps the raw
    chosen probability, top-k>1 renormalizes over the winners; the aux loss
    is computed over the top-1 assignment (GShard eq. 4).  Consumed by the
    sort + grouped-matmul (``bagua_tpu.ops.gmm``) dropless MoE path.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    n_experts = probs.shape[-1]
    gates, eidx = jax.lax.top_k(probs, k)
    mask1 = jax.nn.one_hot(eidx[:, 0], n_experts, dtype=jnp.float32)
    l_aux = _load_balancing_loss(probs, mask1)
    if k > 1:
        gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    return eidx.astype(jnp.int32), gates, l_aux


def top2_gating(
    logits: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """GShard top-2 routing: second expert chosen from the masked
    distribution, gates renormalized over the two winners."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    n_experts = probs.shape[-1]

    index1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(index1, n_experts, dtype=jnp.float32)
    probs_wo_1 = probs * (1.0 - mask1)
    index2 = jnp.argmax(probs_wo_1, axis=-1)
    mask2 = jax.nn.one_hot(index2, n_experts, dtype=jnp.float32)

    # aux loss over the top-1 assignment only (GShard eq. 4)
    l_aux = _load_balancing_loss(probs, mask1)

    # capacity: first-choice tokens fill slots before second-choice tokens
    pos1 = _positions_in_expert(mask1)
    count1 = mask1.sum(axis=0, keepdims=True)
    pos2 = _positions_in_expert(mask2) + count1 * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = (probs * keep1).sum(axis=-1)
    g2 = (probs * keep2).sum(axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    dispatch1 = keep1[:, :, None] * jax.nn.one_hot(
        pos1.astype(jnp.int32), capacity, dtype=jnp.float32
    )
    dispatch2 = keep2[:, :, None] * jax.nn.one_hot(
        pos2.astype(jnp.int32), capacity, dtype=jnp.float32
    )
    dispatch = jnp.maximum(dispatch1, dispatch2)
    combine = g1[:, None, None] * dispatch1 + g2[:, None, None] * dispatch2
    return dispatch, combine, l_aux
