"""Expert-parallel MoE layer.

Counterpart of /root/reference/bagua/torch_api/model_parallel/moe/layer.py:22
(``MoE``) + sharded_moe.py:306 (``MOELayer``: gate → einsum dispatch →
all-to-all → local experts → all-to-all → einsum combine) + experts.py
(expert params flagged so DP averaging skips them, experts.py:26-29).

TPU-first shape: the all-to-all is ``lax.all_to_all`` over an ``'ep'`` mesh
axis inside the jitted step (the reference drives
``torch.distributed.all_to_all_single`` from autograd, sharded_moe.py:77-90);
expert weights live as one leaf ``[n_experts, ...]`` sharded over ``'ep'``,
batched per-expert matmuls run on the MXU via a single einsum.  Parameters
whose name contains ``"expert"`` are excluded from the data-parallel bucket
plan by the trainer (the analog of ``param.expert`` flags).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ...parallel.mesh import axis_bound as _axis_bound
from .gating import top1_gating, top2_gating


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: tokens [batch, seq, d_model] -> same.

    Plugs into ``TransformerLM`` via ``mlp_factory``.  ``ep_size`` is the
    static expert-parallel degree (= mesh ``'ep'`` axis size); each shard owns
    ``n_experts // ep_size`` experts.  Outside shard_map (e.g. ``model.init``)
    the all-to-all is skipped and only the local expert slice is computed —
    parameter shapes are identical, so init-outside / apply-inside works.
    """

    n_experts: int
    d_ff: int
    ep_size: int = 1
    k: int = 2                      # top-k routing (1 or 2)
    capacity_factor: float = 1.25
    axis_name: str = "ep"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    #: capacity-free routing: no token is ever dropped.  Tokens are sorted by
    #: expert and run through the grouped-matmul Pallas kernel
    #: (:mod:`bagua_tpu.ops.gmm`) instead of the dense [T,E,C] dispatch
    #: einsum.  Single-shard (``ep_size == 1``) only for now.
    dropless: bool = False

    @nn.compact
    def __call__(self, x):
        assert self.n_experts % self.ep_size == 0
        n_local = self.n_experts // self.ep_size
        b, s, d = x.shape
        tokens = b * s
        xt = x.reshape(tokens, d)

        # router in f32 (small, precision-sensitive; reference TopKGate
        # casts to fp32 too, sharded_moe.py:241-303)
        logits = nn.Dense(
            self.n_experts, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32, name="router",
        )(xt.astype(jnp.float32))

        # one definition of the expert weights for both routing paths
        # (dropless forces ep_size == 1, so n_local == n_experts there)
        wi = self.param(
            "expert_wi", nn.initializers.lecun_normal(batch_axis=(0,)),
            (n_local, d, self.d_ff), self.param_dtype,
        )
        wo = self.param(
            "expert_wo", nn.initializers.lecun_normal(batch_axis=(0,)),
            (n_local, self.d_ff, d), self.param_dtype,
        )

        if self.dropless:
            if self.ep_size > 1:
                raise NotImplementedError(
                    "dropless MoE is single-shard (ep_size == 1) for now; "
                    "use the capacity path for expert parallelism"
                )
            return self._dropless(xt, logits, wi, wo).reshape(b, s, d)

        capacity = max(1, math.ceil(self.k * tokens * self.capacity_factor
                                    / self.n_experts))
        gate = top1_gating if self.k == 1 else top2_gating
        dispatch, combine, l_aux = gate(logits, capacity)
        self.sow("intermediates", "l_aux", l_aux)

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )

        inside_mesh = self.ep_size > 1 and _axis_bound(self.axis_name)
        if inside_mesh:
            # [E, C, d] -> [E/ep, ep*C, d]: expert shards receive their
            # tokens from every ep peer
            expert_in = lax.all_to_all(
                expert_in, self.axis_name, split_axis=0, concat_axis=1,
                tiled=True,
            )
        elif self.ep_size > 1:
            # init path (outside shard_map): only shapes matter
            expert_in = expert_in[:n_local]

        h = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(self.dtype)))
        out = jnp.einsum("ecf,efd->ecd", h, wo.astype(self.dtype))

        if inside_mesh:
            out = lax.all_to_all(
                out, self.axis_name, split_axis=1, concat_axis=0, tiled=True
            )
        elif self.ep_size > 1:
            out = jnp.concatenate(
                [out] + [jnp.zeros_like(out)] * (self.ep_size - 1), axis=0
            )

        y = jnp.einsum("tec,ecd->td", combine.astype(self.dtype), out)
        return y.reshape(b, s, d)

    def _dropless(self, xt, logits, wi, wo):
        """Sort-by-expert + grouped matmul: every routed (token, expert)
        pair is computed — the capacity-overflow drops of the GShard path
        (sharded_moe.py:93-238) cannot happen."""
        from ...ops.gmm import gmm
        from .gating import topk_routing

        eidx, gates, l_aux = topk_routing(logits, self.k)
        self.sow("intermediates", "l_aux", l_aux)

        flat_e = eidx.reshape(-1)                       # [T*k]
        order = jnp.argsort(flat_e)                     # stable: ties by token
        token_of_row = order // self.k
        x_rows = xt[token_of_row].astype(self.dtype)    # [T*k, d] grouped
        sizes = jnp.bincount(flat_e, length=self.n_experts)

        h = nn.silu(gmm(x_rows, wi.astype(self.dtype), sizes))
        y_rows = gmm(h, wo.astype(self.dtype), sizes)   # [T*k, d]

        w = gates.reshape(-1)[order].astype(self.dtype)
        y = jnp.zeros((xt.shape[0], xt.shape[1]), self.dtype)
        return y.at[token_of_row].add(y_rows * w[:, None])


# The exact parameter names MoEMLP creates.  Marking is by path *segment*
# equality against this set — the explicit analog of the reference's
# ``param.expert = True`` flags (experts.py:26-29) — never by substring, so a
# user param that merely contains "expert" in its name can't be silently
# pulled out of the data-parallel plan.
EXPERT_PARAM_NAMES = frozenset({"expert_wi", "expert_wo"})


def is_expert_param(name: str) -> bool:
    """True for params created by :class:`MoEMLP` (exact segment match).

    Accepts any common path spelling: dotted (``a.b.expert_wi``), slashed,
    or raw ``jax.tree_util.keystr`` output (``['a']['expert_wi']``).
    """
    import re

    return not EXPERT_PARAM_NAMES.isdisjoint(re.split(r"[\[\]'\"./]+", name))


def globalize_expert_params(params, rng, ep_size: int, is_expert=None):
    """Re-draw expert leaves at global shape for the expert-parallel trainer.

    ``model.init`` outside the mesh yields expert leaves of LOCAL shape
    ``[n_experts/ep_size, ...]`` (identical on every rank — a bad symmetric
    init).  This expands each such leaf to ``[n_experts, ...]`` with an
    independent per-expert draw; ``BaguaTrainer(expert_axis=...)`` then shards
    the leading dim over ``'ep'``.  The returned tree is only valid inside the
    trainer (direct ``model.apply`` would see a shape mismatch).
    """
    if is_expert is None:
        is_expert = is_expert_param
    init = nn.initializers.lecun_normal(batch_axis=(0,))
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        if is_expert(name) and ep_size > 1:
            rng, sub = jax.random.split(rng)
            shape = (leaf.shape[0] * ep_size,) + leaf.shape[1:]
            out.append(init(sub, shape, leaf.dtype))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def moe_lm_loss_fn(model, aux_loss_weight: float = 0.01):
    """Next-token loss + load-balancing aux loss collected from every MoE
    layer (the reference accumulates ``l_aux`` per gate, sharded_moe.py:354)."""
    import optax

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        logits, mutated = model.apply(
            {"params": params}, tokens[:, :-1], mutable=["intermediates"]
        )
        nll = optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens[:, 1:]
        ).mean()
        aux = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(mutated.get("intermediates", {})):
            aux = aux + jnp.sum(leaf)
        return nll + aux_loss_weight * aux

    return loss_fn
