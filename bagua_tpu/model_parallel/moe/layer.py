"""Expert-parallel MoE layer.

Counterpart of /root/reference/bagua/torch_api/model_parallel/moe/layer.py:22
(``MoE``) + sharded_moe.py:306 (``MOELayer``: gate → einsum dispatch →
all-to-all → local experts → all-to-all → einsum combine) + experts.py
(expert params flagged so DP averaging skips them, experts.py:26-29).

TPU-first shape: the all-to-all is ``lax.all_to_all`` over an ``'ep'`` mesh
axis inside the jitted step (the reference drives
``torch.distributed.all_to_all_single`` from autograd, sharded_moe.py:77-90);
expert weights live as one leaf ``[n_experts, ...]`` sharded over ``'ep'``,
batched per-expert matmuls run on the MXU via a single einsum.  Parameters
whose name contains ``"expert"`` are excluded from the data-parallel bucket
plan by the trainer (the analog of ``param.expert`` flags).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ...parallel.mesh import axis_bound as _axis_bound
from .gating import top1_gating, top2_gating


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: tokens [batch, seq, d_model] -> same.

    Plugs into ``TransformerLM`` via ``mlp_factory``.  ``ep_size`` is the
    static expert-parallel degree (= mesh ``'ep'`` axis size); each shard owns
    ``n_experts // ep_size`` experts.  Outside shard_map (e.g. ``model.init``)
    the all-to-all is skipped and only the local expert slice is computed —
    parameter shapes are identical, so init-outside / apply-inside works.
    """

    n_experts: int
    d_ff: int
    ep_size: int = 1
    k: int = 2                      # top-k routing (1 or 2)
    capacity_factor: float = 1.25
    axis_name: str = "ep"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    #: capacity-free routing: no token is ever dropped.  Tokens are sorted by
    #: expert and run through the grouped-matmul Pallas kernel
    #: (:mod:`bagua_tpu.ops.gmm`) instead of the dense [T,E,C] dispatch
    #: einsum.  With ``ep_size > 1`` the inter-shard exchange is a ragged
    #: all-to-all with exact per-destination counts (the reference's
    #: ``alltoall_v``, communicators/mod.rs:632-676) instead of dense
    #: capacity slots.
    #:
    #: Regime selection, MEASURED on v5e (E=8, k=2, d_model 512 — full
    #: table in bench.py:bench_moe_dropless): capacity wins below ~12K
    #: tokens per shard per layer (1.16x at 4K), dropless wins above
    #: (1.49x at 32K, where capacity's O(T^2/E) dispatch tensor collapses
    #: it).  The default stays False because the two paths have different
    #: TRAINING semantics (capacity drops overflow tokens; dropless never
    #: drops) — switching must be the user's modelling decision, made with
    #: the perf table in hand.
    dropless: bool = False
    #: dropless EP transfer via ``lax.ragged_all_to_all`` (exact counts on
    #: the wire).  Off by default: XLA:CPU cannot execute the ragged HLO, so
    #: the virtual-mesh test/dryrun environments use the dense-slot
    #: ``all_to_all`` path; enable on real multi-chip TPU meshes.
    use_ragged: bool = False

    @nn.compact
    def __call__(self, x):
        assert self.n_experts % self.ep_size == 0
        n_local = self.n_experts // self.ep_size
        b, s, d = x.shape
        tokens = b * s
        xt = x.reshape(tokens, d)

        # router in f32 (small, precision-sensitive; reference TopKGate
        # casts to fp32 too, sharded_moe.py:241-303)
        logits = nn.Dense(
            self.n_experts, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32, name="router",
        )(xt.astype(jnp.float32))

        # one definition of the expert weights for both routing paths —
        # always the LOCAL table [n_experts // ep_size, ...]
        wi = self.param(
            "expert_wi", nn.initializers.lecun_normal(batch_axis=(0,)),
            (n_local, d, self.d_ff), self.param_dtype,
        )
        wo = self.param(
            "expert_wo", nn.initializers.lecun_normal(batch_axis=(0,)),
            (n_local, self.d_ff, d), self.param_dtype,
        )

        if self.dropless:
            return self._dropless(xt, logits, wi, wo).reshape(b, s, d)

        capacity = max(1, math.ceil(self.k * tokens * self.capacity_factor
                                    / self.n_experts))
        gate = top1_gating if self.k == 1 else top2_gating
        dispatch, combine, l_aux = gate(logits, capacity)
        self.sow("intermediates", "l_aux", l_aux)

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )

        inside_mesh = self.ep_size > 1 and _axis_bound(self.axis_name)
        if inside_mesh:
            # [E, C, d] -> [E/ep, ep*C, d]: expert shards receive their
            # tokens from every ep peer
            expert_in = lax.all_to_all(
                expert_in, self.axis_name, split_axis=0, concat_axis=1,
                tiled=True,
            )
        elif self.ep_size > 1:
            # init path (outside shard_map): only shapes matter
            expert_in = expert_in[:n_local]

        h = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(self.dtype)))
        out = jnp.einsum("ecf,efd->ecd", h, wo.astype(self.dtype))

        if inside_mesh:
            out = lax.all_to_all(
                out, self.axis_name, split_axis=1, concat_axis=0, tiled=True
            )
        elif self.ep_size > 1:
            out = jnp.concatenate(
                [out] + [jnp.zeros_like(out)] * (self.ep_size - 1), axis=0
            )

        y = jnp.einsum("tec,ecd->td", combine.astype(self.dtype), out)
        return y.reshape(b, s, d)

    def _dropless(self, xt, logits, wi, wo):
        """Sort-by-expert + grouped matmul: every routed (token, expert)
        pair is computed — the capacity-overflow drops of the GShard path
        (sharded_moe.py:93-238) cannot happen.

        With expert parallelism the exchange is a ragged all-to-all with
        exact counts: rows sorted by global expert are already grouped by
        owning shard, so shard p receives only the rows routed to its
        experts (worst-case receive buffer: every peer routes all its rows
        here).  Expert outputs ride the symmetric reverse transfer back to
        their source rows, and gates are applied at the source.
        """
        from ...ops.gmm import gmm
        from .gating import topk_routing

        n_local = self.n_experts // self.ep_size
        eidx, gates, l_aux = topk_routing(logits, self.k)
        self.sow("intermediates", "l_aux", l_aux)

        flat_e = eidx.reshape(-1)                       # [T*k]
        order = jnp.argsort(flat_e)                     # stable: ties by token
        token_of_row = order // self.k
        x_rows = xt[token_of_row].astype(self.dtype)    # [T*k, d] grouped
        e_rows = flat_e[order]

        inside_mesh = self.ep_size > 1 and _axis_bound(self.axis_name)
        if inside_mesh:
            y_rows = self._dropless_exchange(x_rows, e_rows, wi, wo, n_local)
        else:
            # single shard — or the init trace outside shard_map, where only
            # shapes matter: fold global expert ids onto the local table
            eid = e_rows if self.ep_size == 1 else e_rows % n_local
            sizes = jnp.bincount(eid, length=n_local)
            local_order = jnp.argsort(eid) if self.ep_size > 1 else None
            rows = x_rows if local_order is None else x_rows[local_order]
            h = nn.silu(gmm(rows, wi.astype(self.dtype), sizes))
            y = gmm(h, wo.astype(self.dtype), sizes)
            if local_order is None:
                y_rows = y
            else:
                y_rows = jnp.zeros_like(y).at[local_order].set(y)

        w = gates.reshape(-1)[order].astype(self.dtype)
        out = jnp.zeros((xt.shape[0], xt.shape[1]), self.dtype)
        return out.at[token_of_row].add(y_rows * w[:, None])

    def _dropless_exchange(self, x_rows, e_rows, wi, wo, n_local):
        """EP dispatch for dropless routing: [T*k, d] rows grouped by global
        expert → owning shards → local grouped matmul → reverse transfer.

        The analog of the reference's ``alltoall_v``-driven MoE all-to-all
        (communicators/mod.rs:632-676, sharded_moe.py:77-90).  Rows for peer
        ``p`` occupy the fixed slot range ``[p*tk, p*tk + count_p)`` of a
        worst-case send buffer, so the transfer is one dense ``all_to_all``
        (validatable on the virtual CPU mesh) and every downstream index is
        slot-deterministic.  ``use_ragged=True`` swaps in
        ``lax.ragged_all_to_all`` with exact counts over the same slot
        layout — moving only the routed bytes on ICI — but XLA:CPU has no
        ragged-all-to-all kernel, so it stays opt-in for real TPU meshes.
        """
        ep, ax = self.ep_size, self.axis_name
        tk, d = x_rows.shape
        cap = ep * tk                                   # worst-case slots

        # per-destination counts (rows sorted by global expert are already
        # grouped by owning shard); the [ep, n_local] counts exchange lets
        # the receiver reconstruct every row's local expert id from the
        # deterministic slot layout — no per-row metadata on the wire
        sizes_global = jnp.bincount(e_rows, length=self.n_experts)
        counts = sizes_global.reshape(ep, n_local).astype(jnp.int32)
        send_sizes = counts.sum(-1)
        input_offsets = (jnp.cumsum(send_sizes) - send_sizes).astype(jnp.int32)
        r = jnp.arange(tk, dtype=jnp.int32)
        peer_of_row = jnp.searchsorted(
            jnp.cumsum(send_sizes), r, side="right"
        ).astype(jnp.int32)
        slot = peer_of_row * tk + (r - input_offsets[peer_of_row])

        # counts_recv[p, e] = rows peer p routed to my local expert e
        counts_recv = lax.all_to_all(counts, ax, 0, 0, tiled=False).reshape(
            ep, n_local
        )
        # rows from peer p occupy slots [p*tk, p*tk + Σe counts_recv[p])
        # ordered by local expert; beyond that the slot is empty (sentinel
        # id n_local, zero payload)
        cums = jnp.cumsum(counts_recv, axis=1)          # [ep, n_local]
        within = jnp.arange(tk, dtype=jnp.int32)
        lid_recv = (
            (within[None, :, None] >= cums[:, None, :]).sum(-1)
            .astype(jnp.int32).reshape(cap)
        )
        sizes = counts_recv.sum(0)                      # rows per local expert

        if self.use_ragged:
            my = lax.axis_index(ax)
            recv_sizes = counts_recv.sum(-1)
            out_offs = jnp.full((ep,), my * tk, jnp.int32)
            x_recv = lax.ragged_all_to_all(
                x_rows, jnp.zeros((cap, d), x_rows.dtype),
                input_offsets, send_sizes, out_offs, recv_sizes,
                axis_name=ax,
            )
        else:
            x_send = jnp.zeros((cap, d), x_rows.dtype).at[slot].set(x_rows)
            x_recv = lax.all_to_all(
                x_send.reshape(ep, tk, d), ax, 0, 0, tiled=False
            ).reshape(cap, d)

        # group received rows by local expert; sentinel (empty-slot) rows
        # sort last, fall outside the grouped range, and are zero
        local_order = jnp.argsort(lid_recv)
        rows = x_recv[local_order]
        from ...ops.gmm import gmm

        h = nn.silu(gmm(rows, wi.astype(self.dtype), sizes))
        y_sorted = gmm(h, wo.astype(self.dtype), sizes)
        y_local = jnp.zeros_like(y_sorted).at[local_order].set(y_sorted)

        # reverse transfer over the same slots, then gather my rows back
        if self.use_ragged:
            peer_in_offsets = lax.all_to_all(
                input_offsets, ax, 0, 0, tiled=False
            ).reshape(ep)
            rev_in_offsets = jnp.arange(ep, dtype=jnp.int32) * tk
            return lax.ragged_all_to_all(
                y_local, jnp.zeros((tk, d), y_local.dtype),
                rev_in_offsets, recv_sizes, peer_in_offsets, send_sizes,
                axis_name=ax,
            )
        y_back = lax.all_to_all(
            y_local.reshape(ep, tk, d), ax, 0, 0, tiled=False
        ).reshape(cap, d)
        return y_back[slot]


# The exact parameter names MoEMLP creates.  Marking is by path *segment*
# equality against this set — the explicit analog of the reference's
# ``param.expert = True`` flags (experts.py:26-29) — never by substring, so a
# user param that merely contains "expert" in its name can't be silently
# pulled out of the data-parallel plan.
EXPERT_PARAM_NAMES = frozenset({"expert_wi", "expert_wo"})


def is_expert_param(name: str) -> bool:
    """True for params created by :class:`MoEMLP` (exact segment match).

    Accepts any common path spelling: dotted (``a.b.expert_wi``), slashed,
    or raw ``jax.tree_util.keystr`` output (``['a']['expert_wi']``).
    """
    import re

    return not EXPERT_PARAM_NAMES.isdisjoint(re.split(r"[\[\]'\"./]+", name))


def globalize_expert_params(params, rng, ep_size: int, is_expert=None):
    """Re-draw expert leaves at global shape for the expert-parallel trainer.

    ``model.init`` outside the mesh yields expert leaves of LOCAL shape
    ``[n_experts/ep_size, ...]`` (identical on every rank — a bad symmetric
    init).  This expands each such leaf to ``[n_experts, ...]`` with an
    independent per-expert draw; ``BaguaTrainer(expert_axis=...)`` then shards
    the leading dim over ``'ep'``.  The returned tree is only valid inside the
    trainer (direct ``model.apply`` would see a shape mismatch).
    """
    if is_expert is None:
        is_expert = is_expert_param
    init = nn.initializers.lecun_normal(batch_axis=(0,))
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        if is_expert(name) and ep_size > 1:
            rng, sub = jax.random.split(rng)
            shape = (leaf.shape[0] * ep_size,) + leaf.shape[1:]
            out.append(init(sub, shape, leaf.dtype))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def moe_lm_loss_fn(model, aux_loss_weight: float = 0.01):
    """Next-token loss + load-balancing aux loss collected from every MoE
    layer (the reference accumulates ``l_aux`` per gate, sharded_moe.py:354)."""
    import optax

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        logits, mutated = model.apply(
            {"params": params}, tokens[:, :-1], mutable=["intermediates"]
        )
        nll = optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens[:, 1:]
        ).mean()
        aux = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(mutated.get("intermediates", {})):
            aux = aux + jnp.sum(leaf)
        return nll + aux_loss_weight * aux

    return loss_fn
