"""Multi-node launcher: run the per-node launcher on every host over ssh.

Counterpart of /root/reference/bagua/script/baguarun.py:36+ (pssh to all
hosts, each running ``bagua.distributed.launch`` with its node_rank).  Uses
plain ``ssh`` subprocesses instead of parallel-ssh (no extra dependency;
TPU pods are also commonly driven by ``gcloud compute tpus tpu-vm ssh
--worker=all``, which ``--ssh_cmd`` supports as a drop-in).

Example::

    bagua-tpu-baguarun --host_list 10.0.0.1,10.0.0.2 --nproc_per_node 1 \
        --master_port 29400 train.py --lr 1e-3

Each host gets ``python -m bagua_tpu.distributed.run --nnodes N
--node_rank i --master_addr <host0> ...``; any host failing kills the rest
(the gang semantics of the per-node launcher, lifted to node level).
"""

from __future__ import annotations

import argparse
import logging
import shlex
import signal
import subprocess
import sys
import time
from typing import List

logger = logging.getLogger("bagua_tpu.baguarun")


def parse_args(argv=None):
    p = argparse.ArgumentParser("bagua-tpu-baguarun")
    p.add_argument("--host_list", type=str, required=True,
                   help="comma-separated hosts; first is the coordinator")
    p.add_argument("--ssh_port", type=int, default=22)
    p.add_argument("--ssh_cmd", type=str, default="ssh -p {port} {host}",
                   help="ssh command template ({port}, {host} substituted); "
                        "override for gcloud / test shims")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master_port", type=int, default=29400)
    p.add_argument("--bagua_service_port", type=int, default=29500)
    p.add_argument("--autotune_level", type=int, default=0)
    p.add_argument("--python", type=str, default="python")
    p.add_argument("--cwd", type=str, default=None,
                   help="remote working directory (default: current)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def node_command(args, node_rank: int, master_addr: str) -> str:
    nnodes = len(args.host_list.split(","))
    parts = [
        args.python, "-m", "bagua_tpu.distributed.run",
        "--nnodes", str(nnodes),
        "--node_rank", str(node_rank),
        "--nproc_per_node", str(args.nproc_per_node),
        "--master_addr", master_addr,
        "--master_port", str(args.master_port),
        "--bagua_service_port", str(args.bagua_service_port),
        "--autotune_level", str(args.autotune_level),
        args.training_script, *args.training_script_args,
    ]
    cmd = " ".join(shlex.quote(x) for x in parts)
    if args.cwd:
        cmd = f"cd {shlex.quote(args.cwd)} && {cmd}"
    return cmd


def launch(args) -> int:
    hosts = [h.strip() for h in args.host_list.split(",") if h.strip()]
    if not hosts:
        raise SystemExit("empty --host_list")
    master = hosts[0]
    procs: List[subprocess.Popen] = []
    for rank, host in enumerate(hosts):
        ssh = shlex.split(
            args.ssh_cmd.format(port=args.ssh_port, host=host)
        )
        remote_cmd = node_command(args, rank, master)
        logger.info("launching node %d on %s: %s", rank, host, remote_cmd)
        procs.append(subprocess.Popen(ssh + [remote_cmd]))

    rc = 0
    try:
        while procs:
            for p in list(procs):
                code = p.poll()
                if code is None:
                    continue
                procs.remove(p)
                if code != 0 and rc == 0:
                    rc = code
                    logger.error("a node failed (exit %d); killing the rest",
                                 code)
                    for q in procs:
                        q.send_signal(signal.SIGTERM)
            if procs:
                time.sleep(0.5)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        rc = 130
    for p in procs:
        p.wait()
    return rc


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    return launch(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
