"""Cluster-level launch scripts (reference bagua/script/)."""
