"""Checkpoint / resume.

The reference has no framework-level checkpointing — its elastic example
hand-rolls ``torch.save`` of ``{epoch, model_state_dict, optimizer_state_dict}``
on rank 0 and reloads on (re)start
(/root/reference/examples/elastic_training/main.py:238-259), relying on
``_bagua_broadcast_parameters`` to re-sync.  On TPU the state is a sharded
pytree, so this is a real subsystem here: orbax-backed, optionally async
(saves overlap training), with retention pruning — the piece SURVEY.md §5.4
calls out as required for the elastic workload.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import weakref
from typing import Any, List, Optional, Tuple

import jax

logger = logging.getLogger(__name__)

# live managers, so emergency paths (watchdog exit) can flush queued async
# saves instead of losing them to os._exit skipping atexit handlers
_LIVE_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()


# ---- storage quarantine (docs/autopilot.md) -------------------------------
#
# Repeated integrity failures / fallback restores on one checkpoint
# directory mean the STORAGE under it is rotting — continuing to save there
# burns wall clock writing checkpoints that will not verify at the next
# restore.  The autopilot's ckpt_integrity rule (or an operator, via
# BAGUA_CKPT_QUARANTINED_PATHS) quarantines the path: every live
# BaguaCheckpointManager on it redirects subsequent SAVES to a
# `<dir>.redirect` sibling, while RESTORES keep walking both directories
# (newest-first across the union — reads of already-verified old steps are
# exactly what quarantine must not break).

_QUARANTINE_LOCK = threading.Lock()
_QUARANTINED: set = set()
_QUARANTINE_SEEDED = False


def _normalize_storage_path(path: str) -> str:
    p = str(path)
    if "://" in p:  # gs:// etc. — keep verbatim minus trailing slashes
        return p.rstrip("/")
    return os.path.abspath(p).rstrip("/")


def _seed_quarantine_from_env() -> None:
    """One-time seed from ``BAGUA_CKPT_QUARANTINED_PATHS`` — the channel
    the elastic launcher uses to carry the autopilot's quarantine verdicts
    into respawned workers at the restart boundary."""
    global _QUARANTINE_SEEDED
    if _QUARANTINE_SEEDED:
        return
    _QUARANTINE_SEEDED = True
    from . import env as _env

    for p in _env.get_ckpt_quarantined_paths():
        _QUARANTINED.add(_normalize_storage_path(p))


def quarantine_storage_path(path: str) -> bool:
    """Quarantine a checkpoint directory (idempotent; returns True when
    newly quarantined).  Live managers on the path redirect their next
    save; future managers resolve the redirect at construction."""
    with _QUARANTINE_LOCK:
        _seed_quarantine_from_env()
        p = _normalize_storage_path(path)
        if p in _QUARANTINED:
            return False
        _QUARANTINED.add(p)
    logger.warning(
        "checkpoint storage QUARANTINED: %s — saves redirect to %s",
        p, redirect_directory(p),
    )
    return True


def is_quarantined(path: str) -> bool:
    with _QUARANTINE_LOCK:
        _seed_quarantine_from_env()
        return _normalize_storage_path(path) in _QUARANTINED


def quarantined_paths() -> List[str]:
    with _QUARANTINE_LOCK:
        _seed_quarantine_from_env()
        return sorted(_QUARANTINED)


def clear_quarantine() -> None:
    """Forget every quarantine (test isolation)."""
    global _QUARANTINE_SEEDED
    with _QUARANTINE_LOCK:
        _QUARANTINED.clear()
        _QUARANTINE_SEEDED = True


def redirect_directory(path: str) -> str:
    """Where saves for a quarantined ``path`` land."""
    return _normalize_storage_path(path) + ".redirect"


def active_directory(path: str) -> str:
    """Resolve a requested checkpoint directory through the quarantine
    registry (chasing redirect-of-redirect up to a small bound — a
    redirect that rots too gets quarantined like any other path)."""
    p = _normalize_storage_path(path)
    for _ in range(4):
        if not is_quarantined(p):
            return p
        p = redirect_directory(p)
    return p


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint failed content verification at restore (recorded sha256
    digest vs the restored leaves) — torn write, bit rot, or an injected
    ``ckpt.write`` fault.  :meth:`BaguaCheckpointManager.restore` treats it
    (like an unreadable checkpoint) as a fallback trigger when no explicit
    step was requested."""


def compute_state_digest(state: Any) -> Optional[dict]:
    """Content checksum of a state pytree: sha256 over every leaf's path,
    shape, dtype, and raw bytes, in tree-flatten order.  Sharding- and
    layout-agnostic w.r.t. the MESH (global logical values are hashed), so
    an elastic restore at a different topology verifies against the digest
    recorded at save time.  Returns None when the state cannot be fetched
    whole (multi-process non-addressable arrays) — verification is then
    skipped with a log line rather than hashing a partial view."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in flat:
        h.update(jax.tree_util.keystr(path).encode())
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            if getattr(leaf, "is_fully_addressable", True) is False:
                logger.info(
                    "checkpoint integrity: %s is not fully addressable on "
                    "this process; digest skipped", jax.tree_util.keystr(path),
                )
                return None
            arr = np.asarray(leaf)
            h.update(str(arr.shape).encode())
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
        else:
            h.update(repr(leaf).encode())
    return {"algo": "sha256", "digest": h.hexdigest(), "leaves": len(flat)}


def flush_all_checkpoints(timeout_s: float = 10.0) -> None:
    """Best-effort flush of every live manager's queued async saves, bounded
    by ``timeout_s`` — called by the watchdog before it terminates a wedged
    process, where an unbounded ``wait_until_finished`` could itself hang."""
    managers = list(_LIVE_MANAGERS)
    if not managers:
        return

    def flush():
        for m in managers:
            try:
                m.wait()
            except Exception as e:  # pragma: no cover - backend-dependent
                logger.warning("checkpoint flush failed: %s", e)

    t = threading.Thread(target=flush, name="bagua-ckpt-flush", daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        logger.error(
            "checkpoint flush did not finish within %.0f s — queued async "
            "saves may be lost", timeout_s,
        )


class BaguaCheckpointManager:
    """Save/restore ``TrainState`` (or any pytree) with retention + async.

    Thin policy layer over ``orbax.checkpoint.CheckpointManager``; all ranks
    must call :meth:`save`/:meth:`restore` collectively (orbax coordinates
    the multi-host barrier itself).
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
        integrity: bool = True,
    ):
        """``integrity=True`` (default) records a content checksum
        (:func:`compute_state_digest`) in every save's layout sidecar and
        verifies it on restore — a corrupted/torn checkpoint then degrades
        to the previous verified step (loud warning) instead of restoring
        garbage.  Costs one host readback of the state per save; set False
        to opt out (e.g. states too large to fetch per save)."""
        import orbax.checkpoint as ocp

        self._ocp = ocp
        #: the directory the CALLER asked for — quarantine verdicts name
        #: this path; ``self.directory`` is the ACTIVE (possibly
        #: redirected) one
        self.requested_directory = str(directory)
        self.directory = active_directory(self.requested_directory)
        if self.directory != _normalize_storage_path(
                self.requested_directory):
            logger.warning(
                "checkpoint directory %s is quarantined; using %s",
                self.requested_directory, self.directory,
            )
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory,
                                          options=self._options)
        #: read-only managers over earlier directories in the quarantine
        #: redirect chain (oldest first) — a mid-life redirect appends the
        #: displaced manager, and a manager CONSTRUCTED on an already-
        #: quarantined path wires the whole chain here, so restores always
        #: keep walking the verified pre-quarantine history
        self._fallbacks: List[Tuple[Any, str]] = []
        chain = _normalize_storage_path(self.requested_directory)
        while chain != self.directory:
            self._fallbacks.append(
                (ocp.CheckpointManager(chain, options=self._options), chain)
            )
            chain = redirect_directory(chain)
        self._async_save = bool(async_save)
        self._integrity = bool(integrity)
        # fleet view: the storage path this rank saves to rides the obs
        # summary, so the autopilot can name WHICH path to quarantine
        try:
            from .obs.export import note_ckpt_directory

            note_ckpt_directory(self.directory)
        except Exception:  # noqa: BLE001 - obs is never load-bearing here
            pass
        # layout sidecars whose orbax save is not yet known-durable:
        # written only once the async save finishes (wait()/close()/next
        # save), so a crash mid-save can't leave a sidecar pointing at a
        # checkpoint that never became readable (ADVICE.md)
        self._pending_layouts: dict = {}
        # steps whose durable files the chaos ``ckpt.write`` hook has not
        # yet had a chance to corrupt (same durability gating as sidecars)
        self._uncorrupted_steps: list = []
        _LIVE_MANAGERS.add(self)

    def save(self, step: int, state: Any, metadata: Optional[dict] = None) -> bool:
        """Queue a save (async by default); returns False when skipped by the
        save-interval policy.

        ``metadata``: an optional JSON-serializable layout descriptor stored
        alongside the state (use ``trainer.checkpoint_layout_metadata()``) and
        validated on :meth:`restore` via ``expect_metadata=``.  Required in
        practice for the flat-resident ZeRO layout, whose on-disk shapes are
        bucket-plan- and world-size-dependent.

        The descriptor is a SIDECAR file (``<dir>/<step>.layout.json``), not
        an orbax item: orbax locks a manager to one item structure on first
        use, so a composite item would make mixing metadata and plain saves
        (or resuming an old checkpoint, then saving) an opaque error.  The
        state's on-disk format is identical with and without metadata.

        Async saves defer the sidecar write until the orbax save is
        DURABLE: orbax finalizes the previous async save before starting a
        new one, so the pending sidecar flushes at the next :meth:`save`,
        or in :meth:`wait`/:meth:`close` — never ahead of its checkpoint."""
        from .obs.spans import trace_span

        self._ensure_active_manager()
        with trace_span("ckpt/save", step=int(step),
                        async_save=self._async_save):
            saved = self._mgr.save(
                int(step), args=self._ocp.args.StandardSave(state)
            )
        if saved:
            # orbax finalizes the PREVIOUS async save inside a proceeding
            # _mgr.save() (its internal wait_until_finished runs after the
            # should_save early-return), so only a save that actually
            # proceeded proves the stashed sidecars point at durable
            # checkpoints — flushing on a skipped save would reopen the
            # crash window this deferral exists to close
            self._flush_pending_layouts()
            self._run_chaos_corruption()
        if saved:
            # integrity chain: the content digest rides the layout sidecar
            # (computed here, while the state is still live — donation in
            # the next train step may invalidate these buffers)
            meta = dict(metadata) if metadata is not None else {}
            if self._integrity and "integrity" not in meta:
                digest = compute_state_digest(state)
                if digest is not None:
                    meta["integrity"] = digest
            if meta:
                if self._async_save:
                    # stashed on EVERY process (written by process 0 only):
                    # a restore of a not-yet-flushed step must see the same
                    # metadata on all processes, or a layout mismatch would
                    # raise on process 0 alone and strand the others in the
                    # collective orbax restore
                    self._pending_layouts[int(step)] = meta
                else:
                    self._write_layout(int(step), meta)
            self._uncorrupted_steps.append(int(step))
            if not self._async_save:
                self._run_chaos_corruption()
        return saved

    def _ensure_active_manager(self) -> None:
        """Re-resolve the quarantine registry: when the active directory
        was quarantined since the last call (the autopilot's
        ``quarantine_storage`` action, in-process), flush what the old
        manager has queued, keep it around READ-ONLY (its verified history
        must stay restorable), and point saves at the redirect."""
        active = active_directory(self.requested_directory)
        if active == self.directory:
            return
        logger.warning(
            "checkpoint storage quarantine: redirecting saves %s -> %s "
            "(restores keep walking both)", self.directory, active,
        )
        try:
            self.wait()  # flush queued async saves + sidecars on old storage
        except Exception as e:  # noqa: BLE001 - rotting storage may throw
            logger.warning("flush of quarantined checkpoint dir failed: %s",
                           e)
        # APPEND, never overwrite: a redirect-of-redirect must keep the
        # original directory's verified history in the restore walk too
        self._fallbacks.append((self._mgr, self.directory))
        self.directory = active
        self._mgr = self._ocp.CheckpointManager(active,
                                                options=self._options)
        try:
            from .obs.export import note_ckpt_directory

            note_ckpt_directory(self.directory)
        except Exception:  # noqa: BLE001
            pass

    @contextlib.contextmanager
    def _using(self, mgr, directory: str):
        """Temporarily point this manager's restore path at another
        (manager, directory) pair — how the newest-first integrity walk
        reaches the pre-quarantine history without changing the
        ``restore_one(step)`` contract ``BaguaTrainer.restore_checkpoint``
        also relies on."""
        if mgr is self._mgr:
            yield
            return
        prev = (self._mgr, self.directory)
        self._mgr, self.directory = mgr, directory
        try:
            yield
        finally:
            self._mgr, self.directory = prev

    def _candidate_steps(self) -> List[Tuple[int, Any, str]]:
        """(step, manager, directory) restore candidates, newest-first;
        at equal steps the active directory shadows every fallback, and a
        newer link of the redirect chain shadows an older one."""
        out = {int(s): (self._mgr, self.directory)
               for s in self._mgr.all_steps()}
        for mgr, d in reversed(self._fallbacks):
            for s in mgr.all_steps():
                out.setdefault(int(s), (mgr, d))
        return [(s,) + out[s] for s in sorted(out, reverse=True)]

    def _run_chaos_corruption(self) -> None:
        """Apply any armed ``ckpt.write`` fault to steps whose orbax files
        are now durable (cheap no-op while nothing is armed).  Gated like
        the sidecar flush: corrupting a still-in-flight async save would
        race the writer instead of modeling post-publish rot."""
        from .faults import inject as _inject

        pending, self._uncorrupted_steps = self._uncorrupted_steps, []
        for step in pending:
            _inject.maybe_corrupt_checkpoint(self.directory, step)

    def _write_layout(self, step: int, metadata: dict) -> None:
        import json

        from .faults import inject as _inject

        if jax.process_index() != 0:
            return
        path = self._layout_path(step)
        # atomic publish (tmp + replace, the native_build.py:71 pattern): a
        # crash mid-write must leave either no sidecar or a complete one —
        # a torn sidecar would fail JSON parsing and discard the layout AND
        # integrity record of a perfectly good checkpoint
        tmp = path.parent / f".{path.name}.tmp"
        tmp.write_text(json.dumps(metadata))
        tmp.replace(path)
        _inject.maybe_corrupt_sidecar(path, step)  # chaos: ckpt.sidecar
        self._prune_layout_sidecars()

    def _flush_pending_layouts(self) -> None:
        """Write sidecars whose orbax save has since become durable.  Call
        only at points where queued async saves are known finished (after
        ``wait_until_finished``, or after the next proceeding ``save``).
        Entries are dropped only on a successful write — a transient
        shared-fs error keeps the stash so wait()/close()/the next save
        retry it."""
        for step in list(self._pending_layouts):
            try:
                self._write_layout(step, self._pending_layouts[step])
                del self._pending_layouts[step]
            except Exception as e:  # pragma: no cover - fs-backend dependent
                logger.warning("layout sidecar write failed for step %s "
                               "(kept for retry): %s", step, e)

    def _prune_layout_sidecars(self) -> None:
        """Best-effort: drop sidecars for steps orbax retention has pruned."""
        try:
            live = {int(s) for s in self._mgr.all_steps()}
            for p in self._layout_path(0).parent.glob("*.layout.json"):
                if int(p.name.split(".")[0]) not in live:
                    p.unlink()
        except Exception as e:  # pragma: no cover - fs-backend dependent
            logger.debug("layout sidecar pruning skipped: %s", e)

    def latest_step(self) -> Optional[int]:
        latest = self._mgr.latest_step()
        for mgr, _ in self._fallbacks:
            old = mgr.latest_step()
            if old is not None and (latest is None or int(old) > int(latest)):
                latest = old
        return latest

    def _layout_path(self, step: int):
        # epath (an orbax dependency) resolves gs://, s3:// etc. — a raw
        # os.path probe would silently skip layout validation on the remote
        # checkpoint directories orbax itself supports
        from etils import epath

        return epath.Path(self.directory) / f"{int(step)}.layout.json"

    def _read_layout(self, step: int) -> Optional[dict]:
        import json

        if int(step) in self._pending_layouts:
            # restoring a step whose async save hasn't been waited on yet:
            # the stashed metadata is authoritative
            return self._pending_layouts[int(step)]
        path = self._layout_path(step)
        if not path.exists():
            return None
        try:
            return json.loads(path.read_text())
        except (ValueError, UnicodeDecodeError) as e:
            # a torn/garbage sidecar makes the step unverifiable — surface
            # it as an integrity failure so a latest-step restore degrades
            # to the previous verified checkpoint instead of crashing here
            raise CheckpointIntegrityError(
                f"layout sidecar for step {step} is unreadable ({e}) — "
                "torn write or corruption"
            ) from e

    def read_layout(self, step: int) -> Optional[dict]:
        """The layout sidecar saved with ``step`` (None when the step was
        saved without ``metadata=``).  ``BaguaTrainer.restore_checkpoint``
        reads this to decide whether a flat-resident checkpoint needs a
        relayout or leaf conversion before it can feed the live trainer."""
        return self._read_layout(int(step))

    #: metadata keys that carry layout PAYLOAD (the full bucket layout
    #: descriptor) or side-channel records (the integrity digest), not
    #: compatibility constraints — never compared.  "ef" (the
    #: error-feedback residual's plan/world descriptor) is payload too:
    #: BaguaTrainer.restore_checkpoint adapts on it explicitly (relayout /
    #: zero-reset), so a residual difference must not fail the strict
    #: comparison that guards the rest of the state
    _LAYOUT_PAYLOAD_KEYS = ("flat_layout", "stacked", "integrity", "ef")

    @classmethod
    def _normalize_layout(cls, meta: Optional[dict]) -> Optional[dict]:
        if meta is None:
            return None
        m = {k: v for k, v in meta.items()
             if k not in cls._LAYOUT_PAYLOAD_KEYS}
        if m.get("layout") == "zero_flat":
            # pre-r6 sidecars named the (then ZeRO-only) flat-resident
            # layout "zero_flat"; it is the same on-disk layout
            m["layout"] = "flat"
        return m

    @classmethod
    def _check_layout(cls, saved: Optional[dict],
                      expected: Optional[dict]) -> None:
        # gossip state carries a leading rank axis, so ITS shapes depend on
        # the world size even under an identical plan — read before
        # normalization strips the payload keys
        stacked = bool((saved or {}).get("stacked")) or bool(
            (expected or {}).get("stacked")
        )
        saved = cls._normalize_layout(saved)
        expected = cls._normalize_layout(expected)
        if (
            saved is not None
            and expected is not None
            and saved.get("plan_signature")
            and saved.get("plan_signature") == expected.get("plan_signature")
        ):
            # the signature pins the CONCRETE layout (tensor order, dtypes,
            # alignment padding): the bucket_bytes KNOB may differ while
            # splitting identically (small models land in the same buckets
            # under many sizes), and — for UNSTACKED state — a world-size
            # change leaves alignment-1 flat buffers byte-identical (an
            # elastic resume of the default allreduce layout).
            # ``opt_shards`` — the key that pins sharded (ZeRO) chunk-state
            # stacking — is still compared, so topology changes that DO
            # reshape state keep raising.
            keys = ("bucket_bytes",) if stacked else ("bucket_bytes",
                                                      "world_size")
            for k in keys:
                saved.pop(k, None)
                expected.pop(k, None)
        if expected is None:
            if saved is not None and saved.get("plan_dependent"):
                logger.warning(
                    "checkpoint was saved in a plan-dependent layout (%s) but "
                    "no expect_metadata was passed — restore cannot verify the "
                    "bucket plan/world size still match", saved.get("layout"),
                )
            return
        if saved is None:
            logger.warning(
                "expect_metadata given but the checkpoint carries no layout "
                "metadata (saved before metadata support, or without "
                "metadata=) — cannot verify layout compatibility"
            )
            return
        missing = [k for k in expected if k not in saved]
        if missing:
            # keys added after the checkpoint was written (e.g. opt_shards,
            # r5): legacy sidecars must stay restorable at the same topology
            logger.warning(
                "checkpoint layout metadata predates field(s) %s — cannot "
                "verify those; restoring", ", ".join(sorted(missing)),
            )
        mismatched = {
            k: (saved[k], expected[k])
            for k in expected
            if k in saved and saved[k] != expected[k]
        }
        if not mismatched:
            return
        detail = ", ".join(
            f"{k}: checkpoint={a!r} vs current={b!r}"
            for k, (a, b) in sorted(mismatched.items())
        )
        plan_dependent = (
            saved.get("plan_dependent")
            or expected.get("plan_dependent")
            or "layout" in mismatched
        )
        if not plan_dependent:
            # leaf-layout state is genuinely plan/world-size independent:
            # an elastic restart at a different topology restores fine —
            # surface the difference, don't block it
            logger.info(
                "checkpoint layout metadata differs (%s) but both layouts "
                "are plan-independent; restoring", detail,
            )
            return
        raise ValueError(
            "checkpoint layout mismatch — this checkpoint cannot restore "
            f"directly into the current trainer ({detail}).  Flat-resident "
            "layouts are bucket-plan- and world-size-dependent: an "
            "elastic restart at a different process count or a "
            "bucket_bytes change produces different flat-buffer shapes.  "
            "Use trainer.restore_checkpoint(manager, state_like) — it "
            "re-lays-out or leaf-converts flat checkpoints across plans "
            "(sharded-opt-state ZeRO excepted) — or restart with the "
            "original world size/bucket_bytes, or re-save in the "
            "plan-independent leaf layout "
            "(trainer.unstack_params(state)) before changing the topology."
        )

    def restore(
        self,
        state_like: Any,
        step: Optional[int] = None,
        expect_metadata: Optional[dict] = None,
        mesh: Optional[Any] = None,
    ) -> Tuple[int, Any]:
        """Restore the given (or latest) step.  ``state_like`` provides the
        target pytree structure/shapes/shardings — pass a freshly-initialized
        ``TrainState``; its buffers are replaced by the checkpoint values.

        Shardings are rebuilt for the live mesh, not taken verbatim from
        ``state_like``: leaves produced by the jitted step carry a
        ``NamedSharding`` and keep it, but host-created leaves (the step
        counter, replicated params fed straight into ``trainer.init``) only
        carry a ``SingleDeviceSharding`` — restoring those as-is would commit
        them to one device and the sharded train step would then reject the
        state.  Any leaf without a ``NamedSharding`` is restored replicated
        over ``mesh`` (pass the live mesh explicitly — essential on an
        ELASTIC restart, where orbax's fallback of reading shardings from
        the checkpoint file would silently resurrect the OLD topology),
        falling back to the mesh harvested from sibling leaves, then to the
        global mesh.  Elastic restores verify the integrity digest too —
        the digest hashes global logical values, so it is topology-free.

        Integrity chain: with no explicit ``step``, restore walks steps
        NEWEST-FIRST and lands on the first one that verifies — an
        unreadable checkpoint, a torn/garbage sidecar, or a content-digest
        mismatch each disqualify a step with a loud warning and fall back
        to the previous one.  An EXPLICIT ``step`` never falls back: a
        verification failure raises :class:`CheckpointIntegrityError`.
        Layout mismatches (``expect_metadata``) are configuration errors,
        not corruption — they raise immediately in both modes.
        """
        self._ensure_active_manager()
        if step is not None:
            for s, mgr, d in self._candidate_steps():
                if s == int(step):
                    with self._using(mgr, d):
                        return self._restore_step(
                            int(step), state_like, expect_metadata, mesh
                        )
            return self._restore_step(
                int(step), state_like, expect_metadata, mesh
            )
        return self._restore_newest_verified(
            lambda s: self._restore_step(s, state_like, expect_metadata,
                                         mesh)
        )

    def _restore_newest_verified(self, restore_one):
        """The ONE integrity-fallback policy: walk steps newest-first and
        return the first result ``restore_one(step)`` produces without a
        :class:`CheckpointIntegrityError` — also used by
        ``BaguaTrainer.restore_checkpoint`` so the trainer's layout-aware
        restore cannot drift from the manager's."""
        from .faults import inject as _inject

        self._ensure_active_manager()
        candidates = self._candidate_steps()
        if not candidates:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        last_err: Optional[Exception] = None
        for i, (s, mgr, d) in enumerate(candidates):
            try:
                with self._using(mgr, d):
                    result = restore_one(s)
            except CheckpointIntegrityError as e:
                from .telemetry import counters

                counters.incr("ckpt/integrity_failures")
                logger.error(
                    "checkpoint step %d FAILED verification (%s) — falling "
                    "back to the previous checkpoint", s, e,
                )
                last_err = e
                continue
            if i > 0:
                from .telemetry import counters

                counters.incr("ckpt/fallback_restores")
                logger.warning(
                    "checkpoint integrity: restored step %d after %d newer "
                    "checkpoint(s) failed verification — training resumes "
                    "from an OLDER state than the last save", s, i,
                )
                _inject.record_recovery("ckpt.write")
                _inject.record_recovery("ckpt.sidecar")
            return result
        raise CheckpointIntegrityError(
            f"no checkpoint under {self.directory} passed verification "
            f"({len(candidates)} candidate step(s) tried)"
        ) from last_err

    def _restore_step(
        self,
        step: int,
        state_like: Any,
        expect_metadata: Optional[dict],
        mesh: Optional[Any],
    ) -> Tuple[int, Any]:
        from .obs.spans import trace_span

        with trace_span("ckpt/restore", ckpt_step=int(step)):
            return self._restore_step_inner(step, state_like,
                                            expect_metadata, mesh)

    def _restore_step_inner(
        self,
        step: int,
        state_like: Any,
        expect_metadata: Optional[dict],
        mesh: Optional[Any],
    ) -> Tuple[int, Any]:
        from jax.sharding import NamedSharding, PartitionSpec

        if mesh is None:
            for leaf in jax.tree.leaves(state_like):
                s = getattr(leaf, "sharding", None)
                if isinstance(s, NamedSharding):
                    mesh = s.mesh
                    break
        if mesh is None:
            from .parallel.mesh import get_global_mesh_if_set

            mesh = get_global_mesh_if_set()
        replicated = (
            NamedSharding(mesh, PartitionSpec()) if mesh is not None else None
        )

        def abstract_leaf(x):
            if not hasattr(x, "shape"):
                return x
            s = getattr(x, "sharding", None)
            if not isinstance(s, NamedSharding):
                s = replicated
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        abstract = jax.tree.map(abstract_leaf, state_like)
        # validate the layout sidecar FIRST: the actionable mismatch error
        # must fire before orbax hits an opaque flat-shape mismatch.  A
        # corrupted sidecar raises CheckpointIntegrityError from the read
        # itself (fallback trigger); a layout MISMATCH is a configuration
        # error and propagates as ValueError (never a fallback)
        sidecar = self._read_layout(step)
        self._check_layout(sidecar, expect_metadata)
        try:
            restored = self._mgr.restore(
                int(step), args=self._ocp.args.StandardRestore(abstract)
            )
        except Exception as e:
            # orbax could not materialize the step (missing/truncated/
            # garbage files): corruption class, not configuration.
            # Deliberate tradeoff: a transient fs error or a stale
            # state_like structure is reclassified too — the walk then
            # tries older steps and the terminal error chains this one, so
            # the root cause stays visible; distinguishing "transient" from
            # "corrupt" generically across orbax/tensorstore backends is
            # not feasible here
            raise CheckpointIntegrityError(
                f"checkpoint step {step} is unreadable "
                f"({type(e).__name__}: {e})"
            ) from e
        self._verify_integrity(step, sidecar, restored)
        return int(step), restored

    def _verify_integrity(self, step: int, sidecar: Optional[dict],
                          restored: Any) -> None:
        """Compare the restored state's content digest against the one
        recorded at save time (no-op for checkpoints saved without one, or
        when the manager opted out of integrity)."""
        from .obs.spans import trace_span

        recorded = (sidecar or {}).get("integrity")
        if not self._integrity or not recorded:
            return
        with trace_span("ckpt/verify", ckpt_step=int(step)):
            actual = compute_state_digest(restored)
        if actual is None:  # multi-process partial view: cannot verify
            logger.info("checkpoint integrity: step %d not verifiable on "
                        "this process (non-addressable state)", step)
            return
        if actual["digest"] != recorded.get("digest"):
            raise CheckpointIntegrityError(
                f"checkpoint step {step} content digest mismatch "
                f"(saved {recorded.get('digest', '?')[:12]}…, restored "
                f"{actual['digest'][:12]}…) — on-disk corruption"
            )
        from .telemetry import counters

        counters.incr("ckpt/verified_restores")

    def try_restore(
        self,
        state_like: Any,
        expect_metadata: Optional[dict] = None,
        mesh: Optional[Any] = None,
    ) -> Tuple[Optional[int], Any]:
        """Restore latest if present, else return (None, state_like) —
        the launcher's resume-on-restart entry point."""
        if self.latest_step() is None:
            return None, state_like
        return self.restore(
            state_like, expect_metadata=expect_metadata, mesh=mesh
        )

    def wait(self) -> None:
        """Block until queued async saves are durable, then write their
        deferred layout sidecars."""
        self._mgr.wait_until_finished()
        self._flush_pending_layouts()
        self._run_chaos_corruption()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_pending_layouts()
        self._run_chaos_corruption()
        self._mgr.close()
        for mgr, _ in self._fallbacks:
            mgr.close()
