"""Grouped matrix multiply (``gmm``) — Pallas TPU kernel for dropless MoE.

``gmm(lhs, rhs, group_sizes)`` multiplies contiguous row groups of ``lhs``
[rows, d] by per-group matrices ``rhs`` [groups, d, f], returning [rows, f].
This is the expert-FFN primitive of dropless (capacity-free) MoE routing:
tokens sorted by expert form ragged groups, and no token is dropped no
matter how skewed the routing — the fix for GShard capacity overflow
(the reference's gate drops tokens past ``capacity``,
/root/reference/bagua/torch_api/model_parallel/moe/sharded_moe.py:93-238).

TPU-first design: ragged row groups are scattered into block-aligned slots
(each group padded up to the 128-row MXU tile), after which every row block
belongs to exactly ONE group — a scalar-prefetched per-block group id then
steers the ``rhs`` BlockSpec, so each grid step is a single dense MXU matmul
with no masking.  The dK accumulation kernel walks row blocks innermost and
revisits its (group, d, f) output block across consecutive steps, the
standard Pallas accumulation pattern.  Backward is a custom VJP: d_lhs is
the same kernel with ``rhs`` transposed; d_rhs is the grouped outer-product
kernel.  Padded rows are zero, so they contribute nothing to any reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .tiles import pick_block

_BLOCK_ROWS = 128
_BLOCK_F = 512


def gmm_reference(lhs, rhs, group_sizes):
    """Dense one-hot reference (test golden; also the CPU fallback)."""
    rows, _ = lhs.shape
    g_of_row = jnp.searchsorted(
        jnp.cumsum(group_sizes), jnp.arange(rows), side="right"
    )
    onehot = jax.nn.one_hot(g_of_row, rhs.shape[0], dtype=lhs.dtype)
    return jnp.einsum(
        "rg,rd,gdf->rf", onehot, lhs, rhs.astype(lhs.dtype)
    ).astype(lhs.dtype)


def _round_up(x, m):
    """Ceiling-round to a multiple; works on ints and traced arrays."""
    return -(-x // m) * m


def _padded_layout(group_sizes, rows: int, n_groups: int, block: int):
    """Map ragged rows to block-aligned padded slots.

    Returns (pos [rows] padded position per row, g_of_block [n_blocks],
    padded_rows static int).
    """
    padded_rows = _round_up(rows + n_groups * (block - 1), block)
    sizes = group_sizes.astype(jnp.int32)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes)])
    padded = _round_up(sizes, block)
    poffs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(padded)])
    r = jnp.arange(rows, dtype=jnp.int32)
    g_of_row = jnp.searchsorted(offs[1:], r, side="right").astype(jnp.int32)
    pos = poffs[g_of_row] + (r - offs[g_of_row])
    starts = jnp.arange(padded_rows // block, dtype=jnp.int32) * block
    g_of_block = jnp.clip(
        jnp.searchsorted(poffs, starts, side="right") - 1, 0, n_groups - 1
    ).astype(jnp.int32)
    return pos, g_of_block, padded_rows


def _fwd_kernel(gid_ref, lhs_ref, rhs_ref, out_ref):
    out_ref[:] = jnp.dot(
        lhs_ref[:], rhs_ref[0], preferred_element_type=jnp.float32
    ).astype(out_ref.dtype)


def _gmm_padded(lhs_p, rhs, g_of_block, block_rows, block_f, interpret):
    """lhs_p: [padded_rows, d] (group-blocked), rhs: [G, d, f]."""
    padded_rows, d = lhs_p.shape
    _, _, f = rhs.shape
    bf = pick_block(f, block_f)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(padded_rows // block_rows, f // bf),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i, j, gid: (i, 0)),
            pl.BlockSpec((1, d, bf), lambda i, j, gid: (gid[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((block_rows, bf), lambda i, j, gid: (i, j)),
    )
    return pl.pallas_call(
        _fwd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((padded_rows, f), lhs_p.dtype),
        interpret=interpret,
    )(g_of_block, lhs_p, rhs)


def _drhs_kernel(gid_ref, lhs_ref, g_ref, out_ref):
    k = pl.program_id(2)
    gid = gid_ref[k]
    prev_same = jnp.logical_and(k > 0, gid_ref[jnp.maximum(k - 1, 0)] == gid)

    @pl.when(jnp.logical_not(prev_same))
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jax.lax.dot_general(
        lhs_ref[:], g_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[None].astype(out_ref.dtype)


def _gmm_drhs_padded(lhs_p, gout_p, n_groups, d, f, g_of_block, block_rows,
                     block_f, interpret):
    """d_rhs[g] = lhs_g^T @ gout_g over padded row blocks: [G, d, f] f32."""
    padded_rows = lhs_p.shape[0]
    bf = pick_block(f, block_f)
    bd = pick_block(d, block_f)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // bd, f // bf, padded_rows // block_rows),
        in_specs=[
            pl.BlockSpec((block_rows, bd), lambda i, j, k, gid: (k, i)),
            pl.BlockSpec((block_rows, bf), lambda i, j, k, gid: (k, j)),
        ],
        out_specs=pl.BlockSpec((1, bd, bf), lambda i, j, k, gid: (gid[k], i, j)),
    )
    return pl.pallas_call(
        _drhs_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_groups, d, f), jnp.float32),
        interpret=interpret,
    )(g_of_block, lhs_p, gout_p)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gmm(lhs, rhs, group_sizes, block_rows, block_f, interpret):
    out, _ = _gmm_fwd_impl(lhs, rhs, group_sizes, block_rows, block_f,
                           interpret)
    return out


def _gmm_fwd_impl(lhs, rhs, group_sizes, block_rows, block_f, interpret):
    rows, d = lhs.shape
    n_groups = rhs.shape[0]
    pos, g_of_block, padded_rows = _padded_layout(
        group_sizes, rows, n_groups, block_rows
    )
    lhs_p = jnp.zeros((padded_rows, d), lhs.dtype).at[pos].set(lhs)
    out_p = _gmm_padded(lhs_p, rhs.astype(lhs.dtype), g_of_block, block_rows,
                        block_f, interpret)
    return out_p[pos], (pos, g_of_block, padded_rows)


def _gmm_vjp_fwd(lhs, rhs, group_sizes, block_rows, block_f, interpret):
    out, layout = _gmm_fwd_impl(lhs, rhs, group_sizes, block_rows, block_f,
                                interpret)
    return out, (lhs, rhs, group_sizes, layout)


def _gmm_vjp_bwd(block_rows, block_f, interpret, res, gout):
    lhs, rhs, group_sizes, (pos, g_of_block, padded_rows) = res
    rows, d = lhs.shape
    n_groups, _, f = rhs.shape
    gout_p = jnp.zeros((padded_rows, f), gout.dtype).at[pos].set(gout)
    # d_lhs = gout @ rhs^T (same grouped structure)
    dlhs_p = _gmm_padded(
        gout_p, jnp.swapaxes(rhs, 1, 2).astype(gout.dtype), g_of_block,
        block_rows, block_f, interpret,
    )
    lhs_p = jnp.zeros((padded_rows, d), lhs.dtype).at[pos].set(lhs)
    drhs = _gmm_drhs_padded(lhs_p, gout_p, n_groups, d, f, g_of_block,
                            block_rows, block_f, interpret)
    # an empty group owns no row blocks, so its output block is never
    # written — select zero rather than uninitialized memory
    mask = (group_sizes.astype(jnp.int32) > 0)[:, None, None]
    drhs = jnp.where(mask, drhs, 0.0)
    return dlhs_p[pos], drhs.astype(rhs.dtype), None


_gmm.defvjp(_gmm_vjp_fwd, _gmm_vjp_bwd)


def gmm(lhs, rhs, group_sizes, *, block_rows: int = _BLOCK_ROWS,
        block_f: int = _BLOCK_F, interpret: bool = False,
        force: bool = False):
    """Grouped matmul: rows of ``lhs`` [rows, d], sorted so group ``g``
    occupies ``group_sizes[:g].sum() : group_sizes[:g+1].sum()``, each
    multiplied by ``rhs[g]`` [d, f].  Differentiable in ``lhs`` and ``rhs``.

    Requires ``d`` and ``f`` to be 128-multiples for the kernel path; falls
    back to the dense one-hot reference off-TPU or for tiny shapes.
    """
    rows, d = lhs.shape
    f = rhs.shape[2]
    use_kernel = force or (
        jax.default_backend() == "tpu"
        and d % 128 == 0
        and f % 128 == 0
        and rows >= block_rows
    )
    if not use_kernel:
        return gmm_reference(lhs, rhs, group_sizes)
    return _gmm(lhs, rhs, group_sizes, block_rows, block_f, interpret)
