"""Pallas TPU kernels for the hot ops (SURVEY.md §7: native-code budget goes
to Pallas where XLA can't express the fusion)."""

from .flash_attention import (  # noqa: F401
    flash_attention,
    flash_supported,
    reference_attention,
)
