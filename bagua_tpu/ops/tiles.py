"""Shared TPU tile-size helpers for the Pallas kernels."""

LANE = 128

_CANDIDATES = (512, 384, 256, LANE)


def pick_block(dim: int, cap: int = 512) -> int:
    """Largest 128-multiple divisor of ``dim`` from the candidate set, not
    exceeding ``cap`` — bigger blocks amortize per-iteration kernel overhead
    while staying inside VMEM tiles."""
    for c in _CANDIDATES:
        if c <= cap and dim % c == 0:
            return c
    return LANE
