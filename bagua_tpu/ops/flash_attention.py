"""Fused flash attention — Pallas TPU kernels for the transformer hot path.

The reference framework has no attention code at all (SURVEY.md §5.7): its
BERT workload runs stock torch attention and Bagua only accelerates the
gradient communication around it.  Here the model family is first-class, so
its hottest op gets the TPU treatment the reference reserved for its CUDA
codec kernels (bagua_kernels.cu): a blockwise online-softmax attention that
never materializes the [seq, seq] score matrix in HBM.

Design (FlashAttention-2 style, TPU-first):

- forward: grid over (batch*heads, q_blocks); K/V for the whole sequence are
  resident in VMEM per grid step while each q block streams through, carrying
  (o, m, l) in registers through a ``fori_loop`` over k blocks.  Causal
  blocks above the diagonal are never visited (loop bound ``j+1``), the
  diagonal block is masked in-register.
- backward: saves only the per-row logsumexp (``m + log l``) and recomputes
  probabilities blockwise — two kernels, one accumulating dK/dV over q
  blocks at/after the diagonal, one accumulating dQ over k blocks at/before
  it.  ``delta = rowsum(dO * O)`` is a cheap XLA-fused precompute.
- all matmuls hit the MXU via ``dot_general(..., preferred_element_type=
  f32)``; softmax math is f32 on the VPU; inputs/outputs stay in the model
  dtype (bf16).

Falls back to the plain jnp implementation off-TPU, for tiny/ragged
sequence lengths, and under ``BAGUA_FLASH_ATTENTION=0``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANE = 128


def reference_attention(q, k, v, dtype, causal: bool = True):
    """Plain (materializing) attention; the fallback and the test golden.
    ``q/k/v``: [batch, seq, heads, head_dim]."""
    b, s, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k,
                scale):
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    j = pl.program_id(1)
    q = q_ref[0]  # keep model dtype: the MXU runs bf16 inputs at full rate
    n_kb_total = k_ref.shape[1] // block_k
    if causal:
        # last k block overlapping [0, (j+1)*block_q)
        n_kb = lax.min(
            (((j + 1) * block_q + block_k - 1) // block_k), n_kb_total
        )
    else:
        n_kb = n_kb_total
    q_pos = j * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        logits = scale * lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o * corr + pv, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = lax.fori_loop(0, n_kb, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    # lse written as an 8-sublane stripe: (1, block_q) output blocks violate
    # the TPU (8, 128) tile floor, so the row is broadcast over 8 sublanes
    lse = (m + jnp.log(l)).reshape(1, block_q)
    lse_ref[0] = jnp.broadcast_to(lse, (8, block_q))


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    """q/k/v: [bh, s, d] -> (o [bh, s, d], lse [bh, s] f32)."""
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kv_spec = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0),
                           memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, block_k=block_k,
            scale=float(1.0 / (d ** 0.5)),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_q), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal, block_q, scale):
    block_k, d = k_ref.shape[1], k_ref.shape[2]
    kb = pl.program_id(1)
    k_blk = k_ref[0]
    v_blk = v_ref[0]
    n_qb_total = q_ref.shape[1] // block_q
    qb_start = (kb * block_k) // block_q if causal else 0
    k_pos = kb * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        delta = (
            delta_ref[0, 0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
        )
        s_ij = scale * lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = qb * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s_ij = jnp.where(q_pos >= k_pos, s_ij, NEG_INF)
        p = jnp.exp(s_ij - lse).astype(k_blk.dtype)
        # dV += P^T dO
        dv = dv + lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p.astype(jnp.float32) * (dp - delta)).astype(k_blk.dtype)
        # dK += scale * dS^T Q
        dk = dk + scale * lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(qb_start, n_qb_total, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, causal, block_k, scale):
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    j = pl.program_id(1)
    q_blk = q_ref[0]
    do_blk = do_ref[0]
    lse = lse_ref[0, 0, pl.ds(j * block_q, block_q)].reshape(block_q, 1)
    delta = delta_ref[0, 0, pl.ds(j * block_q, block_q)].reshape(block_q, 1)
    n_kb_total = k_ref.shape[1] // block_k
    if causal:
        n_kb = lax.min(
            (((j + 1) * block_q + block_k - 1) // block_k), n_kb_total
        )
    else:
        n_kb = n_kb_total
    q_pos = j * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s_ij = scale * lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s_ij = jnp.where(q_pos >= k_pos, s_ij, NEG_INF)
        p = jnp.exp(s_ij - lse)
        dp = lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        return dq + scale * lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = lax.fori_loop(0, n_kb, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret,
         dlse=None):
    """``lse``: [bh, 1, s] f32 (one sublane of the forward's stripe).

    ``dlse`` [bh, s]: cotangent of the logsumexp output (only when the
    caller consumed lse, e.g. ring-attention merging).  It enters the
    standard backward as ``ds_ij += p_ij * dlse_i``, i.e. an effective
    ``delta_i - dlse_i`` — no kernel change needed.
    """
    bh, s, d = q.shape
    delta = (
        (do.astype(jnp.float32) * o.astype(jnp.float32))
        .sum(axis=-1)
        .reshape(bh, 1, s)
    )
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32).reshape(bh, 1, s)

    seq_spec = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    kb_spec = pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0),
                           memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, block_q=block_q,
            scale=float(1.0 / (d ** 0.5)),
        ),
        grid=(bh, s // block_k),
        in_specs=[seq_spec, kb_spec, kb_spec, seq_spec, row_spec, row_spec],
        out_specs=[kb_spec, kb_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    qb_spec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                           memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, block_k=block_k,
            scale=float(1.0 / (d ** 0.5)),
        ),
        grid=(bh, s // block_q),
        in_specs=[qb_spec, seq_spec, seq_spec, qb_spec, row_spec, row_spec],
        out_specs=qb_spec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, lse[:, 0, :]


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal, block_q, block_k, interpret)
    return (o, lse[:, 0, :]), (q, k, v, o, lse[:, :1, :])


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret,
                dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(q, k, v, *, causal: bool, block_q: int = 0,
                             block_k: int = 0, interpret: bool = False):
    """Like :func:`flash_attention` but also returns the per-row logsumexp
    ([batch, heads, seq] f32) — the merge statistic for combining partial
    attentions over K/V blocks (ring attention).  No fallback: the caller
    gates on :func:`flash_supported`.  Output ``o`` is f32 (merging
    precision)."""
    from .tiles import pick_block

    b, s, h, d = q.shape
    # the kernels size K/V buffers from q's length — equal chunks only
    assert k.shape[1] == s and v.shape[1] == s, (q.shape, k.shape, v.shape)
    block_q = block_q or pick_block(s)
    block_k = block_k or pick_block(s)
    if s % block_q or s % block_k:
        # no silent fallback here (the caller gates on flash_supported):
        # a non-divisible grid would TRUNCATE the sequence
        raise ValueError(
            f"seq {s} is not a multiple of block sizes "
            f"({block_q}, {block_k}); flash_attention_with_lse has no "
            "reference fallback"
        )

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    o, lse = _flash_lse(fold(q), fold(k), fold(v), causal, block_q, block_k,
                        interpret)
    o = o.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(jnp.float32)
    return o, lse.reshape(b, h, s)


def _enabled() -> bool:
    from .. import env

    return env.is_flash_attention_enabled()


# below this XLA's fused attention is already faster — re-validated r5 at
# BERT-Large's seq 384: plain 104.7 vs forced-flash 99.2 seq/s at batch 8
# (BENCH_BERT_SWEEP.json); the kernel pays from ~1k tokens (3.0x at 4096)
MIN_FLASH_SEQ = 1024


def flash_supported(seq: int, head_dim: int, block: int = _LANE) -> bool:
    """Whether the fused kernel pays: on-TPU, sequence long enough that the
    [seq, seq] HBM materialization hurts (measured crossover ~1k on v5p),
    block-aligned, and K/V + Q/dO fitting the per-step VMEM budget."""
    if not _enabled():
        return False
    if jax.default_backend() != "tpu":
        return False
    if seq < MIN_FLASH_SEQ or seq % block:
        return False
    # each kernel keeps 2 full-sequence operands resident (K+V fwd, Q+dO in
    # the dK/dV pass), double-buffered by the pipeline: 4 bf16 seq×lane
    # buffers must stay under the ~16 MB VMEM budget with headroom
    return 4 * seq * max(head_dim, _LANE) * 2 <= 12 * 1024 * 1024


def flash_attention(q, k, v, dtype=None, *, causal: bool = True,
                    block_q: int = 0, block_k: int = 0,
                    interpret: bool = False, force: bool = False):
    """Drop-in for :func:`reference_attention`: ``q/k/v`` are
    [batch, seq, heads, head_dim], returns [batch, seq, heads, head_dim] in
    ``dtype`` (default: q.dtype).

    ``force`` skips the platform check (tests run the kernel in interpret
    mode on CPU).
    """
    from .tiles import pick_block

    b, s, h, d = q.shape
    dtype = dtype or q.dtype
    block_q = block_q or pick_block(s)
    block_k = block_k or pick_block(s)
    if not force and not flash_supported(s, d, max(block_q, block_k)):
        return reference_attention(q, k, v, dtype, causal=causal)
    if s % block_q or s % block_k:
        return reference_attention(q, k, v, dtype, causal=causal)

    def fold(x):  # [b, s, h, d] -> [b*h, s, d]
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    # lse is discarded; its zero cotangent enters the backward as a no-op
    o, _ = _flash_lse(fold(q), fold(k), fold(v), causal, block_q, block_k,
                      interpret)
    return (
        o.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(dtype)
    )
