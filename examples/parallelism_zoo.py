"""Every parallelism axis in one script: dp, hierarchical, tp, pp, sp, ep.

Runs on a virtual 8-device CPU mesh by default (same mechanism as the test
suite) so it works on any machine:

    python examples/parallelism_zoo.py

On a real TPU pod slice, drop the env overrides and size the meshes to
``len(jax.devices())``.  The reference framework covers only the dp rows
(SURVEY.md §2.3); tp/pp/sp are additive capabilities of this rebuild.
"""

import os
import sys

# runnable from a plain checkout: `python examples/parallelism_zoo.py`
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BAGUA_ZOO_REAL_DEVICES", "0") != "1":
    # demo default: a virtual 8-device CPU mesh (works everywhere); set
    # BAGUA_ZOO_REAL_DEVICES=1 on a pod slice with >= 8 real chips.
    # last-occurrence-wins, so appending overrides any inherited count
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if os.environ.get("BAGUA_ZOO_REAL_DEVICES", "0") != "1":
    # an accelerator-plugin sitecustomize may pre-empt JAX_PLATFORMS
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import bagua_tpu  # noqa: E402
from bagua_tpu.algorithms.gradient_allreduce import (  # noqa: E402
    GradientAllReduceAlgorithm,
)
from bagua_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    TransformerLM,
    lm_loss_fn,
    sp_lm_loss_fn,
    tp_param_dim,
)
from bagua_tpu.parallel.mesh import build_mesh  # noqa: E402

VOCAB, SEQ = 64, 16


def _data(batch, seq=SEQ):
    return jax.random.randint(jax.random.PRNGKey(0), (batch, seq + 1), 0, VOCAB)


def _cfg(**kw):
    return TransformerConfig(vocab_size=VOCAB, d_model=32, n_heads=4,
                             n_layers=4, d_ff=64, max_seq_len=SEQ,
                             dtype=jnp.float32, **kw)


def run(name, trainer, params, tokens, steps=5):
    state = trainer.init(params)
    batch = trainer.shard_batch({"tokens": tokens})
    for _ in range(steps):
        state, loss = trainer.train_step(state, batch)
    print(f"{name:32s} loss after {steps} steps: {float(loss):.4f}")


def main():
    bagua_tpu.init_process_group()
    n = len(jax.devices())
    assert n >= 8, f"need 8 devices, found {n}"

    # --- data parallel (flat) --------------------------------------------
    model = TransformerLM(_cfg())
    tokens = _data(16)
    params = model.init(jax.random.PRNGKey(1), tokens[:2, :-1])["params"]
    run("dp=8", bagua_tpu.BaguaTrainer(
        lm_loss_fn(model), optax.adam(1e-2), GradientAllReduceAlgorithm(),
        mesh=build_mesh({"dp": 8}), autotune=False), params, tokens)

    # --- hierarchical (inter x intra, the reference's Leader/Worker) -----
    run("hierarchical inter=2 x intra=4", bagua_tpu.BaguaTrainer(
        lm_loss_fn(model), optax.adam(1e-2),
        GradientAllReduceAlgorithm(hierarchical=True),
        mesh=build_mesh({"inter": 2, "intra": 4}), autotune=False),
        params, tokens)

    # --- tensor parallel (Megatron-style) --------------------------------
    from bagua_tpu.parallel.tensor_parallel import globalize_tp_params

    tp_model = TransformerLM(_cfg(tp_axis="tp", tp_size=4))
    tp_params = globalize_tp_params(
        tp_model.init(jax.random.PRNGKey(2), tokens[:2, :-1])["params"],
        jax.random.PRNGKey(3), 4, tp_param_dim)
    run("dp=2 x tp=4", bagua_tpu.BaguaTrainer(
        lm_loss_fn(tp_model), optax.adam(1e-2), GradientAllReduceAlgorithm(),
        mesh=build_mesh({"dp": 2, "tp": 4}), tp_axis="tp", autotune=False),
        tp_params, tokens)

    # --- pipeline parallel (GPipe microbatches) --------------------------
    from bagua_tpu.parallel.pipeline import (
        PipelinedTransformerLM, globalize_pp_params, pp_lm_loss_fn,
    )

    pp_model = PipelinedTransformerLM(_cfg(), pp_size=4, n_microbatches=2)
    pp_params = globalize_pp_params(
        pp_model.init(jax.random.PRNGKey(4), tokens[:2])["params"],
        jax.random.PRNGKey(5), 4)
    run("dp=2 x pp=4 (2 microbatches)", bagua_tpu.BaguaTrainer(
        pp_lm_loss_fn(pp_model), optax.adam(1e-2),
        GradientAllReduceAlgorithm(),
        mesh=build_mesh({"dp": 2, "pp": 4}), pp_axis="pp", autotune=False),
        pp_params, tokens)

    # --- sequence parallel (ring attention) ------------------------------
    from bagua_tpu.parallel.ring_attention import make_ring_attention

    sp_cfg = _cfg(sp_axis="sp")
    sp_model = TransformerLM(sp_cfg, attn_fn=make_ring_attention(4))
    sp_params = sp_model.init(
        jax.random.PRNGKey(6), tokens[:2, : SEQ // 4])["params"]
    run("dp=2 x sp=4 (ring attention)", bagua_tpu.BaguaTrainer(
        sp_lm_loss_fn(sp_model, sp_size=4), optax.adam(1e-2),
        GradientAllReduceAlgorithm(),
        mesh=build_mesh({"dp": 2, "sp": 4}), seq_axis="sp", autotune=False),
        sp_params, tokens)

    # --- expert parallel (dropless MoE) ----------------------------------
    from bagua_tpu.model_parallel.moe import MoEMLP, moe_lm_loss_fn
    from bagua_tpu.model_parallel.moe.layer import globalize_expert_params

    moe_model = TransformerLM(_cfg(), mlp_factory=lambda i: (
        lambda: MoEMLP(n_experts=8, d_ff=64, k=2, ep_size=4, dropless=True,
                       dtype=jnp.float32)
    ) if i == 1 else None)
    moe_params = globalize_expert_params(
        moe_model.init(jax.random.PRNGKey(7), tokens[:2, :-1])["params"],
        jax.random.PRNGKey(8), ep_size=4)
    run("dp=2 x ep=4 (dropless MoE)", bagua_tpu.BaguaTrainer(
        moe_lm_loss_fn(moe_model), optax.adam(1e-2),
        GradientAllReduceAlgorithm(),
        mesh=build_mesh({"dp": 2, "ep": 4}), expert_axis="ep",
        autotune=False), moe_params, tokens)

    # --- 3-D: dp x pp x tp in one step -----------------------------------
    m3_cfg = _cfg(tp_axis="tp", tp_size=2)
    m3 = PipelinedTransformerLM(m3_cfg, pp_size=2, n_microbatches=2)
    m3_params = globalize_pp_params(
        m3.init(jax.random.PRNGKey(9), tokens[:2])["params"],
        jax.random.PRNGKey(10), 2, tp_size=2)
    run("dp=2 x pp=2 x tp=2 (3-D)", bagua_tpu.BaguaTrainer(
        pp_lm_loss_fn(m3), optax.adam(1e-2), GradientAllReduceAlgorithm(),
        mesh=build_mesh({"dp": 2, "pp": 2, "tp": 2}), pp_axis="pp",
        tp_axis="tp", autotune=False), m3_params, tokens)

    # --- ZeRO-1 (sharded optimizer state) + grad accumulation ------------
    from bagua_tpu.algorithms.zero import ZeroOptimizerAlgorithm

    run("dp=8 ZeRO-1 + accum=2", bagua_tpu.BaguaTrainer(
        lm_loss_fn(model), None,
        ZeroOptimizerAlgorithm(optax.adam(1e-2), clip_global_norm=1.0),
        mesh=build_mesh({"dp": 8}), accum_steps=2, autotune=False),
        params, tokens)

    print("all parallelism axes ran")


if __name__ == "__main__":
    main()
