"""BERT-Large SQuAD-style fine-tune — the BASELINE.json compressed-comm workload.

Counterpart of /root/reference/examples/squad/main.py (BERT-Large SQuAD
fine-tuning, the workload BASELINE.json names for ByteGrad/QAdam).  A span
head (start/end logits) sits on the Transformer encoder; data is
SQuAD-shaped synthetic by default (seq 384, span labels) — pass ``--dataset``
with a tokenized .npz (input_ids, start_positions, end_positions) for real
data.

    python examples/squad_finetune.py --algorithm bytegrad --steps 10
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms.bytegrad import ByteGradAlgorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.algorithms.q_adam import QAdamAlgorithm
from bagua_tpu.models.transformer import TransformerConfig, TransformerLM, bert_large_config


class SquadModel(nn.Module):
    """Encoder trunk + span-extraction head (start/end logits)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids):
        hidden = TransformerLM(self.cfg, head=False)(input_ids)
        logits = nn.Dense(2, dtype=jnp.float32, name="qa_head")(hidden)
        return logits[..., 0], logits[..., 1]  # start, end: [B, S]


def make_algorithm(name: str, lr: float):
    if name == "bytegrad":
        return ByteGradAlgorithm(hierarchical=False), optax.adamw(lr)
    if name == "qadam":
        return QAdamAlgorithm(warmup_steps=20, lr=lr, hierarchical=False), None
    return GradientAllReduceAlgorithm(), optax.adamw(lr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--algorithm", default="bytegrad",
                    choices=["gradient_allreduce", "bytegrad", "qadam"])
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=384)
    ap.add_argument("--lr", type=float, default=3e-5)
    ap.add_argument("--tiny", action="store_true",
                    help="4-layer config for CPU smoke runs")
    ap.add_argument("--dataset", type=str, default=None,
                    help=".npz with input_ids/start_positions/end_positions")
    args = ap.parse_args()

    bagua_tpu.init_process_group()
    n_dev = len(jax.devices())
    batch = args.batch * n_dev

    if args.tiny:
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_heads=4,
                                n_layers=4, d_ff=512, max_seq_len=args.seq)
    else:
        cfg = bert_large_config(max_seq_len=args.seq)
    model = SquadModel(cfg)

    if args.dataset:
        # real tokenized SQuAD rows: cycle through the WHOLE file batch by
        # batch (the reference fine-tunes over the real dataset, not one
        # memorized batch; .buildkite benchmark_master.sh:83-153)
        data = np.load(args.dataset)
        n_rows = (len(data["input_ids"]) // batch) * batch
        if n_rows == 0:
            raise SystemExit(f"dataset has fewer than {batch} rows")
        ids = data["input_ids"][:n_rows].astype(np.int32)
        starts = data["start_positions"][:n_rows].astype(np.int32)
        ends = data["end_positions"][:n_rows].astype(np.int32)
    else:
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (batch, args.seq)).astype(np.int32)
        starts = rng.integers(0, args.seq, batch).astype(np.int32)
        ends = np.minimum(starts + rng.integers(1, 16, batch), args.seq - 1).astype(np.int32)

    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids[:2]))["params"]

    def loss_fn(p, b):
        s_logits, e_logits = model.apply({"params": p}, b["ids"])
        return 0.5 * (
            optax.softmax_cross_entropy_with_integer_labels(s_logits, b["start"]).mean()
            + optax.softmax_cross_entropy_with_integer_labels(e_logits, b["end"]).mean()
        )

    algo, tx = make_algorithm(args.algorithm, args.lr)
    trainer = bagua_tpu.BaguaTrainer(loss_fn, tx, algo)
    state = trainer.init(params)
    n_batches = max(1, len(ids) // batch)
    shards = {}  # shard lazily: only batches --steps actually touches

    def shard(k):
        if k not in shards:
            shards[k] = trainer.shard_batch({
                "ids": ids[k * batch:(k + 1) * batch],
                "start": starts[k * batch:(k + 1) * batch],
                "end": ends[k * batch:(k + 1) * batch],
            })
        return shards[k]

    import time

    losses = []
    t0 = None
    for step in range(args.steps):
        state, loss = trainer.train_step(state, shard(step % n_batches))
        losses.append(float(loss))
        if step == 0:
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0 if args.steps > 1 else float("nan")
    seq_per_sec = (args.steps - 1) * batch / dt
    print(f"algorithm={args.algorithm} first_loss={losses[0]:.4f} "
          f"final_loss={losses[-1]:.4f} throughput={seq_per_sec:.2f} seq/s")
    assert losses[-1] < losses[0], "no learning signal"


if __name__ == "__main__":
    main()
