"""MoE MNIST example — expert parallelism end to end.

Counterpart of /root/reference/examples/moe/mnist_main.py (an MNIST net whose
hidden layer is a DeepSpeed-style MoE, trained under with_bagua and gated in
CI on an exact final loss, benchmark_master.sh:126-153).  Uses
MNIST-shaped synthetic data (no dataset download in this image); pass
``--mnist-dir`` with the standard IDX files to train on real MNIST.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe_mnist.py --steps 60
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.model_parallel.moe import MoEMLP
from bagua_tpu.model_parallel.moe.layer import globalize_expert_params
from bagua_tpu.parallel.mesh import build_mesh

import flax.linen as nn


class MoEMnistNet(nn.Module):
    """Conv stem -> MoE hidden layer -> classifier (reference mnist_main.py
    shape: two convs, an MoE fc1, fc2 head)."""

    n_experts: int = 4
    ep_size: int = 1

    @nn.compact
    def __call__(self, x):  # x: [B, 28, 28, 1]
        x = nn.relu(nn.Conv(16, (3, 3), (2, 2))(x))
        x = nn.relu(nn.Conv(32, (3, 3), (2, 2))(x))
        x = x.reshape(x.shape[0], 1, -1)          # [B, 1, feat] as tokens
        x = nn.Dense(64)(x)
        x = MoEMLP(n_experts=self.n_experts, d_ff=128,
                   ep_size=self.ep_size, k=1)(x)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(10)(x)


def load_batches(args, rng):
    if args.mnist_dir:
        import gzip
        import struct

        with gzip.open(os.path.join(args.mnist_dir, "train-images-idx3-ubyte.gz")) as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(num, rows, cols, 1)
        with gzip.open(os.path.join(args.mnist_dir, "train-labels-idx1-ubyte.gz")) as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8)
        images = images.astype(np.float32) / 255.0
    else:  # synthetic MNIST-shaped, deterministic
        images = rng.normal(size=(args.batch * 8, 28, 28, 1)).astype(np.float32)
        labels = rng.integers(0, 10, args.batch * 8)
    return images, labels.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--mnist-dir", type=str, default=None)
    args = ap.parse_args()

    bagua_tpu.init_process_group()
    n_dev = len(jax.devices())
    ep = n_dev if n_dev > 1 else 1
    mesh = build_mesh({"dp": 1, "ep": ep}) if ep > 1 else build_mesh()

    model = MoEMnistNet(n_experts=max(4, ep), ep_size=ep)
    rng = np.random.default_rng(0)
    images, labels = load_batches(args, rng)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(images[:2]))["params"]
    if ep > 1:
        params = globalize_expert_params(params, jax.random.PRNGKey(1), ep_size=ep)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()

    trainer = bagua_tpu.BaguaTrainer(
        loss_fn, optax.adam(args.lr), GradientAllReduceAlgorithm(),
        mesh=mesh, expert_axis="ep" if ep > 1 else None,
    )
    state = trainer.init(params)

    losses = []
    for step in range(args.steps):
        lo = (step * args.batch) % (len(images) - args.batch)
        batch = trainer.shard_batch({
            "x": images[lo:lo + args.batch], "y": labels[lo:lo + args.batch],
        })
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
        if step % 10 == 0:
            print(f"step {step} loss {losses[-1]:.6f}")
    print(f"final_loss {losses[-1]:.6f}")
    assert losses[-1] < losses[0], (losses[0], losses[-1])


if __name__ == "__main__":
    main()
