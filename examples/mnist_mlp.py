"""Minimum end-to-end example (reference examples/mnist/main.py equivalent):
an MLP on real handwritten digits (the vendored 8x8 scans — see
bagua_tpu/contrib/digits_data.py) with the gradient_allreduce algorithm;
``--data synthetic`` switches to the MNIST-shaped synthetic teacher task.

Run directly (single process, all local devices) or through the launcher:

    python -m bagua_tpu.distributed.run --autotune_level 1 examples/mnist_mlp.py
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import bagua_tpu
from bagua_tpu.algorithms import (
    AsyncModelAverageAlgorithm,
    ByteGradAlgorithm,
    DecentralizedAlgorithm,
    GradientAllReduceAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
    QAdamAlgorithm,
)
from bagua_tpu.models.mlp import MLP


def make_algorithm(name: str):
    return {
        "gradient_allreduce": lambda: GradientAllReduceAlgorithm(),
        "bytegrad": lambda: ByteGradAlgorithm(),
        "decentralized": lambda: DecentralizedAlgorithm(),
        "low_precision_decentralized": lambda: LowPrecisionDecentralizedAlgorithm(),
        "async": lambda: AsyncModelAverageAlgorithm(),
        "qadam": lambda: QAdamAlgorithm(warmup_steps=20),
    }[name]()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--algorithm", default="gradient_allreduce")
    ap.add_argument("--data", choices=("digits", "synthetic"), default="digits",
                    help="real vendored digit scans (default) or the "
                         "synthetic fixed-teacher task")
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch-per-device", type=int, default=None,
                    help="synthetic mode only (default 32); digits mode "
                         "trains full-batch")
    ap.add_argument("--lr", type=float, default=None,
                    help="default: 2e-3 (adam) on digits, 0.05 (sgd+mom) "
                         "synthetic")
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()
    if args.data == "digits" and args.batch_per_device is not None:
        ap.error("--batch-per-device only applies to --data synthetic "
                 "(digits trains full-batch)")

    mesh = bagua_tpu.init_process_group()
    n_dev = len(jax.devices())

    key = jax.random.PRNGKey(args.seed)
    k1, k2, k3 = jax.random.split(key, 3)
    x_test = y_test = None
    if args.data == "digits":
        from bagua_tpu.contrib.digits_data import load_digits_dataset

        xt, yt, x_test, y_test = load_digits_dataset(train_multiple_of=n_dev)
        x, y = jnp.asarray(xt), jnp.asarray(yt)  # full-batch (1.5k rows)
        in_dim, lr = 64, (args.lr if args.lr is not None else 2e-3)
        model = MLP(features=(128, 64, 10))
        opt_fn = lambda: optax.adam(lr)
    else:
        # synthetic, learnable MNIST-shaped task (fixed teacher)
        args.lr = 0.05 if args.lr is None else args.lr
        batch = (args.batch_per_device or 32) * n_dev
        x = jax.random.normal(k1, (batch, 28 * 28))
        teacher = jax.random.normal(k2, (28 * 28, 10))
        y = jnp.argmax(x @ teacher, axis=-1)
        in_dim = 28 * 28
        model = MLP(features=(128, 64, 10))
        opt_fn = lambda: optax.sgd(args.lr, momentum=0.9)
    params = model.init(k3, jnp.zeros((2, in_dim)))["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]
        ).mean()

    algo = make_algorithm(args.algorithm)
    opt = None if algo.owns_optimizer else opt_fn()
    trainer = bagua_tpu.BaguaTrainer(loss_fn, opt, algo, mesh=mesh,
                                     model_name="mnist_mlp")
    state = trainer.init(params)
    batch_tree = trainer.shard_batch({"x": x, "y": y})
    for step in range(args.steps):
        state, loss = trainer.train_step(state, batch_tree)
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step} loss {float(loss):.6f}", flush=True)
    print(f"final_loss {float(loss):.6f}", flush=True)
    if x_test is not None:
        params = trainer.unstack_params(state)
        logits = model.apply({"params": params}, jnp.asarray(x_test))
        acc = float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y_test)))
        print(f"test_accuracy {acc:.4f}", flush=True)


if __name__ == "__main__":
    main()
