"""Minimum end-to-end example (reference examples/mnist/main.py equivalent):
an MLP on a synthetic MNIST-shaped task with the gradient_allreduce algorithm.

Run directly (single process, all local devices) or through the launcher:

    python -m bagua_tpu.distributed.run --autotune_level 1 examples/mnist_mlp.py
"""

import argparse

import jax
import jax.numpy as jnp
import optax

import bagua_tpu
from bagua_tpu.algorithms import (
    AsyncModelAverageAlgorithm,
    ByteGradAlgorithm,
    DecentralizedAlgorithm,
    GradientAllReduceAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
    QAdamAlgorithm,
)
from bagua_tpu.models.mlp import MLP


def make_algorithm(name: str):
    return {
        "gradient_allreduce": lambda: GradientAllReduceAlgorithm(),
        "bytegrad": lambda: ByteGradAlgorithm(),
        "decentralized": lambda: DecentralizedAlgorithm(),
        "low_precision_decentralized": lambda: LowPrecisionDecentralizedAlgorithm(),
        "async": lambda: AsyncModelAverageAlgorithm(),
        "qadam": lambda: QAdamAlgorithm(warmup_steps=20),
    }[name]()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--algorithm", default="gradient_allreduce")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch-per-device", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()

    mesh = bagua_tpu.init_process_group()
    n_dev = len(jax.devices())
    model = MLP(features=(128, 64, 10))

    # synthetic, learnable MNIST-shaped task (fixed teacher)
    key = jax.random.PRNGKey(args.seed)
    k1, k2, k3 = jax.random.split(key, 3)
    batch = args.batch_per_device * n_dev
    x = jax.random.normal(k1, (batch, 28 * 28))
    teacher = jax.random.normal(k2, (28 * 28, 10))
    y = jnp.argmax(x @ teacher, axis=-1)
    params = model.init(k3, x[:2])["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]
        ).mean()

    algo = make_algorithm(args.algorithm)
    opt = None if algo.owns_optimizer else optax.sgd(args.lr, momentum=0.9)
    trainer = bagua_tpu.BaguaTrainer(loss_fn, opt, algo, mesh=mesh,
                                     model_name="mnist_mlp")
    state = trainer.init(params)
    for step in range(args.steps):
        state, loss = trainer.train_step(state, {"x": x, "y": y})
        trainer.record_speed(batch)
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step} loss {float(loss):.6f}", flush=True)
    print(f"final_loss {float(loss):.6f}", flush=True)


if __name__ == "__main__":
    main()
