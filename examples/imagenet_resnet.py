"""ResNet50 classification — the full contrib stack in one training loop.

Counterpart of /root/reference/examples/imagenet/main.py (ResNet + real data
pipeline + DDP-style training).  Demonstrates every contrib piece working
together the way the reference's example composes its utilities:

- ``CachedDataset`` over the (native C++ when available) TCP store — slow
  sample decode paid once;
- ``LoadBalancingDistributedSampler`` — complexity-balanced shards;
- ``SyncBatchNorm`` via ``ResNet.norm_cls`` — cross-shard batch statistics;
- ``fuse_optimizer`` — per-dtype fused update buffers;
- any communication algorithm via ``--algorithm``.

Synthetic ImageNet-shaped data by default; point ``--data-dir`` at a
directory of ``{class}/{img}.npy`` arrays for real images.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imagenet_resnet.py --steps 4 --tiny
"""

import argparse
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms.bytegrad import ByteGradAlgorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.contrib import (
    CachedDataset,
    LoadBalancingDistributedSampler,
    SyncBatchNorm,
    fuse_optimizer,
)
from bagua_tpu.models.resnet import ResNet, ResNet50, classification_loss_fn


class SyntheticImageNet:
    """ImageNet-shaped samples with a deterministic 'decode' cost."""

    def __init__(self, n, size, classes):
        self.n, self.size, self.classes = n, size, classes

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        img = rng.normal(size=(self.size, self.size, 3)).astype(np.float32)
        return img, int(i % self.classes)


class NpyDirImageNet:
    """Real images from ``{data_dir}/{class_name}/*.npy`` — each file one
    HWC float/uint8 array (the reference example reads an ImageFolder tree,
    /root/reference/examples/imagenet/main.py; zero-egress environments
    pre-decode to .npy).  Labels follow sorted class-dir order.  Arrays are
    center-cropped/padded to ``size`` and normalized to zero mean."""

    def __init__(self, data_dir, size):
        import os

        self.size = size
        self.items = []
        classes = sorted(
            d for d in os.listdir(data_dir)
            if os.path.isdir(os.path.join(data_dir, d))
        )
        self.classes = len(classes)
        for label, cls in enumerate(classes):
            cdir = os.path.join(data_dir, cls)
            for f in sorted(os.listdir(cdir)):
                if f.endswith(".npy"):
                    self.items.append((os.path.join(cdir, f), label))
        if not self.items:
            raise FileNotFoundError(f"no {{class}}/*.npy under {data_dir}")

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        path, label = self.items[i]
        img = np.load(path).astype(np.float32)
        if img.ndim == 3 and img.shape[-1] == 1:
            img = img[..., 0]  # (H, W, 1) grayscale
        if img.ndim == 2:
            img = np.stack([img] * 3, -1)
        if img.max() > 2.0:  # uint8-range input
            img = img / 127.5 - 1.0
        s = self.size
        h, w = img.shape[:2]
        if h < s or w < s:
            img = np.pad(img, ((0, max(0, s - h)), (0, max(0, s - w)), (0, 0)))
            h, w = img.shape[:2]
        top, left = (h - s) // 2, (w - s) // 2
        return img[top:top + s, left:left + s, :3], label


class _Subset:
    """Index-remapped view (train split) over a dataset/cache."""

    def __init__(self, base, idx):
        self.base, self.idx = base, idx

    def __len__(self):
        return len(self.idx)

    def __getitem__(self, i):
        return self.base[self.idx[i]]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-per-device", type=int, default=8)
    ap.add_argument("--algorithm", default="gradient_allreduce",
                    choices=["gradient_allreduce", "bytegrad"])
    ap.add_argument("--tiny", action="store_true",
                    help="small ResNet + 64px images for CPU smoke runs")
    ap.add_argument("--data-dir", type=str, default=None,
                    help="directory of {class}/{img}.npy real images")
    ap.add_argument("--epochs", type=int, default=1,
                    help="passes over the real dataset (with --data-dir)")
    ap.add_argument("--eval-frac", type=float, default=0.2,
                    help="held-out fraction for the accuracy gate")
    ap.add_argument("--gate-accuracy", type=float, default=None,
                    help="fail unless held-out accuracy reaches this")
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    mesh = bagua_tpu.init_process_group()
    n_dev = len(jax.devices())
    batch = args.batch_per_device * n_dev
    size = 64 if args.tiny else 224

    real = NpyDirImageNet(args.data_dir, size) if args.data_dir else None
    classes = real.classes if real else (16 if args.tiny else 1000)

    norm_cls = partial(SyncBatchNorm, axis_name=mesh.axis_names)
    if args.tiny:
        model = ResNet(stage_sizes=(1, 1), num_classes=classes,
                       num_filters=16, norm_cls=norm_cls)
    else:
        model = ResNet50(num_classes=classes, norm_cls=norm_cls)

    dataset = real if real else SyntheticImageNet(batch * 8, size, classes)
    # held-out split for the accuracy gate (real data only)
    eval_idx = []
    train_idx = list(range(len(dataset)))
    if real is not None and args.eval_frac > 0:
        rng = np.random.default_rng(0)
        perm = rng.permutation(len(dataset))
        n_eval = max(1, int(len(dataset) * args.eval_frac))
        eval_idx, train_idx = list(perm[:n_eval]), list(perm[n_eval:])

    cached = CachedDataset(dataset, backend="tcp", dataset_name="imagenet",
                           writer_buffer_size=8, num_shards=2)
    sampler = LoadBalancingDistributedSampler(
        _Subset(cached, train_idx),
        complexity_fn=lambda s: int(abs(s[0]).sum() * 100),
        num_replicas=1, rank=0,  # one JAX process drives all local chips
    )

    images = jnp.zeros((2, size, size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), images, train=True)
    algo = (ByteGradAlgorithm(hierarchical=False)
            if args.algorithm == "bytegrad"
            else GradientAllReduceAlgorithm())
    trainer = bagua_tpu.BaguaTrainer(
        classification_loss_fn(model, batch_stats=variables["batch_stats"]),
        fuse_optimizer(optax.sgd(args.lr, momentum=0.9)),
        algo, mesh=mesh,
    )
    state = trainer.init(variables["params"])

    indices = list(sampler)  # positions into train_idx
    steps = (
        args.epochs * max(1, len(indices) // batch) if real else args.steps
    )
    losses = []
    for step in range(steps):
        sel = [indices[(step * batch + j) % len(indices)] for j in range(batch)]
        samples = [cached[train_idx[i]] if real else cached[i] for i in sel]
        data = trainer.shard_batch({
            "images": np.stack([s[0] for s in samples]),
            "labels": np.array([s[1] for s in samples], np.int32),
        })
        state, loss = trainer.train_step(state, data)
        losses.append(float(loss))
        print(f"step {step} loss {losses[-1]:.4f}")
    n_cached = cached.cache_loader.num_keys()
    cached.cache_loader.store.shutdown()
    print(f"final_loss {losses[-1]:.6f} cache_entries {n_cached}")
    assert np.isfinite(losses[-1])

    if eval_idx:
        # held-out accuracy with batch-mode normalization (the trainer keeps
        # running stats frozen; see classification_loss_fn)
        apply = jax.jit(lambda p, x: model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )[0])
        correct = total = 0
        eb = min(batch, len(eval_idx))
        # leaf view (flat-resident state holds bucket flats)
        eval_params = trainer.unstack_params(state)
        for i0 in range(0, len(eval_idx), eb):
            sel = eval_idx[i0:i0 + eb]  # tail partial batch included
            samples = [dataset[i] for i in sel]
            logits = apply(eval_params,
                           jnp.asarray(np.stack([s[0] for s in samples])))
            pred = np.argmax(np.asarray(logits), -1)
            labels = np.array([s[1] for s in samples])
            correct += int((pred == labels).sum())
            total += len(sel)
        acc = correct / max(1, total)
        print(f"eval_accuracy {acc:.4f} ({total} held-out samples)")
        if args.gate_accuracy is not None:
            assert acc >= args.gate_accuracy, (
                f"held-out accuracy {acc:.3f} below gate {args.gate_accuracy}"
            )


if __name__ == "__main__":
    main()
