"""ResNet50 classification — the full contrib stack in one training loop.

Counterpart of /root/reference/examples/imagenet/main.py (ResNet + real data
pipeline + DDP-style training).  Demonstrates every contrib piece working
together the way the reference's example composes its utilities:

- ``CachedDataset`` over the (native C++ when available) TCP store — slow
  sample decode paid once;
- ``LoadBalancingDistributedSampler`` — complexity-balanced shards;
- ``SyncBatchNorm`` via ``ResNet.norm_cls`` — cross-shard batch statistics;
- ``fuse_optimizer`` — per-dtype fused update buffers;
- any communication algorithm via ``--algorithm``.

Synthetic ImageNet-shaped data by default; point ``--data-dir`` at a
directory of ``{class}/{img}.npy`` arrays for real images.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imagenet_resnet.py --steps 4 --tiny
"""

import argparse
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bagua_tpu
from bagua_tpu.algorithms.bytegrad import ByteGradAlgorithm
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.contrib import (
    CachedDataset,
    LoadBalancingDistributedSampler,
    SyncBatchNorm,
    fuse_optimizer,
)
from bagua_tpu.models.resnet import ResNet, ResNet50, classification_loss_fn


class SyntheticImageNet:
    """ImageNet-shaped samples with a deterministic 'decode' cost."""

    def __init__(self, n, size, classes):
        self.n, self.size, self.classes = n, size, classes

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        img = rng.normal(size=(self.size, self.size, 3)).astype(np.float32)
        return img, int(i % self.classes)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-per-device", type=int, default=8)
    ap.add_argument("--algorithm", default="gradient_allreduce",
                    choices=["gradient_allreduce", "bytegrad"])
    ap.add_argument("--tiny", action="store_true",
                    help="small ResNet + 64px images for CPU smoke runs")
    ap.add_argument("--data-dir", type=str, default=None)
    args = ap.parse_args()

    mesh = bagua_tpu.init_process_group()
    n_dev = len(jax.devices())
    batch = args.batch_per_device * n_dev
    size = 64 if args.tiny else 224
    classes = 16 if args.tiny else 1000

    norm_cls = partial(SyncBatchNorm, axis_name=mesh.axis_names)
    if args.tiny:
        model = ResNet(stage_sizes=(1, 1), num_classes=classes,
                       num_filters=16, norm_cls=norm_cls)
    else:
        model = ResNet50(num_classes=classes, norm_cls=norm_cls)

    dataset = SyntheticImageNet(batch * 8, size, classes)
    cached = CachedDataset(dataset, backend="tcp", dataset_name="imagenet",
                           writer_buffer_size=8, num_shards=2)
    sampler = LoadBalancingDistributedSampler(
        cached, complexity_fn=lambda s: int(abs(s[0]).sum() * 100),
        num_replicas=1, rank=0,  # one JAX process drives all local chips
    )

    images = jnp.zeros((2, size, size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), images, train=True)
    algo = (ByteGradAlgorithm(hierarchical=False)
            if args.algorithm == "bytegrad"
            else GradientAllReduceAlgorithm())
    trainer = bagua_tpu.BaguaTrainer(
        classification_loss_fn(model, batch_stats=variables["batch_stats"]),
        fuse_optimizer(optax.sgd(0.05, momentum=0.9)),
        algo, mesh=mesh,
    )
    state = trainer.init(variables["params"])

    indices = list(sampler)
    losses = []
    for step in range(args.steps):
        sel = [indices[(step * batch + j) % len(indices)] for j in range(batch)]
        samples = [cached[i] for i in sel]
        data = trainer.shard_batch({
            "images": np.stack([s[0] for s in samples]),
            "labels": np.array([s[1] for s in samples], np.int32),
        })
        state, loss = trainer.train_step(state, data)
        losses.append(float(loss))
        print(f"step {step} loss {losses[-1]:.4f}")
    n_cached = cached.cache_loader.num_keys()
    cached.cache_loader.store.shutdown()
    print(f"final_loss {losses[-1]:.6f} cache_entries {n_cached}")
    assert np.isfinite(losses[-1])


if __name__ == "__main__":
    main()
