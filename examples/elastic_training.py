"""Elastic training example (reference examples/elastic_training/main.py):
checkpoint every N steps, resume from the latest checkpoint on (re)start —
the launcher's gang restart makes this the recovery path after any worker
failure.

Crash injection for tests: set BAGUA_TEST_CRASH_AT_STEP=k and the process
exits(1) at step k on the FIRST attempt (a marker file suppresses repeats).
"""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import optax

import bagua_tpu
from bagua_tpu.algorithms import GradientAllReduceAlgorithm
from bagua_tpu.checkpoint import BaguaCheckpointManager
from bagua_tpu.models.mlp import MLP


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    mesh = bagua_tpu.init_process_group()
    n_dev = len(jax.devices())
    model = MLP(features=(32, 16, 8))
    key = jax.random.PRNGKey(args.seed)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, (8 * n_dev, 16))
    y = jnp.argmax(x @ jax.random.normal(k2, (16, 8)), axis=-1)
    params = model.init(k3, x[:2])["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]
        ).mean()

    trainer = bagua_tpu.BaguaTrainer(
        loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(), mesh=mesh
    )
    state = trainer.init(params)

    mgr = BaguaCheckpointManager(args.ckpt_dir, max_to_keep=2)
    # layout metadata: on an elastic restart at a DIFFERENT topology, a
    # plan-dependent (flat-resident ZeRO) checkpoint fails here with an
    # actionable error instead of an opaque orbax shape mismatch
    layout = trainer.checkpoint_layout_metadata()
    # mesh= anchors the restore to the LIVE mesh: on an elastic restart at
    # a different world size the checkpoint's recorded shardings describe
    # devices that no longer exist
    start_step, state = mgr.try_restore(
        state, expect_metadata=layout, mesh=mesh)
    if start_step is not None:
        print(f"resumed from checkpoint step {start_step}", flush=True)
        start = start_step + 1
    else:
        start = 0

    crash_at = int(os.environ.get("BAGUA_TEST_CRASH_AT_STEP", -1))
    marker = os.path.join(args.ckpt_dir, "crashed.marker")

    for step in range(start, args.steps):
        if step == crash_at and not os.path.exists(marker):
            open(marker, "w").close()
            mgr.wait()
            print("injected crash", flush=True)
            sys.exit(1)
        state, loss = trainer.train_step(state, {"x": x, "y": y})
        if step % args.save_every == 0 or step == args.steps - 1:
            mgr.save(step, state, metadata=layout)
        print(f"step {step} loss {float(loss):.6f}", flush=True)
    mgr.close()
    print(f"final_loss {float(loss):.6f}", flush=True)


if __name__ == "__main__":
    main()
