"""Cross-check every eager communication primitive against reference math.

Counterpart of /root/reference/examples/communication_primitives/main.py,
which cross-checks each bagua primitive against ``torch.distributed``.  There
is no second comm library to diff against on TPU, so the oracle is explicit
numpy math over the rank axis — same assertions, same coverage (send/recv,
broadcast, allreduce(+inplace), reduce, allgather, gather, scatter,
reduce_scatter, alltoall, alltoall_v, barrier).

Run on any device count (virtual CPU mesh works):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/communication_primitives.py
"""

import numpy as np
import jax
import jax.numpy as jnp

import bagua_tpu
from bagua_tpu import ReduceOp


def main():
    bagua_tpu.init_process_group()
    n = len(jax.devices())
    assert n >= 2, "world size must be at least 2 (use the virtual CPU mesh)"
    comm = bagua_tpu.get_backend("communication_primitives_test").global_communicator
    rng = np.random.default_rng(0)

    def rand(*shape):
        return rng.normal(size=shape).astype(np.float32)

    # send/recv (rank 0 -> rank 1, expressed as a permutation)
    x = rand(n, 4)
    out = np.asarray(bagua_tpu.send_recv(jnp.asarray(x), [(0, 1), (1, 0)] + [(r, r) for r in range(2, n)], comm=comm))
    np.testing.assert_allclose(out[1], x[0]), "send/recv"

    # broadcast
    x = rand(n, 4)
    out = np.asarray(bagua_tpu.broadcast(jnp.asarray(x), 0, comm=comm))
    for r in range(n):
        np.testing.assert_allclose(out[r], x[0])

    # allreduce + inplace
    x = rand(n, 4)
    out = np.asarray(bagua_tpu.allreduce(jnp.asarray(x), ReduceOp.SUM, comm=comm))
    out_inplace = np.asarray(bagua_tpu.allreduce_inplace(jnp.asarray(x), ReduceOp.SUM, comm=comm))
    np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)), rtol=1e-5)
    np.testing.assert_allclose(out, out_inplace)

    # reduce (only dst holds the result; non-dst recv untouched -> recv=)
    x = rand(n, 4)
    recv = rand(n, 4)
    out = np.asarray(bagua_tpu.reduce(
        jnp.asarray(x), 1, ReduceOp.SUM, comm=comm, recv=jnp.asarray(recv)))
    np.testing.assert_allclose(out[1], x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(out[0], recv[0])

    # allgather
    x = rand(n, 3)
    out = np.asarray(bagua_tpu.allgather(jnp.asarray(x), comm=comm))
    for r in range(n):
        np.testing.assert_allclose(out[r].reshape(n, 3)[r], x[r])

    # gather (dst holds everyone's slice; non-dst untouched -> zeros)
    out = np.asarray(bagua_tpu.gather(jnp.asarray(x), 0, comm=comm))
    np.testing.assert_allclose(out[0].reshape(n, 3), x)
    np.testing.assert_allclose(out[1], np.zeros_like(out[1]))

    # scatter (rank r gets chunk r of src's buffer)
    x = rand(n, n * 2)
    out = np.asarray(bagua_tpu.scatter(jnp.asarray(x), 0, comm=comm))
    for r in range(n):
        np.testing.assert_allclose(out[r], x[0].reshape(n, 2)[r])

    # reduce_scatter
    x = rand(n, n * 2)
    out = np.asarray(bagua_tpu.reduce_scatter(jnp.asarray(x), ReduceOp.SUM, comm=comm))
    total = x.sum(0).reshape(n, 2)
    for r in range(n):
        np.testing.assert_allclose(out[r], total[r], rtol=1e-5)

    # alltoall
    x = rand(n, n * 2)
    out = np.asarray(bagua_tpu.alltoall(jnp.asarray(x), comm=comm))
    for r in range(n):
        np.testing.assert_allclose(
            out[r].reshape(n, 2), x[:, r * 2:(r + 1) * 2]
        )

    # alltoall_v (ragged)
    counts = rng.integers(0, 3, (n, n))
    L = int(counts.sum(1).max())
    send = np.zeros((n, max(1, L)), np.float32)
    for r in range(n):
        send[r, :counts[r].sum()] = rng.normal(size=counts[r].sum())
    out = np.asarray(bagua_tpu.alltoall_v(jnp.asarray(send), counts, comm=comm))
    in_off = np.concatenate([np.zeros((n, 1), np.int64),
                             np.cumsum(counts, 1)[:, :-1]], 1)
    for d in range(n):
        pos = 0
        for s in range(n):
            c = counts[s][d]
            np.testing.assert_allclose(
                out[d, pos:pos + c], send[s, in_off[s][d]:in_off[s][d] + c]
            )
            pos += c

    # barrier
    bagua_tpu.barrier(comm=comm)

    print(f"communication primitives OK (world={n})")


if __name__ == "__main__":
    main()
