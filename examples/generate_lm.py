"""Train a tiny LM, then decode with the KV cache — single-device and
tensor-parallel.

Runs on a virtual 8-device CPU mesh by default (same mechanism as the test
suite):

    python examples/generate_lm.py

The script trains the LM to memorize a fixed token sequence through the
prefetching input pipeline, then generates the continuation back two ways
(plain `generate` and `generate_tp` over a tp=2 mesh) and checks they agree
with the memorized sequence.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BAGUA_ZOO_REAL_DEVICES", "0") != "1":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if os.environ.get("BAGUA_ZOO_REAL_DEVICES", "0") != "1":
    jax.config.update("jax_platforms", "cpu")
import dataclasses  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import bagua_tpu  # noqa: E402
from bagua_tpu.algorithms import GradientAllReduceAlgorithm  # noqa: E402
from bagua_tpu.contrib import prefetch_to_device  # noqa: E402
from bagua_tpu.models.generate import generate, generate_tp  # noqa: E402
from bagua_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    TransformerLM,
    lm_loss_fn,
)
from bagua_tpu.parallel.mesh import build_mesh  # noqa: E402


def main():
    bagua_tpu.init_process_group()
    n = len(jax.devices())

    cfg = TransformerConfig(vocab_size=32, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128, max_seq_len=24, dtype=jnp.float32)
    model = TransformerLM(cfg)
    seq = np.array([3, 14, 15, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 31, 8],
                   np.int32)
    tokens = np.tile(seq, (8 * n, 1))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(tokens[:2, :-1]))["params"]

    trainer = bagua_tpu.BaguaTrainer(
        lm_loss_fn(model), optax.adam(1e-2), GradientAllReduceAlgorithm(),
        donate=False, autotune=False,
    )
    state = trainer.init(params)
    n_steps = int(os.environ.get("BAGUA_EXAMPLE_STEPS", "80"))
    for batch in prefetch_to_device(
        ({"tokens": tokens} for _ in range(n_steps)), trainer=trainer, size=2
    ):
        state, loss = trainer.train_step(state, batch)
    print(f"final train loss: {float(loss):.5f}")

    trained = trainer.unstack_params(state)
    prompt = jnp.asarray(tokens[:2, :4])
    expect = np.tile(seq[4:-1], (2, 1))

    out = np.asarray(generate(model, trained, prompt, seq.size - 5))
    print("generated (1 device):", out[0].tolist())
    assert (out == expect).all(), (out[0], expect[0])

    if n >= 2 and (os.cpu_count() or 1) >= 2:
        # (single-core hosts skip: 8 virtual devices time-slicing one core
        # can trip XLA's collective stuck-detector mid-scan; the tp decode
        # path itself is covered by tests/test_generate.py)
        # the SAME replicated params drive tensor-parallel decode: tp=1
        # training params are valid tp slices only when re-laid-out, so
        # here we demo the API on a tp-configured model trained densely —
        # heads split 2 ways, logits reduced with the conjugate psum
        cfg_tp = dataclasses.replace(cfg, tp_axis="tp", tp_size=2)
        # NOTE: dense kernels ARE the global tp kernels; generate_tp shards
        # them along the head/width dims per tp_param_dim
        # mesh spans ALL devices (extra axes replicate): XLA's in-process
        # CPU communicator can wedge on collectives over a device SUBSET
        # when the process previously ran full-device work
        out_tp = np.asarray(generate_tp(
            TransformerLM(cfg_tp), trained, prompt, seq.size - 5,
            build_mesh({"rep": n // 2, "tp": 2}),
        ))
        print("generated (tp=2):    ", out_tp[0].tolist())
        assert (out_tp == expect).all(), (out_tp[0], expect[0])

    print("generate_lm OK")


if __name__ == "__main__":
    main()
