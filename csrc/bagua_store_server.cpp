// Native KV store server for bagua_tpu's contrib cache layer.
//
// Role counterpart of the redis-server instances the reference's RedisStore
// spawns per node (/root/reference/bagua/torch_api/contrib/utils/
// redis_store.py:38+): a small native daemon holding the shared sample
// cache, one per shard, fronted by the hash-sharded ClusterStore view.
// Thread-per-connection; values are opaque byte strings.
//
// Wire protocol (little-endian, mirrored in contrib/utils/tcp_store.py):
//   request:  u8 op | op payload; bytes fields are u32 len + raw
//   ops:      1=SET k v  2=GET k  3=MSET n (k v)*  4=MGET n k*
//             5=NUM_KEYS 6=CLEAR  7=PING           8=SHUTDOWN
//   response: GET -> u8 present + [val]
//             MGET -> u32 n + n*(u8 present + [val])
//             NUM_KEYS -> u64;  others -> u8 0
//
// Usage: bagua_store_server <host> <port>   (port 0 = auto-pick)
// Prints "LISTENING <port>" on stdout once bound.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Op : uint8_t {
  OP_SET = 1,
  OP_GET = 2,
  OP_MSET = 3,
  OP_MGET = 4,
  OP_NUM_KEYS = 5,
  OP_CLEAR = 6,
  OP_PING = 7,
  OP_SHUTDOWN = 8,
};

std::unordered_map<std::string, std::string> g_data;
std::mutex g_mu;
std::atomic<bool> g_shutdown{false};
int g_listen_fd = -1;

bool recv_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_all(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// sanity caps: a desynced or malicious client must not make the shared
// server allocate gigabytes from one malformed length field
constexpr uint32_t kMaxFrame = 1u << 30;  // 1 GiB per value
constexpr uint32_t kMaxBatch = 1u << 20;  // keys per mset/mget

bool recv_bytes(int fd, std::string* out) {
  uint32_t len;
  if (!recv_exact(fd, &len, 4)) return false;
  if (len > kMaxFrame) return false;  // drop the connection
  out->resize(len);
  return len == 0 || recv_exact(fd, out->data(), len);
}

void append_u32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

void append_bytes(std::string* out, const std::string& v) {
  append_u32(out, static_cast<uint32_t>(v.size()));
  out->append(v);
}

void handle_conn(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string key, val;
  for (;;) {
    uint8_t op;
    if (!recv_exact(fd, &op, 1)) break;
    switch (op) {
      case OP_SET: {
        if (!recv_bytes(fd, &key) || !recv_bytes(fd, &val)) goto done;
        {
          std::lock_guard<std::mutex> lk(g_mu);
          g_data[key] = val;
        }
        uint8_t ack = 0;
        if (!send_all(fd, &ack, 1)) goto done;
        break;
      }
      case OP_GET: {
        if (!recv_bytes(fd, &key)) goto done;
        std::string reply;
        {
          std::lock_guard<std::mutex> lk(g_mu);
          auto it = g_data.find(key);
          if (it == g_data.end()) {
            reply.push_back(0);
          } else {
            reply.push_back(1);
            append_bytes(&reply, it->second);
          }
        }
        if (!send_all(fd, reply.data(), reply.size())) goto done;
        break;
      }
      case OP_MSET: {
        uint32_t n;
        if (!recv_exact(fd, &n, 4) || n > kMaxBatch) goto done;
        std::vector<std::pair<std::string, std::string>> items(n);
        for (uint32_t i = 0; i < n; ++i) {
          if (!recv_bytes(fd, &items[i].first) ||
              !recv_bytes(fd, &items[i].second))
            goto done;
        }
        {
          std::lock_guard<std::mutex> lk(g_mu);
          for (auto& kv : items) g_data[std::move(kv.first)] = std::move(kv.second);
        }
        uint8_t ack = 0;
        if (!send_all(fd, &ack, 1)) goto done;
        break;
      }
      case OP_MGET: {
        uint32_t n;
        if (!recv_exact(fd, &n, 4) || n > kMaxBatch) goto done;
        std::vector<std::string> keys(n);
        for (uint32_t i = 0; i < n; ++i)
          if (!recv_bytes(fd, &keys[i])) goto done;
        std::string reply;
        append_u32(&reply, n);
        {
          std::lock_guard<std::mutex> lk(g_mu);
          for (const auto& k : keys) {
            auto it = g_data.find(k);
            if (it == g_data.end()) {
              reply.push_back(0);
            } else {
              reply.push_back(1);
              append_bytes(&reply, it->second);
            }
          }
        }
        if (!send_all(fd, reply.data(), reply.size())) goto done;
        break;
      }
      case OP_NUM_KEYS: {
        uint64_t n;
        {
          std::lock_guard<std::mutex> lk(g_mu);
          n = g_data.size();
        }
        if (!send_all(fd, &n, 8)) goto done;
        break;
      }
      case OP_CLEAR: {
        {
          std::lock_guard<std::mutex> lk(g_mu);
          g_data.clear();
        }
        uint8_t ack = 0;
        if (!send_all(fd, &ack, 1)) goto done;
        break;
      }
      case OP_PING: {
        uint8_t ack = 0;
        if (!send_all(fd, &ack, 1)) goto done;
        break;
      }
      case OP_SHUTDOWN: {
        uint8_t ack = 0;
        send_all(fd, &ack, 1);
        g_shutdown.store(true);
        ::shutdown(g_listen_fd, SHUT_RDWR);
        goto done;
      }
      default:
        goto done;  // unknown op: drop the connection
    }
  }
done:
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = argc > 1 ? argv[1] : "127.0.0.1";
  int port = argc > 2 ? std::atoi(argv[2]) : 0;

  g_listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (g_listen_fd < 0) return 1;
  int one = 1;
  ::setsockopt(g_listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return 1;
  if (::bind(g_listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
    return 1;
  if (::listen(g_listen_fd, 128) != 0) return 1;

  socklen_t len = sizeof(addr);
  ::getsockname(g_listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  std::vector<std::thread> threads;
  while (!g_shutdown.load()) {
    int fd = ::accept(g_listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (g_shutdown.load()) break;
      continue;
    }
    threads.emplace_back(handle_conn, fd);
  }
  ::close(g_listen_fd);
  for (auto& t : threads)
    if (t.joinable()) t.join();
  return 0;
}
