#!/usr/bin/env bash
# CI gate — the analog of the reference's .buildkite/pipeline.yml
# (pytest job + benchmark gates + lint workflows).  Runs entirely on the
# virtual CPU mesh unless RUN_TPU_BENCH=1.
#
# Usage:  bash scripts/ci.sh            # lint + compile + tests + goldens
#         RUN_TPU_BENCH=1 bash scripts/ci.sh   # + the TPU headline bench
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== lint (syntax + import graph) ==="
python -m compileall -q bagua_tpu tests examples bench.py __graft_entry__.py
python - <<'PY'
import pathlib, ast, sys
bad = []
for p in pathlib.Path("bagua_tpu").rglob("*.py"):
    tree = ast.parse(p.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "torch":
                bad.append(str(p))
        elif isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "torch" for a in node.names):
                bad.append(str(p))
if bad:
    sys.exit(f"torch imports in the TPU package: {bad}")
print("import graph clean")
PY

echo "=== unit + integration tests (8-device CPU mesh) ==="
python -m pytest tests/ -q

echo "=== multichip dryrun (virtual CPU mesh) ==="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -c "import jax; jax.config.update('jax_platforms','cpu'); \
import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

echo "=== deterministic loss goldens (CPU) ==="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -c "import jax; jax.config.update('jax_platforms','cpu'); \
import runpy, sys; sys.argv=['bench.py','--goldens']; \
runpy.run_path('bench.py', run_name='__main__')"

if [[ "${RUN_TPU_BENCH:-0}" == "1" ]]; then
  echo "=== TPU headline bench ==="
  python bench.py
fi

echo "CI green"
