#!/usr/bin/env bash
# CI gate — the analog of the reference's .buildkite/pipeline.yml
# (pytest job + benchmark gates + lint workflows).  Runs entirely on the
# virtual CPU mesh unless RUN_TPU_BENCH=1.
#
# Usage:  bash scripts/ci.sh            # lint + compile + tests + goldens
#         RUN_TPU_BENCH=1 bash scripts/ci.sh   # + the TPU headline bench
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== lint (syntax) ==="
python -m compileall -q bagua_tpu tests examples bench.py __graft_entry__.py

echo "=== bagua-lint (AST + jaxpr + concurrency + trace-coherence engines) ==="
# All four engines (--engine all is the default): AST hot-path rules, the
# jaxpr collective-consistency sweep, the host-concurrency race detector
# (lock-order inversions, unguarded shared writes, lock-held IO,
# signal-unsafe locking), and the step-cache-key coherence prover (every
# knob that shapes the traced step must ride _step_key; ISSUE 18).
# Fails on any unsuppressed finding not in the shrink-only baseline (stale
# baseline entries fail too — the baseline can only shrink), and proves
# overlap-vs-serialized collective-multiset equality for the algorithm
# families at accum_steps 1 and 4 — including the hierarchical two-level
# configs (family:hier on a 2-slice x 4-chip mesh: intra reduce-scatter,
# inter allreduce on the 1/intra shard, intra allgather; ISSUE 11) and
# the compressed-ring configs (bytegrad:hier-compressed + forced
# int8/fp8 DCN codecs: quantized ppermute payloads with their f32
# sidecars must emit identical multisets streamed vs serialized;
# ISSUE 15).  The historical torch-import gate is now the `torch-import`
# rule.  See docs/analysis.md, docs/hierarchical.md, docs/compression.md.
JAX_PLATFORMS=cpu \
python -m bagua_tpu.analysis bagua_tpu/ --baseline .bagua-lint-baseline.json

echo "=== generated docs in sync (API reference + env-var table) ==="
JAX_PLATFORMS=cpu python scripts/gen_api_docs.py --check
JAX_PLATFORMS=cpu python scripts/gen_env_docs.py --check

echo "=== obs smoke trace (flight recorder on one live drill) ==="
# One drill from the chaos matrix with the observability plane on: the
# drill itself asserts its flight-recorder dump exists, schema-validates,
# names the firing fault point, and surfaces its badput class in the
# goodput ledger (exit code carries the verdict).  The full-matrix
# CHAOS_DRILL.json is schema-gated in test_bench_sanity.py.
OBS_TMP="$(mktemp -d)"
BAGUA_OBS_EXPORT_DIR="$OBS_TMP/export" BAGUA_OBS_EXPORT_INTERVAL_S=1 \
python scripts/chaos_drill.py --only nan_grad_skip_loss_continuity \
  --dump-dir "$OBS_TMP/dumps"

echo "=== lockdep witness (chaos smoke under BAGUA_LOCKDEP=on) ==="
# The same drill re-run with the runtime lockdep shim recording every real
# lock acquisition order, then cross-checked against the static
# acquisition graph: zero runtime inversions (a live deadlock window the
# drill actually exercised) and every witnessed edge between known locks
# present in the static model (witness ⊆ static — the concurrency
# engine's 'no cycle' verdicts are only trustworthy if it saw every real
# ordering).  See docs/analysis.md, ISSUE 18.
BAGUA_LOCKDEP=on BAGUA_LOCKDEP_OUT="$OBS_TMP/lockdep_witness.json" \
BAGUA_OBS_EXPORT_DIR="$OBS_TMP/export2" BAGUA_OBS_EXPORT_INTERVAL_S=1 \
python scripts/chaos_drill.py --only nan_grad_skip_loss_continuity \
  --dump-dir "$OBS_TMP/dumps2"
JAX_PLATFORMS=cpu \
python -m bagua_tpu.analysis bagua_tpu/ --engine concurrency \
  --witness "$OBS_TMP/lockdep_witness.json" \
  --baseline .bagua-lint-baseline.json

echo "=== obs HTTP plane smoke (live /metrics + /fleet scrape) ==="
# The HTTP status plane scraped DURING a live cpu-sim training run: the
# /metrics scrape must parse as fully registered+typed Prometheus text
# and match the concurrent on-disk metrics.prom series-for-series, and
# /fleet must validate against the bagua-obs-fleet-v1 schema with the
# historian's trend augmentation aboard (ISSUE 14).
python scripts/obs_http_smoke.py --export-dir "$OBS_TMP/http_export"

echo "=== fleet timeline from the drill's flight dumps ==="
# The dumps the smoke trace just wrote must assemble into a schema-valid,
# clock-aligned Perfetto trace — the analysis layer's own end-to-end gate.
python -m bagua_tpu.obs.timeline "$OBS_TMP/dumps" \
  --out "$OBS_TMP/timeline.json" --check

echo "=== goodput ledger over the smoke trace's metrics export ==="
# The drill's exporter wrote metrics.jsonl with the ledger gauges aboard;
# the CLI renders the per-run report and gates conservation (every class
# second accounted, classes sum to wall within 1%).
python -m bagua_tpu.obs.ledger "$OBS_TMP/export" \
  --flight "$OBS_TMP/dumps" --check
rm -rf "$OBS_TMP"

echo "=== autopilot replay smoke (policy engine over a recorded fleet stream) ==="
# The coordinator-side policy matrix in observe mode over the committed
# fleet snapshot stream: the decided action plan (fence -> retune hint ->
# two SLO ladder rungs -> storage quarantine) must match the committed
# expectation exactly — a policy change that re-orders or drops an action
# fails here before it ships.  Full matrix actuation is chaos-drilled in
# CHAOS_DRILL.json (schema-gated in test_bench_sanity.py); operators can
# replay their own streams with `python -m bagua_tpu.autopilot --replay`.
python -m bagua_tpu.autopilot \
  --replay tests/data/autopilot_fleet_stream.jsonl \
  --expect tests/data/autopilot_expected_plan.json \
  --sustain 2 --cooldown-s 0 --budget 8 --slo-goodput 0.5 \
  --straggler-ratio 3.0 --ckpt-failures 3 --family async > /dev/null

echo "=== autopilot trend-rule replay (historian windows close the loop) ==="
# The historian-backed trend rules over the committed synthetic stream
# (ISSUE 14): the shrinking-HBM-headroom rank decides the pre-OOM
# resize, the DCN-dominant rank decides the compression-escalation
# hint, and the flat control rank decides NOTHING — and without
# --historian the same stream is provably inert (the rules fire only
# from historian trend windows, gated in tests/test_autopilot.py).
python -m bagua_tpu.autopilot \
  --replay tests/data/autopilot_trend_stream.jsonl \
  --expect tests/data/autopilot_trend_plan.json \
  --historian --trend-window-s 600 \
  --sustain 2 --cooldown-s 300 --budget 8 > /dev/null

echo "=== autotune v2 smoke (goodput-scored search round, cpu mesh) ==="
# One live v2 search round: a real trainer on the two-tier cpu-sim mesh
# checks in with windowed goodput observations, the sidecar builds the
# capability-gated knob space from the registration capabilities, and the
# scored window MUST be fleet-min-goodput-scored (not summed speed).  The
# committed convergence evidence (tuned >= default within the 24-window
# cap) is BENCH_AUTOTUNE.json, schema-gated in tests/test_bench_sanity.py;
# regenerate with `python benchmarks/autotune_bench.py`.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python benchmarks/autotune_smoke.py --ci > /dev/null

echo "=== serve smoke (continuous-batching engine, short synthetic trace) ==="
# The serving plane end-to-end on the 8-dev cpu-sim image: weights loaded
# through the integrity-verified serving loader, a short Poisson trace
# through the paged-KV continuous-batching engine, the continuous-vs-
# static A/B, and the schema validation serve_bench runs before writing
# (an invalid record exits non-zero).  The committed full-trace
# BENCH_SERVE.json is schema-gated in tests/test_bench_sanity.py.
SERVE_TMP="$(mktemp -d)"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
BAGUA_OBS_EXPORT_DIR="$SERVE_TMP/export" BAGUA_OBS_EXPORT_INTERVAL_S=1 \
python benchmarks/serve_bench.py --smoke --out "$SERVE_TMP/BENCH_SERVE.json"

echo "=== goodput ledger over the serve smoke's metrics export ==="
# Conservation must hold with the serving classes aboard (prefill/decode
# as serving goodput, batch_formation_idle/weight_load as named badput):
# every class second accounted, classes sum to wall within 1%.
python -m bagua_tpu.obs.ledger "$SERVE_TMP/export" --check
rm -rf "$SERVE_TMP"

echo "=== bench trend sentinel (advisory) ==="
# Quick probe re-measured with the committed artifact's own protocol,
# compared noise-bound-aware; refreshes BENCH_TREND.json (schema-gated in
# test_bench_sanity.py).  Advisory: regressions print and are recorded in
# the trend artifact, they do not fail CI — cpu-sim CI hosts are noisy and
# the probe runs fewer trials than the committed record.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m bagua_tpu.obs.regress --out BENCH_TREND.json \
  || echo "advisory: bench trend sentinel reported a problem (non-blocking)"

echo "=== scale smoke (4-process loopback pod drill) ==="
# The pod simulator end to end with REAL worker processes: cold-start
# rendezvous through the restart TCPStore, shaped hierarchical+compressed
# collectives over loopback rings, lease-expiry shrink, standby regrow,
# and an autopilot straggler fence — the full coordinator lifecycle at
# world 4 under a tight timeout.  The committed 32/64/128-rank sweep
# (BENCH_SCALE.json) is schema-gated in tests/test_bench_sanity.py;
# regenerate it with `python scripts/scale_drill.py`.
timeout -k 10 120 python scripts/scale_drill.py --smoke > /dev/null

echo "=== failover smoke (SIGKILL the live coordinator process) ==="
# Coordinator failover end to end with REAL processes: a replicated
# restart store (primary + follower servers, op-log replication,
# generation fence), a killable coordinator renewing the leadership
# lease, a standby watching it, and 4 workers mid-collective.  The drill
# SIGKILLs the primary and asserts the standby promotes within the
# member lease TTL, ZERO workers restart, and the autopilot/historian
# state RESUMES from the replicated store.  The committed 32-rank fault
# matrix (FAILOVER_DRILL.json) is schema-gated in
# tests/test_bench_sanity.py; regenerate with
# `python scripts/failover_drill.py`.
timeout -k 10 150 python scripts/failover_drill.py --smoke > /dev/null

echo "=== compressed-ring smoke (1-bit EF codec over the loopback pod) ==="
# The stateful ISSUE-17 wire format end to end over real sockets: the same
# 4-process drill with the DCN stage forced onto bit-packed sign payloads
# + mean-abs sidecars (the numpy mirror of the jax codec) — the workers'
# transport-integrity bounds must hold and the verdict records the codec.
# The jaxpr-exact >=12x DCN byte pins and the EF convergence separation
# live in BENCH_COMPRESS.json (schema-gated in tests/test_bench_sanity.py).
timeout -k 10 120 env BAGUA_SCALE_DCN_CODEC=onebit_ef \
  python scripts/scale_drill.py --smoke > /dev/null

echo "=== chaos fast subset (fault injection -> detection -> recovery) ==="
# The deterministic slice of scripts/chaos_drill.py: every injection point
# fires, every detector sees it, every recovery completes.  The committed
# CHAOS_DRILL.json full-matrix record is schema-gated in
# tests/test_bench_sanity.py; regenerate it with scripts/chaos_drill.py.
python -m pytest tests/test_faults.py -q

echo "=== unit + integration tests (8-device CPU mesh) ==="
# test_faults.py already ran as the named chaos gate above
python -m pytest tests/ -q --ignore=tests/test_faults.py

echo "=== multichip dryrun (virtual CPU mesh) ==="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -c "import jax; jax.config.update('jax_platforms','cpu'); \
import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

echo "=== deterministic loss goldens (CPU) ==="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -c "import jax; jax.config.update('jax_platforms','cpu'); \
import runpy, sys; sys.argv=['bench.py','--goldens']; \
runpy.run_path('bench.py', run_name='__main__')"

if [[ "${RUN_TPU_BENCH:-0}" == "1" ]]; then
  echo "=== TPU headline bench ==="
  python bench.py
fi

echo "CI green"
