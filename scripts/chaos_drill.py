#!/usr/bin/env python
"""Chaos drill: the full fault matrix, in-process, on the cpu-sim mesh.

Unlike ``scripts/elastic_drill.py`` (which SIGKILLs real launcher process
groups — high fidelity, slow, non-repeatable), this drill arms the seeded
injection registry (:mod:`bagua_tpu.faults.inject`) inside ONE process on
the 8-device virtual CPU mesh and proves every defense end-to-end,
deterministically:

1. **store flake → retry**: an injected ``store.op`` failure on a live
   TCPStore connection recovers through ``_RestartStore``'s
   reconnect-and-retry.
2. **heartbeat loss → lease expiry (shrink signal)**: dropped beats starve
   the lease; the coordinator-side tracker expires it — the event that
   shrinks an elastic world.
3. **checkpoint corruption → fallback restore**: the newest checkpoint's
   data file is corrupted post-publish; restore degrades to the previous
   step and the content checksum verifies it.
4. **NaN gradient → skip-and-continue**: ``grad.poison`` fires inside the
   compiled train step; ``BAGUA_GRAD_GUARD=skip`` rewinds the step and the
   final loss is BIT-IDENTICAL to a clean run of one fewer step on
   ``bench.golden_task()`` (loss continuity).
5. **collective hang → watchdog abort + reset recovery**: the waiter's
   readback wedges; the monitor fires, raises the abort flag, and after
   ``reset_abort`` training resumes — twice, proving re-arming.
6. **10× straggler → degraded but alive**: a ``step.straggle`` peer dilates
   every synchronous step; throughput degrades by roughly the dilation
   factor yet every step completes with a finite loss — and the async
   family under the SAME fault retains most of its throughput (it gates on
   the straggler only at negotiated boundaries).
7. **async partition → bounded-staleness catch-up**: ``async.partition``
   drops every negotiation round; the applied-round counter stalls, the
   staleness tracker catches it at the cap, and the forced synchronous
   catch-up re-syncs the replicas bit-identically while training continues.
8. **chronic bad health → coordinator fence**: unhealthy worker beacons
   ride the lease heartbeat; the tracker names the node, the production
   fence path (``distributed.run.publish_health_fence``) publishes the
   ``health_fenced`` stop — and the coordinator-side fleet snapshot
   records every rank's obs summary.

Every fault-driven failure mode must also leave a **schema-valid
flight-recorder dump** (``bagua_tpu.obs.recorder``) naming the firing
fault point — asserted per drill and recorded in the matrix.

Writes ``CHAOS_DRILL.json`` (schema-gated in ``tests/test_bench_sanity.py``);
exit code 0 iff every fault was detected AND recovered.

Usage: python scripts/chaos_drill.py [--only DRILL ...]
       (--only runs a subset — the CI smoke trace — and does NOT rewrite
       CHAOS_DRILL.json unless --out is given)
"""

import argparse
import glob
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
# the hang drill uses its OWN HangWatchdog instance; the process-global
# watchdog's waiter runs the same collective.hang hook, and its readbacks
# of earlier drills' step losses would race the drill for the single
# armed fire — keep it out of the picture
os.environ["BAGUA_COMM_TIMEOUT_S"] = "off"
# flight-recorder dumps land here; every drill asserts its failure mode
# left a schema-valid artifact naming the firing fault point.  Always a
# FRESH directory — an inherited BAGUA_OBS_DUMP_DIR could hold stale
# flight_*.json from a previous run, and a stale artifact satisfying a
# drill's expectation would mask a broken recorder (the exact regression
# this gate exists to catch).  --dump-dir NAMES the fresh directory (the
# CI timeline stage assembles a fleet trace from these dumps afterwards)
# but must still be empty — it is parsed here, before jax imports, because
# the env var must be set before any bagua module reads it.
def _early_dump_dir():
    d = None
    for i, arg in enumerate(sys.argv):
        if arg == "--dump-dir" and i + 1 < len(sys.argv):
            d = sys.argv[i + 1]
        elif arg.startswith("--dump-dir="):  # argparse's = form too
            d = arg.split("=", 1)[1]
    if d:
        os.makedirs(d, exist_ok=True)
        if os.listdir(d):
            sys.exit(f"--dump-dir {d} is not empty — flight "
                     "expectations need a fresh directory")
        return d
    return tempfile.mkdtemp(prefix="chaos_obs_")


DUMP_DIR = os.environ["BAGUA_OBS_DUMP_DIR"] = _early_dump_dir()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import bagua_tpu  # noqa: E402
from bagua_tpu import telemetry  # noqa: E402
from bagua_tpu.faults import inject  # noqa: E402
from bagua_tpu.faults.inject import FaultSpec, fault_scope  # noqa: E402

OUT = os.path.join(REPO, "CHAOS_DRILL.json")


def _counter_deltas(before):
    after = telemetry.counters.snapshot()
    keys = set(before) | set(after)
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in sorted(keys)
            if after.get(k, 0) != before.get(k, 0)}


#: drill name -> the goodput-ledger badput class its defense path must
#: FEED (ISSUE 10): a rewound step's wall lands in `rewind`, a forced
#: catch-up in `catchup_sync`, the fallback-restore walk in `checkpoint` —
#: asserted as a class-delta across the drill, so an efficiency regression
#: in a recovery path can't hide behind a passing recovery verdict.  One
#: mapping, shared with the test_bench_sanity artifact gate.
from bagua_tpu.obs.ledger import (  # noqa: E402
    DRILL_BADPUT_EXPECTATIONS as LEDGER_EXPECTATIONS,
)

#: drill name -> the fault point (or non-fault trigger) whose
#: flight-recorder dump the drill must leave behind
FLIGHT_EXPECTATIONS = {
    "store_flake_retry": {"fault_point": "store.op"},
    "heartbeat_loss_lease_expiry": {"fault_point": "elastic.heartbeat"},
    "checkpoint_corruption_fallback_restore": {"fault_point": "ckpt.write"},
    "nan_grad_skip_loss_continuity": {"fault_point": "grad.poison"},
    "collective_hang_watchdog_recovery": {"fault_point": "collective.hang",
                                          "trigger": "watchdog_abort"},
    "straggler_throughput_degrades": {"fault_point": "step.straggle",
                                      "trigger": "step_anomaly"},
    "async_partition_staleness_catchup": {"fault_point": "async.partition"},
    "health_fence_flight_record": {"trigger": "health_fence"},
    # fleet autopilot (docs/autopilot.md): every decided action leaves an
    # `autopilot_action` flight dump with its triggering evidence
    "autopilot_straggler_fence_resize": {"fault_point": "step.straggle",
                                         "trigger": "autopilot_action"},
    "autopilot_victim_retune_hint": {"fault_point": "step.straggle",
                                     "trigger": "autopilot_action"},
    "autopilot_slo_escalation_ladder": {"trigger": "autopilot_action"},
    "autopilot_ckpt_quarantine": {"fault_point": "ckpt.write",
                                  "trigger": "autopilot_action"},
    "autopilot_trend_rules": {"trigger": "autopilot_action"},
}


def _ledger_class_check(cls, before, after):
    """The class-delta verdict the drill matrix records: the drill's
    defense path must have added wall seconds to its badput class (and,
    for rewind, one reclassified window per grad-guard skip)."""
    before_classes = (before or {}).get("classes") or {}
    after_classes = (after or {}).get("classes") or {}
    delta = round(after_classes.get(cls, 0.0)
                  - before_classes.get(cls, 0.0), 6)
    verdict = {"badput_class": cls, "delta_s": delta,
               "surfaced": delta > 0}
    if cls == "rewind":
        verdict["rewind_windows_delta"] = (
            (after or {}).get("rewind_windows", 0)
            - (before or {}).get("rewind_windows", 0)
        )
    return verdict


def _flight_record_check(expect):
    """Scan the dump dir for a schema-valid flight record matching the
    expectation (fault point and/or trigger); returns the verdict dict the
    drill matrix records."""
    from bagua_tpu.obs import recorder as obs_recorder

    point = expect.get("fault_point")
    trigger = expect.get("trigger")
    found_point = found_trigger = False
    problems = []
    for path in sorted(glob.glob(os.path.join(DUMP_DIR, "flight_*.json"))):
        try:
            rec = json.load(open(path))
        except (OSError, ValueError) as e:
            problems.append(f"{os.path.basename(path)}: unreadable ({e})")
            continue
        bad = obs_recorder.validate_flight_record(rec)
        if bad:
            problems.append(f"{os.path.basename(path)}: {bad}")
            continue
        if point and (rec.get("fault_point") == point
                      or point in rec.get("fired_faults", {})):
            found_point = True
        if trigger and rec.get("trigger") == trigger:
            found_trigger = True
    # a match only counts when its containing dump schema-validated (the
    # loop skips invalid dumps before matching), so found == schema-valid
    ok = (found_point or not point) and (found_trigger or not trigger)
    verdict = {"schema_valid": ok, "found": ok}
    if point:
        verdict["fault_point"] = point
    if trigger:
        verdict["trigger"] = trigger
    if problems:
        verdict["problems"] = problems[:5]
    return verdict


def drill_store_flake():
    """store.op flake on a real TCPStore connection → retry recovers."""
    from bagua_tpu.contrib.utils.tcp_store import TCPStore, start_tcp_store
    from bagua_tpu.distributed import run as run_mod

    server = start_tcp_store("127.0.0.1", 0)
    try:
        host, port = server.address

        class _Args:
            master_addr = host
            restart_coordinator_port = port

        orig = run_mod._connect_restart_store
        run_mod._connect_restart_store = (
            lambda args, timeout_s=60.0: TCPStore(host, port,
                                                  timeout_s=timeout_s)
        )
        try:
            store = run_mod._RestartStore(args=_Args())
            store.set("drill/k", "v1")
            with fault_scope(FaultSpec("store.op")):
                got = store.get("drill/k")
                recovered = got == b"v1"
                fired = inject.get_plan().fired("store.op")
        finally:
            run_mod._connect_restart_store = orig
        return {"injected": True, "detected": fired, "recovered": recovered,
                "details": f"get returned {got!r} after injected flake + "
                           "reconnect-and-retry"}
    finally:
        server.stop()


def drill_heartbeat_loss():
    """Dropped heartbeats starve the lease → tracker expiry (the elastic
    shrink trigger), then beats resume and the next epoch re-admits."""
    from bagua_tpu.contrib.utils.store import InMemoryStore
    from bagua_tpu.elastic.membership import (
        LeaseHeartbeat,
        LeaseTracker,
        MembershipClient,
    )

    store = InMemoryStore()
    client = MembershipClient(store, node_id=0, max_nnodes=1)
    hb = LeaseHeartbeat(lambda: store, node_id=0, epoch=0,
                        interval_s=0.05).start()
    try:
        deadline = time.time() + 10
        while client.read_beats(0, [0])[0] is None and time.time() < deadline:
            time.sleep(0.05)
        tracker = LeaseTracker(client, epoch=0, member_ids=[0], ttl_s=0.4)
        healthy_before = tracker.poll() == []
        with fault_scope(FaultSpec("elastic.heartbeat", count=-1)):
            expired = []
            deadline = time.time() + 10
            while not expired and time.time() < deadline:
                time.sleep(0.1)
                expired = tracker.poll()
            detected = expired == [0]
            inject.record_recovery("elastic.heartbeat")
        # beats resume once the fault disarms: a fresh epoch's tracker sees
        # the node alive again (the rejoin half of shrink→regrow)
        seq0 = client.read_beats(0, [0])[0]
        deadline = time.time() + 10
        recovered = False
        while time.time() < deadline:
            time.sleep(0.1)
            seq = client.read_beats(0, [0])[0]
            if seq is not None and seq0 is not None and seq > seq0:
                recovered = True
                break
        return {"injected": True, "detected": detected,
                "recovered": bool(healthy_before and recovered),
                "details": "lease expired under beat starvation; beats "
                           "resumed after disarm"}
    finally:
        hb.stop()


def drill_checkpoint_corruption(tmp):
    """Corrupt the newest checkpoint post-publish → restore falls back to
    the previous step and the content digest verifies it."""
    import jax.numpy as jnp

    from bagua_tpu.checkpoint import BaguaCheckpointManager

    def state(v):
        return {"w": jnp.arange(4096, dtype=jnp.float32) * v,
                "step": jnp.int32(0)}

    mgr = BaguaCheckpointManager(os.path.join(tmp, "ckpt"),
                                 async_save=False, max_to_keep=5)
    mgr.save(1, state(1.0))
    mgr.save(2, state(2.0))
    with fault_scope(FaultSpec("ckpt.write", step=3)):
        mgr.save(3, state(3.0))
        before = telemetry.counters.snapshot()
        step, restored = mgr.try_restore(state(0.0))
        deltas = _counter_deltas(before)
    mgr.close()
    ok = (
        step == 2
        and np.array_equal(np.asarray(restored["w"]),
                           np.asarray(state(2.0)["w"]))
        and deltas.get("ckpt/verified_restores", 0) >= 1
    )
    return {"injected": True,
            "detected": deltas.get("ckpt/integrity_failures", 0) >= 1,
            "recovered": bool(ok),
            "details": f"latest (3) corrupted; restore landed on step "
                       f"{step} with verified checksum"}


def drill_nan_grad_skip():
    """grad.poison at step 3 under BAGUA_GRAD_GUARD=skip: the rewound run
    of n steps must be bit-identical to a clean run of n-1 steps on the
    golden task (same batch every step ⇒ skipping one update IS running
    one fewer), proving exact loss continuity."""
    import bench
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.parallel.mesh import build_mesh

    loss_fn, params, batch = bench.golden_task()
    mesh = build_mesh({"dp": 8})

    def run(n, guard="off", poison=None):
        import contextlib

        cm = (fault_scope(FaultSpec("grad.poison", step=poison))
              if poison is not None else contextlib.nullcontext())
        with cm:
            t = BaguaTrainer(loss_fn, optax.sgd(0.1),
                             GradientAllReduceAlgorithm(), mesh=mesh,
                             autotune=False, grad_guard=guard)
            s = t.init(params)
            b = t.shard_batch(batch)
            loss = None
            for _ in range(n):
                s, loss = t.train_step(s, b)
            if guard != "off":
                t.flush_grad_health()
            fired = (inject.get_plan().fired("grad.poison")
                     if poison is not None else False)
        return float(loss), jax.tree.leaves(t.unstack_params(s)), fired

    before = telemetry.counters.snapshot()
    l_clean, p_clean, _ = run(9)
    l_skip, p_skip, fired = run(10, guard="skip", poison=5)
    deltas = _counter_deltas(before)
    exact = all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(p_clean, p_skip))
    return {"injected": True,
            "detected": bool(fired
                             and deltas.get("grad_guard/skipped_steps",
                                            0) == 1),
            "recovered": bool(exact and np.isfinite(l_skip)),
            "details": f"poisoned 10-step run final loss {l_skip:.6f} == "
                       f"clean 9-step run {l_clean:.6f}; params "
                       f"bit-identical: {exact}"}


def drill_guard_on_goldens():
    """No faults + BAGUA_GRAD_GUARD=skip must reproduce the exact loss
    goldens for every deterministic family (flat and leaf layouts ride the
    same ``loss_goldens`` sweep) — the guard's selects pass healthy state
    through bitwise.  ``async`` is excluded: its final loss is
    host-timing-dependent even without the guard (see test_loss_goldens)."""
    import bench

    def goldens(guard):
        os.environ["BAGUA_GRAD_GUARD"] = guard
        try:
            return bench.loss_goldens()
        finally:
            os.environ.pop("BAGUA_GRAD_GUARD", None)

    off, on = goldens("off"), goldens("skip")
    families = sorted(k for k in off if k != "async")
    diffs = {k: (off[k], on[k]) for k in families if off[k] != on[k]}
    return {"injected": True,  # the guard itself is the intervention
            "detected": True,
            "recovered": not diffs,
            "details": (f"guard-on goldens equal for {len(families)} "
                        f"deterministic families: {families}" if not diffs
                        else f"goldens diverged under guard: {diffs}")}


def drill_collective_hang():
    """Wedged readback → watchdog fires + aborts → reset_abort resumes a
    live overlap+flat trainer; a second episode proves re-arming."""
    import jax.numpy as jnp

    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.models.mlp import MLP
    from bagua_tpu.parallel.mesh import build_mesh
    from bagua_tpu.watchdog import HangWatchdog

    mesh = build_mesh({"dp": 8})
    model = MLP(features=(16, 8))
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    y = jnp.zeros((16,), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x[:2])["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]).mean()

    t = BaguaTrainer(loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
                     mesh=mesh, autotune=False, accum_steps=2,
                     overlap="on", flat_resident="on")
    s = t.init(params)
    b = t.shard_batch({"x": x, "y": y})
    s, _ = t.train_step(s, b)

    wd = HangWatchdog(timeout_s=0.3, action="abort")
    episodes = []
    try:
        for episode in range(2):
            deadline = time.time() + 10
            while not wd._armed and time.time() < deadline:
                time.sleep(0.05)
            with fault_scope(FaultSpec("collective.hang", duration_s=1.5)):
                wd.fired.clear()
                wd.watch_result(np.zeros(()), f"wedged-step-{episode}")
                deadline = time.time() + 15
                while not bagua_tpu.is_aborted() and time.time() < deadline:
                    time.sleep(0.05)
                fired = wd.fired.is_set() and bagua_tpu.is_aborted()
                failed_fast = False
                try:
                    # rebind: if the abort flag was NOT up (drill failure),
                    # this dispatch consumes (donates) s and the verdict
                    # below must keep using the returned state
                    s, _ = t.train_step(s, b)
                except bagua_tpu.BaguaAborted:
                    failed_fast = True
                deadline = time.time() + 15
                while wd._active and time.time() < deadline:
                    time.sleep(0.05)
                # reset INSIDE the armed scope so the recovery is
                # attributed to the injected hang in the counters
                bagua_tpu.reset_abort()
            s, loss = t.train_step(s, b)
            episodes.append(fired and failed_fast
                            and bool(np.isfinite(float(loss))))
    finally:
        wd.stop()
        bagua_tpu.reset_abort()
    plan_fired = telemetry.counters.get("faults/collective.hang/fired") >= 2
    return {"injected": True, "detected": bool(all(episodes) and plan_fired),
            "recovered": bool(all(episodes) and len(episodes) == 2),
            "details": f"2 hang episodes: abort+fail-fast+resume each time "
                       f"({episodes})"}


def _golden_trainer(algo, **kw):
    import bench
    import optax
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.parallel.mesh import build_mesh

    loss_fn, params, batch = bench.golden_task()
    t = BaguaTrainer(loss_fn, optax.sgd(0.1), algo,
                     mesh=build_mesh({"dp": 8}), autotune=False, **kw)
    s = t.init(params)
    return t, s, t.shard_batch(batch)


def _anomaly_leg(straggle_rank, sim_rank, base_ms, factor, tmp):
    """One real trainer run for the straggler anomaly detector: clean
    baseline steps, then an armed ``step.straggle`` window, on the async
    family — its ``async/negotiate`` boundaries are both where a slow
    peer gates this rank AND the anchor spans the fleet timeline aligns
    on.  Returns the suspects flagged DURING the straggle window, the
    health-beacon path (the worker half of the fleet view), and writes
    this leg's span-ring slice to the dump dir as simulated rank
    ``sim_rank``'s ring dump (``spans_rank<r>.json``) for the timeline
    assembly."""
    from bagua_tpu.algorithms import AsyncModelAverageAlgorithm
    from bagua_tpu.elastic.membership import write_health_beacon
    from bagua_tpu.obs import export as obs_export
    from bagua_tpu.obs import spans as obs_spans

    obs_export.reset_local_summary()

    def _key(sp):
        return (sp.get("name"), sp.get("t0"), sp.get("t1"),
                sp.get("thread"))

    ring_before = {_key(sp) for sp in obs_spans.recorder.snapshot()}
    dropped_before = obs_spans.recorder.dropped
    algo = AsyncModelAverageAlgorithm(warmup_steps=0, period_steps=4)
    t, s, b = _golden_trainer(algo)
    for _ in range(10):
        s, _ = t.train_step(s, b)
    straggle_start = t._step_counter
    with fault_scope(FaultSpec("step.straggle", rank=straggle_rank,
                               count=-1, base_ms=base_ms, factor=factor)):
        for _ in range(6):
            s, _ = t.train_step(s, b)
    # one clean step so the LAST straggled window is observed too (the
    # detector inspects each window when the next step opens)
    s, _ = t.train_step(s, b)
    s = algo.barrier(t, s)
    suspects = [sp for sp in (t.anomaly_detector.suspects
                              if t.anomaly_detector else [])
                if sp["step"] >= straggle_start]
    beacon = os.path.join(tmp, f"straggler_beacon.r{sim_rank}")
    write_health_beacon(beacon)
    # this leg's ring slice, relabeled as the simulated rank: both legs
    # count steps from 1, so their async/negotiate boundary spans share
    # (name, step) anchor keys — the timeline aligns leg B's clock window
    # onto leg A's exactly the way a real fleet's blocking gather would
    leg_spans = [dict(sp, rank=sim_rank)
                 for sp in obs_spans.recorder.snapshot()
                 if _key(sp) not in ring_before]
    ring_dump = os.path.join(DUMP_DIR, f"spans_rank{sim_rank}.json")
    with open(ring_dump, "w") as f:
        json.dump({"rank": sim_rank, "spans": leg_spans,
                   # the leg's REAL drop delta: a rotated ring means
                   # leg_spans is a tail, and the timeline must say so
                   "spans_dropped":
                       obs_spans.recorder.dropped - dropped_before,
                   "simulated": True}, f, indent=1)
    return suspects, beacon


def drill_straggler_throughput(tmp):
    """A 10× peer straggler gates every synchronous step: throughput
    degrades by roughly the dilation yet every step completes — while the
    async family under the SAME armed fault keeps its steps ungated and
    pays only at negotiated boundaries (the BENCH_STRAGGLER measurement
    in miniature).  The anomaly detector must additionally flag the slow
    window on BOTH sides of the fault — collective-dominant on the gated
    peer, dispatch-dominant on the straggler itself — and the
    coordinator-side fleet snapshot must name the straggling rank from
    the ``straggler_suspect`` phase breakdowns riding the beacons."""
    from bagua_tpu.algorithms import (
        AsyncModelAverageAlgorithm,
        GradientAllReduceAlgorithm,
    )

    base_ms, factor, steps = 10.0, 10.0, 12

    def timed_run(algo):
        t, s, b = _golden_trainer(algo)
        s, loss = t.train_step(s, b)  # compile outside the timer
        float(loss)
        t0 = time.time()
        n_finite = 0
        for _ in range(steps):
            s, loss = t.train_step(s, b)
            n_finite += bool(np.isfinite(float(loss)))
        dt = time.time() - t0
        if hasattr(algo, "barrier"):
            s = algo.barrier(t, s)
        return dt, n_finite

    before = telemetry.counters.snapshot()
    clean_dt, _ = timed_run(GradientAllReduceAlgorithm())
    with fault_scope(FaultSpec("step.straggle", rank=1, count=-1,
                               base_ms=base_ms, factor=factor)):
        sync_dt, sync_ok = timed_run(GradientAllReduceAlgorithm())
        async_dt, async_ok = timed_run(
            AsyncModelAverageAlgorithm(warmup_steps=0, period_steps=4)
        )
        deltas = _counter_deltas(before)
        stall = (factor - 1.0) * base_ms / 1000.0
        detected = (
            deltas.get("faults/step.straggle/fired", 0) >= steps
            and sync_dt >= clean_dt + steps * stall * 0.9  # dilation landed
        )
        # alive-under-degradation IS the recovery: every step completed
        # with a finite loss, and the async family dodged the per-step
        # gating.  Recorded INSIDE the scope — record_recovery is a no-op
        # once the plan is disarmed.
        recovered = (
            sync_ok == steps and async_ok == steps and async_dt < sync_dt
        )
        if detected and recovered:
            inject.record_recovery("step.straggle")

    # --- anomaly extension: the detector must flag the slow window on
    # both sides of the fault and the fleet snapshot must NAME the
    # straggling rank from the phase breakdowns ---
    anomaly_env = {"BAGUA_OBS_ANOMALY_WARMUP": "4",
                   "BAGUA_OBS_ANOMALY_WINDOW": "24"}
    saved = {k: os.environ.get(k) for k in anomaly_env}
    os.environ.update(anomaly_env)
    try:
        # this process as the gated PEER of straggling rank 1: the wait
        # files under `collective`
        victim_suspects, victim_beacon = _anomaly_leg(
            1, 0, base_ms, factor, tmp)
        # this process as the straggler ITSELF (spec names our rank): the
        # local slowness files under `dispatch`
        self_suspects, straggler_beacon = _anomaly_leg(
            0, 1, base_ms, factor, tmp)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    victim_ok = bool(victim_suspects) and \
        victim_suspects[-1]["dominant_phase"] == "collective"
    self_ok = bool(self_suspects) and \
        self_suspects[-1]["dominant_phase"] == "dispatch"

    # both legs ran in THIS process (env rank 0); relabel the second
    # beacon as simulated rank 1 — the identity is the only hand-made part
    # of the fleet path below (beacons -> merged heartbeat payload ->
    # tracker -> fleet snapshot -> straggler naming are all production)
    fleet_ok = False
    fleet_suspects = {}
    if victim_ok and self_ok:
        from bagua_tpu.elastic import membership as mb
        from bagua_tpu.obs import export as obs_export
        from bagua_tpu.obs.anomaly import fleet_straggler_suspects

        rec = json.load(open(straggler_beacon))
        rec["obs"]["rank"] = 1
        rec["obs"]["straggler_suspect"]["rank"] = 1
        with open(straggler_beacon, "w") as f:
            json.dump(rec, f)
        payload = mb.merged_health_source(
            [victim_beacon, straggler_beacon])()
        fleet_path = os.path.join(tmp, "straggler_fleet.json")
        obs_export.write_fleet_snapshot(fleet_path, 0, {0: payload})
        fleet = json.load(open(fleet_path))
        fleet_suspects = fleet_straggler_suspects(fleet)
        fleet_ok = (
            not obs_export.validate_fleet_snapshot(fleet)
            and [s["rank"] for s in fleet_suspects["stragglers"]] == [1]
            and 0 in [s["rank"] for s in fleet_suspects["victims"]]
        )

    # the fleet timeline over the two legs' ring dumps: a schema-valid,
    # CLOCK-ALIGNED multi-rank Perfetto trace whose anchors are the legs'
    # shared async/negotiate boundary steps
    timeline_verdict = {"assembled": False}
    try:
        from bagua_tpu.obs import timeline as obs_timeline

        recs = obs_timeline.load_rank_records(
            [os.path.join(DUMP_DIR, "spans_rank0.json"),
             os.path.join(DUMP_DIR, "spans_rank1.json")])
        trace = obs_timeline.assemble_timeline(recs)
        problems = obs_timeline.validate_timeline(trace)
        trace_path = os.path.join(tmp, "straggler_timeline.json")
        with open(trace_path, "w") as f:
            json.dump(trace, f)
        meta = trace["metadata"]
        timeline_verdict = {
            "assembled": True,
            "schema_valid": not problems,
            "problems": problems[:5],
            "ranks": sorted(meta["ranks"]),
            "aligned": meta["aligned"],
            "anchor_spans_rank1": meta["ranks"].get("1", {}).get(
                "anchor_spans", 0),
            "events": len(trace["traceEvents"]),
        }
    except Exception as e:  # noqa: BLE001 - verdict, not crash
        timeline_verdict["error"] = f"{type(e).__name__}: {e}"
    timeline_ok = (
        timeline_verdict.get("schema_valid") is True
        and timeline_verdict.get("aligned") is True
        and timeline_verdict.get("ranks") == ["0", "1"]
        and timeline_verdict.get("anchor_spans_rank1", 0) >= 2
    )

    return {"injected": True,
            "detected": bool(detected and victim_ok and self_ok),
            "recovered": bool(recovered and fleet_ok and timeline_ok),
            "timeline": timeline_verdict,
            "anomaly": {
                "victim_flagged": victim_ok,
                "victim_dominant_phase": (victim_suspects[-1]
                                          ["dominant_phase"]
                                          if victim_suspects else None),
                "straggler_flagged": self_ok,
                "straggler_dominant_phase": (self_suspects[-1]
                                             ["dominant_phase"]
                                             if self_suspects else None),
                "fleet_names_straggler_rank": ([s["rank"] for s in
                                                fleet_suspects.get(
                                                    "stragglers", [])]
                                               if fleet_suspects else []),
                "fleet_ok": fleet_ok,
            },
            "details": f"{steps} steps: clean {clean_dt:.2f}s, sync+straggle "
                       f"{sync_dt:.2f}s (all finite: {sync_ok == steps}), "
                       f"async+straggle {async_dt:.2f}s — async retained "
                       f"{sync_dt / async_dt:.1f}x sync throughput; anomaly "
                       f"detector flagged peer(collective)="
                       f"{victim_ok} self(dispatch)={self_ok}, fleet named "
                       f"rank 1: {fleet_ok}"}


def drill_async_partition_catchup():
    """Persistent ``async.partition`` drops: the applied-round counter
    stalls, the negotiated gather sees the lag hit ``max_staleness_rounds``
    and forces a synchronous catch-up average — replicas bit-identical at
    the sync point, training continues, telemetry records the round trip."""
    import jax

    from bagua_tpu.algorithms import AsyncModelAverageAlgorithm

    cap = 2
    algo = AsyncModelAverageAlgorithm(warmup_steps=2, period_steps=2,
                                      max_staleness_rounds=cap)
    t, s, b = _golden_trainer(algo)

    synced_rows_ok = []
    orig = algo._catchup_sync

    def spy(tr, state, watchdog, step, reason):
        out = orig(tr, state, watchdog, step, reason)
        rows = [np.asarray(x) for x in jax.tree.leaves(out.params)]
        synced_rows_ok.append(all(
            np.array_equal(a[0], a[r])
            for a in rows for r in range(1, a.shape[0])
        ))
        return out

    algo._catchup_sync = spy
    before = telemetry.counters.snapshot()
    lags = []
    with fault_scope(FaultSpec("async.partition", count=-1)):
        loss = None
        for _ in range(20):
            s, loss = t.train_step(s, b)
            lags.append(algo._rounds_launched - algo._rounds_applied)
    s = algo.barrier(t, s)
    deltas = _counter_deltas(before)
    detected = (
        deltas.get("faults/async.partition/fired", 0) >= 1
        and deltas.get("async/missed_boundaries", 0) >= 1
        and deltas.get("async/catchup_syncs", 0) >= 1
    )
    recovered = (
        deltas.get("faults/async.partition/recovered", 0) >= 1
        and max(lags) <= cap                 # the bounded-staleness invariant
        and bool(synced_rows_ok) and all(synced_rows_ok)
        and np.isfinite(float(loss))
        and deltas.get("async/rounds_launched", 0)
        >= deltas.get("async/catchup_syncs", 0)
    )
    return {"injected": True, "detected": bool(detected),
            "recovered": bool(recovered),
            "details": f"{deltas.get('async/rounds_dropped', 0)} rounds "
                       f"dropped, {deltas.get('async/catchup_syncs', 0)} "
                       f"catch-up sync(s), max lag {max(lags)} <= cap {cap}, "
                       f"replicas bit-identical at every sync point: "
                       f"{all(synced_rows_ok)}"}


def drill_health_fence(tmp):
    """Chronic bad worker health → the coordinator's fence, end-to-end
    through the PRODUCTION pieces: per-rank beacon files → the launcher's
    merged heartbeat payload → LeaseTracker harvesting →
    ``publish_health_fence`` (the exact function monitor_elastic calls),
    which publishes the ``health_fenced`` stop AND dumps the flight
    record; the coordinator-side fleet snapshot is written and
    schema-validated alongside."""
    from bagua_tpu.contrib.utils.store import InMemoryStore
    from bagua_tpu.distributed.run import publish_health_fence
    from bagua_tpu.elastic import membership as mb
    from bagua_tpu.obs import export as obs_export

    store = InMemoryStore()
    client = mb.MembershipClient(store, node_id=0, max_nnodes=2)
    # node 1's workers report non-finite-gradient steps via their beacons
    beacons = [os.path.join(tmp, f"fence_beacon.r{i}") for i in range(2)]
    with open(beacons[0], "w") as f:
        json.dump({"grad_unhealthy": 2,
                   "obs": {"rank": 2, "step": 41, "step_dt_p50": 0.01,
                           "step_dt_p90": 0.02}}, f)
    with open(beacons[1], "w") as f:
        json.dump({"async_missed": 1,
                   "obs": {"rank": 3, "step": 40, "step_dt_p50": 0.01,
                           "step_dt_p90": 0.03}}, f)
    hb = mb.LeaseHeartbeat(
        lambda: store, node_id=1, epoch=0, interval_s=0.05, max_nnodes=2,
        health_source=mb.merged_health_source(beacons),
    ).start()
    try:
        client.beat(0, 1)  # the coordinator's own (healthy) heartbeat
        tracker = mb.LeaseTracker(client, epoch=0, member_ids=[1],
                                  ttl_s=30.0, fence_unhealthy_after=3,
                                  observe_only_ids=[0])
        unhealthy = []
        deadline = time.time() + 10
        while not unhealthy and time.time() < deadline:
            time.sleep(0.1)
            tracker.poll()
            unhealthy = tracker.unhealthy_members()
        detected = unhealthy == [1]
        if detected:
            publish_health_fence(client, 0, tracker, unhealthy)
        stop = client.read_stop(0)
        fenced = bool(stop and stop["kind"] == mb.STOP_HEALTH
                      and stop["nodes"] == [1])
        fleet_path = os.path.join(tmp, "fleet_snapshot.json")
        obs_export.write_fleet_snapshot(
            fleet_path, 0, {nid: tracker.health_of(nid) for nid in (0, 1)})
        fleet = json.load(open(fleet_path))
        fleet_ok = (
            not obs_export.validate_fleet_snapshot(fleet)
            and fleet["ranks"]["1"]["obs"].get("2", {}).get("step") == 41
            and fleet["ranks"]["1"]["health"].get("grad_unhealthy") == 2
        )
    finally:
        hb.stop()
    return {"injected": True, "detected": bool(detected),
            "recovered": bool(fenced and fleet_ok),
            "fleet_snapshot_valid": bool(fleet_ok),
            "details": f"tracker named node(s) {unhealthy}; stop event "
                       f"{stop and stop['kind']}; fleet snapshot carries "
                       f"per-rank obs summaries (valid: {fleet_ok})"}


# ---- fleet autopilot drills (docs/autopilot.md) ---------------------------
#
# The policy matrix end-to-end, each rule injected -> detected -> DECIDED ->
# ACTUATED -> recovered: the autopilot consumes fleet snapshots built by the
# production merge (beacons -> merged_health_source -> build_fleet_record),
# decides through the pure core, and actuates through the pre-existing
# machinery only — the health-fence stop event, AutotuneClient perf hints
# with service-side consumption, the autotune recommendation path for the
# family switch (the trainer's switch is a re-jit + a queued state
# migration), and the checkpoint storage-quarantine registry.


def _autopilot_engine(mode="act", actuators=None, **cfg):
    from bagua_tpu.autopilot import AutopilotEngine, PolicyConfig

    base = dict(mode=mode, sustain=2, cooldown_s=0.0, budget=8,
                staleness_s=60.0, slo_goodput=0.0, straggler_ratio=3.0,
                suspect_ttl_s=600.0, ckpt_failures=3, switch_family="async")
    base.update(cfg)
    return AutopilotEngine(config=PolicyConfig(**base), actuators=actuators)


def _fleet_record_from_beacon(beacon_path, node_id=1):
    """The production coordinator merge over one worker beacon: node 0 is
    the (payload-less) coordinator, ``node_id`` the reporting worker."""
    from bagua_tpu.elastic import membership as mb
    from bagua_tpu.obs.export import build_fleet_record

    payload = mb.merged_health_source([beacon_path])()
    return build_fleet_record(0, {0: None, node_id: payload})


def _relabel_beacon_rank(beacon_path, rank):
    """Both anomaly legs run in THIS process (env rank 0); relabeling the
    beacon's rank is the only hand-made part of the fleet path (same
    convention as the straggler drill)."""
    rec = json.load(open(beacon_path))
    rec["obs"]["rank"] = rank
    if "straggler_suspect" in rec["obs"]:
        rec["obs"]["straggler_suspect"]["rank"] = rank
    with open(beacon_path, "w") as f:
        json.dump(rec, f)


def _actuate_autopilot_stop(action):
    """The monitor loop's fence/resize half on a live membership client:
    ``publish_autopilot_stop`` (the production publisher) converts the
    action into the ``health_fenced`` stop event the epoch/resize
    machinery rides; returns (stop_event, survivor_set) for a 2-node
    world."""
    from bagua_tpu.contrib.utils.store import InMemoryStore
    from bagua_tpu.distributed.run import publish_autopilot_stop
    from bagua_tpu.elastic import membership as mb

    client = mb.MembershipClient(InMemoryStore(), node_id=0, max_nnodes=2)
    nodes = [int(n) for n in action.target]
    publish_autopilot_stop(client, 0, action, nodes)
    stop = client.read_stop(0)
    survivors = {0, 1} - set(stop["nodes"]) if stop else {0, 1}
    return stop, survivors


def drill_autopilot_straggler_fence(tmp):
    """Chronic dispatch-dominant straggler -> autopilot fence + resize:
    a REAL self-straggled trainer run flags dispatch-dominant suspects
    (the production detector), the beacon rides the production merge into
    a fleet snapshot, the policy engine sustains the evidence over two
    snapshots and decides the fence, and the action actuates through the
    same ``health_fenced`` stop event lease expiry rides — the world
    resizes down to the survivors."""
    from bagua_tpu import telemetry as _t
    from bagua_tpu.elastic import membership as mb

    anomaly_env = {"BAGUA_OBS_ANOMALY_WARMUP": "4",
                   "BAGUA_OBS_ANOMALY_WINDOW": "24"}
    saved = {k: os.environ.get(k) for k in anomaly_env}
    os.environ.update(anomaly_env)
    before = telemetry.counters.snapshot()
    try:
        # self-straggle on the async family: local slowness files under
        # `dispatch` — the straggler's own signature
        suspects, beacon = _anomaly_leg(0, 1, 10.0, 10.0, tmp)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
    deltas = _counter_deltas(before)
    detected = (
        bool(suspects)
        and suspects[-1]["dominant_phase"] == "dispatch"
        and deltas.get("faults/step.straggle/fired", 0) >= 1
    )
    _relabel_beacon_rank(beacon, 1)

    engine = _autopilot_engine(sustain=2)
    actions = []
    for _ in range(2):
        time.sleep(0.02)  # distinct snapshot time_unix per poll
        actions = engine.observe_snapshot(_fleet_record_from_beacon(beacon))
    decided = (
        len(actions) == 1 and actions[0].kind == "fence"
        and actions[0].rule == "chronic_straggler"
        and actions[0].target == [1]
    )
    stop, survivors = (None, None)
    if decided:
        stop, survivors = _actuate_autopilot_stop(actions[0])
        engine.note_actuated(actions[0])
        if detected:
            inject.record_recovery("step.straggle")
    actuated = bool(
        stop and stop["kind"] == mb.STOP_HEALTH and stop["nodes"] == [1]
        and stop["rejoin"] is False
    )
    return {"injected": True,
            "detected": bool(detected and decided),
            "recovered": bool(actuated and survivors == {0}),
            "decided_actions": [a.kind for a in actions],
            "details": f"dispatch-dominant suspect (ratio "
                       f"{suspects[-1]['ratio'] if suspects else None}) "
                       f"sustained 2 snapshots -> fence node 1; stop "
                       f"{stop and stop['kind']} rejoin={stop and stop['rejoin']}; "
                       f"world resizes to {sorted(survivors or [])}"}


def drill_autopilot_victim_retune(tmp):
    """Collective-dominant victim -> retune hint CONSUMED: the gated-peer
    leg flags a collective-dominant suspect, the engine decides a retune
    hint and delivers it through ``AutotuneClient.report_metrics`` as the
    controller rank, and the live autotune service provably consumes it —
    the hinted sampling window is RE-MEASURED instead of scored."""
    import threading

    from bagua_tpu.autopilot import default_engine_actuators
    from bagua_tpu.service.autotune_service import (
        AutotuneService,
        make_server,
    )

    anomaly_env = {"BAGUA_OBS_ANOMALY_WARMUP": "4",
                   "BAGUA_OBS_ANOMALY_WINDOW": "24"}
    saved = {k: os.environ.get(k) for k in anomaly_env}
    os.environ.update(anomaly_env)
    before = telemetry.counters.snapshot()
    try:
        # peer-of-rank-1 straggle on the async family: the WAIT files
        # under `collective` — the victim's signature
        suspects, beacon = _anomaly_leg(1, 0, 10.0, 10.0, tmp)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
    deltas = _counter_deltas(before)
    detected = (
        bool(suspects)
        and suspects[-1]["dominant_phase"] == "collective"
        and deltas.get("faults/step.straggle/fired", 0) >= 1
    )

    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=10,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
    )
    server = make_server(0, service)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        model = "autopilot_victim_drill"
        # open a sampling window: one scored sample, window restarts
        service.report_metrics({"model_name": model, "rank": 0,
                                "train_iter": 1, "hyperparameters": {},
                                "speed": 100.0})
        service.ask_hyperparameters({"model_name": model, "rank": 0,
                                     "train_iter": 1})
        task = service._task(model)
        samples_before = task.n_samples

        engine = _autopilot_engine(
            sustain=2,
            actuators=default_engine_actuators(
                model_name=model, autotune_addr=f"127.0.0.1:{port}"),
        )
        actions = []
        for _ in range(2):
            time.sleep(0.02)
            actions = engine.observe_snapshot(
                _fleet_record_from_beacon(beacon))
        decided = (
            len(actions) == 1 and actions[0].kind == "retune_hint"
            and actions[0].rule == "collective_victim"
        )
        with task.lock:
            delivered = task.perf_hints_total >= 1 and any(
                h.get("kind") == "autopilot_retune_hint"
                and h.get("reported_by") == -1 for h in task.perf_hints
            )
        # the service CONSUMES the hint: the next confidence-gated
        # decision re-measures the window instead of scoring it
        service.report_metrics({"model_name": model, "rank": 0,
                                "train_iter": 2, "hyperparameters": {},
                                "speed": 100.0})
        service.ask_hyperparameters({"model_name": model, "rank": 0,
                                     "train_iter": 2})
        consumed = (task.n_samples == samples_before
                    and task.sample_retried is True)
        if detected and decided and consumed:
            inject.record_recovery("step.straggle")
    finally:
        server.shutdown()
    return {"injected": True,
            "detected": bool(detected and decided),
            "recovered": bool(delivered and consumed),
            "decided_actions": [a.kind for a in actions],
            "details": f"collective-dominant victim sustained 2 snapshots "
                       f"-> retune hint; delivered as controller rank -1: "
                       f"{delivered}; service re-measured the hinted "
                       f"window (n_samples {samples_before} unchanged, "
                       f"retry armed): {consumed}"}


def drill_autopilot_slo_ladder(tmp):
    """Sustained goodput-SLO breach -> the escalation ladder walked IN
    ORDER (hint -> retune -> family switch -> resize), with the switch
    actuated END-TO-END: the engine pins the family through the autotune
    service's recommendation path, and a LIVE autotuned trainer applies it
    at its next check-in — a re-jit plus the queued replicated->stacked
    state migration, never a restart.  The terminal resize actuates
    through the same stop event the fence rides."""
    import threading

    import optax

    import bench
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.autopilot import LADDER, default_engine_actuators
    from bagua_tpu.communication import get_hyperparameters_service_client
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.obs.export import build_fleet_record
    from bagua_tpu.parallel.mesh import build_mesh
    from bagua_tpu.service.autotune_service import (
        AutotuneService,
        make_server,
    )

    model = "autopilot_ladder_drill"
    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=50,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
    )
    server = make_server(0, service)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    env_save = {k: os.environ.get(k) for k in
                ("BAGUA_SERVICE_PORT", "MASTER_ADDR", "BAGUA_AUTOTUNE")}
    os.environ.update(BAGUA_SERVICE_PORT=str(port),
                      MASTER_ADDR="127.0.0.1", BAGUA_AUTOTUNE="1")
    get_hyperparameters_service_client.cache_clear()
    try:
        loss_fn, params, batch = bench.golden_task()
        trainer = BaguaTrainer(
            loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
            mesh=build_mesh({"dp": 8}), model_name=model,
            flat_resident="off",
        )
        state = trainer.init(params)
        b = trainer.shard_batch(batch)
        for _ in range(100):  # past the first check-in (step 100)
            state, loss = trainer.train_step(state, b)

        # the injected degradation: a fleet whose worst rank sits far
        # below the goodput SLO, sustained — each poll re-merges a fresh
        # snapshot the way the coordinator writer does
        engine = _autopilot_engine(
            sustain=1, slo_goodput=0.5, switch_family="async",
            actuators=default_engine_actuators(
                model_name=model, autotune_addr=f"127.0.0.1:{port}"),
        )
        fired = []
        for _ in range(len(LADDER)):
            time.sleep(0.02)
            record = build_fleet_record(0, {0: None, 1: {"obs": {
                "1": {"rank": 1, "step": 100, "goodput_fraction": 0.12},
            }}})
            fired.extend(engine.observe_snapshot(record))
        ladder_order = [a.kind for a in fired]
        decided = ladder_order == list(LADDER)
        task = service._task(model)
        with task.lock:
            pinned = task.pinned_algorithm == "async"

        # the switch lands at the trainer's next check-in, then the queued
        # replication migration converts the live state before the
        # re-jitted stacked step consumes it
        for _ in range(110):
            state, loss = trainer.train_step(state, b)
        switched = type(trainer.algorithm).__name__ == \
            "AsyncModelAverageAlgorithm"
        stacked = jax.tree.leaves(state.params)[0].shape[0] == 8
        if switched and hasattr(trainer.algorithm, "barrier"):
            state = trainer.algorithm.barrier(trainer, state)
        finite = bool(np.isfinite(float(loss)))

        stop, survivors = (None, None)
        resize = [a for a in fired if a.kind == "resize"]
        if resize:
            stop, survivors = _actuate_autopilot_stop(resize[0])
            engine.note_actuated(resize[0])
        actuated_resize = bool(stop and stop["rejoin"] is False
                               and stop["nodes"] == [1])
    finally:
        for k, v in env_save.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
        get_hyperparameters_service_client.cache_clear()
        server.shutdown()
    return {"injected": True,
            "detected": bool(decided and pinned),
            "recovered": bool(switched and stacked and finite
                              and actuated_resize),
            "ladder_order": ladder_order,
            "details": f"ladder walked {ladder_order} (in order: {decided}); "
                       f"service pinned family async: {pinned}; trainer "
                       f"switched via re-jit+migration: {switched} "
                       f"(stacked: {stacked}, finite loss: {finite}); "
                       f"terminal resize stop published: {actuated_resize}"}


def drill_autopilot_off_noop():
    """BAGUA_AUTOPILOT=off (the default) changes NOTHING: the launcher's
    engine-construction gate stays closed (run_elastic builds no engine —
    the coordinator monitor path is the pre-autopilot one), no
    ``autopilot/*`` counter moves, and the compiled train step is
    jaxpr-IDENTICAL across off/observe/act — the autopilot is
    coordinator-side by construction and never reaches the traced
    program."""
    from bagua_tpu import env as _env
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm

    saved = os.environ.get("BAGUA_AUTOPILOT")
    os.environ.pop("BAGUA_AUTOPILOT", None)
    before = telemetry.counters.snapshot()
    try:
        default_off = _env.get_autopilot_mode() == "off"
        # run_elastic's gate, verbatim: mode off -> no engine exists
        engine_gate_closed = not (_env.get_autopilot_mode() != "off")
        t, s, b = _golden_trainer(GradientAllReduceAlgorithm())
        jaxprs = {}
        for mode in ("off", "observe", "act"):
            os.environ["BAGUA_AUTOPILOT"] = mode
            jaxprs[mode] = str(t.trace_step(s, b))
    finally:
        os.environ.pop("BAGUA_AUTOPILOT", None)
        if saved is not None:
            os.environ["BAGUA_AUTOPILOT"] = saved
    deltas = _counter_deltas(before)
    no_autopilot_counters = not any(
        k.startswith("autopilot/") for k in deltas)
    pinned = jaxprs["off"] == jaxprs["observe"] == jaxprs["act"]
    return {"injected": True,  # the mode flip itself is the intervention
            "detected": bool(pinned),
            "recovered": bool(default_off and engine_gate_closed
                              and no_autopilot_counters),
            "jaxpr_identical": bool(pinned),
            "details": f"default mode off: {default_off}; engine gate "
                       f"closed: {engine_gate_closed}; step jaxpr "
                       f"identical across off/observe/act: {pinned}; no "
                       f"autopilot counters moved: {no_autopilot_counters}"}


def drill_autopilot_ckpt_quarantine(tmp):
    """Torn checkpoints xN -> storage quarantine: repeated armed
    ``ckpt.write`` corruption drives the REAL integrity counters up, the
    per-rank obs summary carries them (with the manager's storage path)
    through the production beacon merge, the engine decides
    ``quarantine_storage``, the actuator quarantines the path in the
    checkpoint registry — and the SAME live manager's next save redirects,
    after which restore lands on a verified step again."""
    import jax.numpy as jnp

    from bagua_tpu import checkpoint as ck
    from bagua_tpu.autopilot import default_engine_actuators
    from bagua_tpu.elastic import membership as mb
    from bagua_tpu.obs import export as obs_export
    from bagua_tpu.obs.export import build_fleet_record

    ck.clear_quarantine()
    obs_export.reset_local_summary()
    d = os.path.join(tmp, "autopilot_ckpt")

    def state(v):
        return {"w": jnp.arange(4096, dtype=jnp.float32) * v,
                "step": jnp.int32(0)}

    mgr = ck.BaguaCheckpointManager(d, async_save=False, max_to_keep=8)
    mgr.save(1, state(1.0))
    before = telemetry.counters.snapshot()
    with fault_scope(FaultSpec("ckpt.write", count=3)):
        for i, v in ((2, 2.0), (3, 3.0), (4, 4.0)):
            mgr.save(i, state(v))
        step, restored = mgr.try_restore(state(0.0))
        deltas = _counter_deltas(before)
        detected = (
            step == 1
            and deltas.get("ckpt/integrity_failures", 0) >= 3
            and deltas.get("ckpt/fallback_restores", 0) >= 1
        )

        # the evidence reaches the fleet snapshot through the production
        # path: obs summary (integrity counters + storage path) -> beacon
        # -> merged heartbeat payload -> coordinator merge
        obs_export.note_step(4, 0.01)
        beacon = os.path.join(tmp, "quarantine_beacon.r1")
        mb.write_health_beacon(beacon)
        record = build_fleet_record(
            0, {0: None, 1: mb.merged_health_source([beacon])()})

        engine = _autopilot_engine(
            sustain=1, ckpt_failures=3,
            actuators=default_engine_actuators(autotune_addr=None),
        )
        actions = engine.observe_snapshot(record)
        decided = (
            len(actions) == 1
            and actions[0].kind == "quarantine_storage"
            and str(actions[0].target) == ck._normalize_storage_path(d)
        )
        actuated = decided and ck.is_quarantined(d)

        # recovery: the live manager's next save redirects off the rotten
        # storage, and restore verifies again (no more fallback walking)
        recovered = False
        if actuated:
            mgr.save(5, state(5.0))
            redirected = mgr.directory == ck.redirect_directory(d)
            before2 = telemetry.counters.snapshot()
            step2, restored2 = mgr.try_restore(state(0.0))
            deltas2 = _counter_deltas(before2)
            recovered = (
                redirected and step2 == 5
                and np.array_equal(np.asarray(restored2["w"]),
                                   np.asarray(state(5.0)["w"]))
                and deltas2.get("ckpt/integrity_failures", 0) == 0
                and deltas2.get("ckpt/verified_restores", 0) >= 1
            )
            if detected and recovered:
                inject.record_recovery("ckpt.write")
    mgr.close()
    ck.clear_quarantine()
    return {"injected": True,
            "detected": bool(detected and decided),
            "recovered": bool(actuated and recovered),
            "decided_actions": [a.kind for a in actions],
            "details": f"3 torn saves -> restore fell back to step {step} "
                       f"with {deltas.get('ckpt/integrity_failures', 0)} "
                       f"integrity failures; engine quarantined {d}; next "
                       f"save redirected and restore verified step 5: "
                       f"{recovered}"}


def drill_autopilot_trend_rules(tmp):
    """Historian trend windows close the loop over DCN and HBM signals
    (ISSUE 14): a synthetic degradation stream — node 1's HBM headroom
    shrinking toward exhaustion, node 2's steps DCN-dominated, node 3 a
    flat control — flows through the LIVE telemetry historian (restart-
    store-persisted) into the act-mode engine.  The pre-OOM resize
    decides from the projected-exhaustion window and actuates through
    the production stop publisher (the world resizes BEFORE the OOM);
    the compression-escalation hint is delivered to a live autotune
    service as the controller rank and re-grants the re-measure; the
    flat control never fires; and a relaunched historian resumes its
    rings from the store."""
    import threading

    from bagua_tpu.autopilot import default_engine_actuators
    from bagua_tpu.contrib.utils.store import InMemoryStore
    from bagua_tpu.elastic import membership as mb
    from bagua_tpu.obs.historian import Historian
    from bagua_tpu.service.autotune_service import (
        AutotuneService,
        make_server,
    )

    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=10,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
    )
    server = make_server(0, service)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    model = "autopilot_trend_drill"
    store = InMemoryStore()
    historian = Historian(capacity=64, window_s=600.0, store=store,
                          persist_every=1)
    engine = _autopilot_engine(
        sustain=2, cooldown_s=300.0,
        actuators=default_engine_actuators(
            model_name=model, autotune_addr=f"127.0.0.1:{port}"),
    )

    def rank_obs(rank, step, headroom, dcn):
        return {"rank": rank, "step": step, "goodput_fraction": 0.9,
                "step_dt_p50": 0.1, "hbm_headroom_bytes": headroom,
                "device_comm_dcn_s_per_step": dcn,
                "device_comm_ici_s_per_step": 0.01}

    def fleet_record(i):
        from bagua_tpu.obs.export import build_fleet_record

        record = build_fleet_record(0, {0: None})
        record["ranks"] = {
            # node 1: headroom collapsing — the polls are ~20 ms apart,
            # so the fitted slope is steep and exhaustion projects well
            # inside the 600 s horizon
            "1": {"health": {}, "obs": {"1": rank_obs(
                1, 100 + i, 4.0e9 - i * 3.0e8, 0.005)}},
            # node 2: 70% of the step wall is DCN device seconds
            "2": {"health": {}, "obs": {"2": rank_obs(
                2, 100 + i, 8.0e9, 0.07)}},
            # node 3: flat control — must never fire a rule
            "3": {"health": {}, "obs": {"3": rank_obs(
                3, 100 + i, 8.0e9, 0.005)}},
        }
        record["nnodes"] = 3
        return record

    all_actions = []
    try:
        task = service._task(model)
        task.sample_retried = True  # a spent re-measure the hint re-grants
        for i in range(8):
            time.sleep(0.02)  # distinct snapshot time_unix per poll
            record = historian.ingest(fleet_record(i))
            all_actions.extend(engine.observe_snapshot(record))
        kinds = [a.kind for a in all_actions]
        resize = [a for a in all_actions if a.kind == "resize"]
        compress = [a for a in all_actions if a.kind == "compress_dcn"]
        trends = record["ranks"]["1"]["obs"]["1"].get("trends") or {}
        detected = (
            trends.get("hbm_headroom_slope", 0) < 0
            and trends.get("hbm_headroom_eta_s") is not None
            and (record["ranks"]["2"]["obs"]["2"]["trends"]
                 ["dcn_comm_share"]) >= 0.5
        )
        decided = (
            kinds == ["resize", "compress_dcn"]
            and resize[0].rule == "hbm_exhaustion"
            and resize[0].target == [1]
            and compress[0].rule == "dcn_dominance"
            and compress[0].target == "bytegrad"
            and not any("3" == str(n) for a in all_actions
                        for n in (a.target if isinstance(a.target, list)
                                  else []))
        )
        stop, survivors = (None, None)
        delivered = regranted = False
        if decided:
            stop, survivors = _actuate_autopilot_stop(resize[0])
            engine.note_actuated(resize[0])
            with task.lock:
                delivered = any(
                    h.get("kind") == "autopilot_compress_dcn"
                    and h.get("family") == "bytegrad"
                    and h.get("reported_by") == -1
                    for h in task.perf_hints
                )
            regranted = task.sample_retried is False
        actuated = bool(
            stop and stop["kind"] == mb.STOP_HEALTH and stop["nodes"] == [1]
            and stop["rejoin"] is False
        )
        # a relaunched coordinator's historian resumes the trend windows
        resumed = Historian(capacity=64, window_s=600.0, store=store)
        persisted = (
            resumed.slope("1", "hbm_headroom_bytes") is not None
            and resumed.slope("1", "hbm_headroom_bytes") < 0
        )
    finally:
        server.shutdown()
    recovered = bool(actuated and survivors == {0} and delivered
                     and regranted and persisted)
    return {"injected": True,
            "detected": bool(detected and decided),
            "recovered": recovered,
            "decided_actions": kinds,
            "details": f"historian trends (headroom slope "
                       f"{trends.get('hbm_headroom_slope')} B/s, eta "
                       f"{trends.get('hbm_headroom_eta_s')}s) -> "
                       f"pre-OOM resize of node 1 (world -> "
                       f"{sorted(survivors or [])}); DCN share 0.7 -> "
                       f"bytegrad compression hint delivered={delivered} "
                       f"re-measure re-granted={regranted}; historian "
                       f"resumed from store={persisted}"}


def drill_autopilot_compress_codec(tmp):
    """The ``compress_dcn`` trend hint ACTUATES a real wire-byte reduction
    (ISSUE 15): delivered to a live autotune service as the controller
    rank, the hint sets the recommended ``compress_inter`` codec; a LIVE
    autotuned trainer on the 2-slice hierarchical mesh applies it at its
    next check-in — a re-jit whose cross-slice tier now rides the
    COMPRESSED ring (quantized u8 ppermute hops, fp32 accumulation) —
    and the traced step's DCN wire bytes provably drop >= 3x while
    training stays finite."""
    import threading

    import optax

    import bench
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.analysis.jaxpr_check import iter_collectives
    from bagua_tpu.autopilot import default_engine_actuators
    from bagua_tpu.autopilot.policy import Action
    from bagua_tpu.communication import get_hyperparameters_service_client
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.parallel.mesh import build_mesh
    from bagua_tpu.service.autotune_service import (
        AutotuneService,
        make_server,
    )

    def dcn_wire_bytes(trainer, state, batch):
        jaxpr = trainer.trace_step(state, batch)
        total = 0
        for c in iter_collectives(jaxpr):
            if "inter" in c.axes:
                total += c.nbytes
        return total

    model = "autopilot_compress_drill"
    # autotune_level=0: the recommendation is served verbatim (no BO
    # sampling that could flip is_hierarchical_reduce between the two
    # byte measurements) — controller hints still actuate through
    # report_metrics regardless of level
    service = AutotuneService(
        world_size=1, autotune_level=0, max_samples=50,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
    )
    server = make_server(0, service)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    env_save = {k: os.environ.get(k) for k in
                ("BAGUA_SERVICE_PORT", "MASTER_ADDR", "BAGUA_AUTOTUNE")}
    os.environ.update(BAGUA_SERVICE_PORT=str(port),
                      MASTER_ADDR="127.0.0.1", BAGUA_AUTOTUNE="1")
    get_hyperparameters_service_client.cache_clear()
    try:
        loss_fn, params, batch = bench.golden_task()
        trainer = BaguaTrainer(
            loss_fn, optax.sgd(0.1),
            GradientAllReduceAlgorithm(hierarchical=True),
            mesh=build_mesh({"inter": 2, "intra": 4}), model_name=model,
            flat_resident="off",
        )
        state = trainer.init(params)
        b = trainer.shard_batch(batch)
        # step 1: registration applies the service's default
        # recommendation (is_hierarchical_reduce=False); pin the
        # hierarchical path in the recommendation so the check-in at step
        # 100 restores the two-level form this drill compresses
        state, loss = trainer.train_step(state, b)
        task = service._task(model)
        with task.lock:
            task.recommended.is_hierarchical_reduce = True
        for _ in range(105):  # past the step-100 check-in
            state, loss = trainer.train_step(state, b)
        assert trainer.algorithm.hierarchical, "check-in did not restore " \
            "the hierarchical recommendation"
        codec_before = trainer.compress_inter
        dcn_before = dcn_wire_bytes(trainer, state, b)

        # the hint, delivered exactly as the engine's actuator delivers a
        # decided compress_dcn action (controller rank -1)
        actuators = default_engine_actuators(
            model_name=model, autotune_addr=f"127.0.0.1:{port}")
        with task.lock:
            task.sample_retried = True  # a spent re-measure to re-grant
        delivered = actuators["compress_dcn"](Action(
            kind="compress_dcn", rule="dcn_dominance", target="bytegrad",
            reason="drill: sustained DCN dominance",
            evidence={"codec": "minmax_uint8"},
        ))
        with task.lock:
            service_actuated = (
                task.recommended.compress_inter == "minmax_uint8")
            regranted = task.sample_retried is False

        # the codec lands at the trainer's next check-in: a re-jit keyed
        # by the step cache, never a restart
        for _ in range(110):
            state, loss = trainer.train_step(state, b)
        flipped = trainer.compress_inter == "minmax_uint8"
        dcn_after = dcn_wire_bytes(trainer, state, b)
        ratio = dcn_before / max(dcn_after, 1)
        finite = bool(np.isfinite(float(loss)))
    finally:
        for k, v in env_save.items():
            os.environ.pop(k, None) if v is None else os.environ.update(
                {k: v})
        get_hyperparameters_service_client.cache_clear()
        server.shutdown()
    return {"injected": True,
            "detected": bool(delivered and service_actuated and regranted),
            "recovered": bool(flipped and ratio >= 3.0 and finite),
            "dcn_wire_bytes_before": int(dcn_before),
            "dcn_wire_bytes_after": int(dcn_after),
            "dcn_reduction_ratio": round(ratio, 2),
            "details": f"hint delivered={delivered}, service set "
                       f"compress_inter=minmax_uint8: {service_actuated} "
                       f"(re-measure re-granted={regranted}); live trainer "
                       f"codec {codec_before!r} -> "
                       f"{trainer.compress_inter!r} at check-in; traced "
                       f"DCN wire bytes {dcn_before} -> {dcn_after} "
                       f"({ratio:.2f}x, gate >= 3x); loss finite={finite}"}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", nargs="+", default=None, metavar="DRILL",
                    help="run only the named drill(s) — the CI smoke trace; "
                         "CHAOS_DRILL.json is NOT rewritten unless --out is "
                         "also given")
    ap.add_argument("--out", default=None,
                    help="output path (default: CHAOS_DRILL.json for the "
                         "full matrix, none for --only subsets)")
    ap.add_argument("--dump-dir", default=None,
                    help="flight-recorder dump directory (must be empty; "
                         "default: a fresh tempdir) — consumed before "
                         "argparse so the env var precedes jax imports")
    args = ap.parse_args(argv)
    if args.dump_dir and \
            os.path.abspath(args.dump_dir) != os.path.abspath(DUMP_DIR):
        # a programmatic main(argv=[... , "--dump-dir", d]) cannot be
        # honored: the env var was consumed from sys.argv at import time,
        # before jax — fail loudly instead of dumping into a tempdir the
        # caller never looks at
        ap.error(f"--dump-dir must appear on the PROCESS command line "
                 f"(dumps already bound to {DUMP_DIR} at import)")

    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    counters_before = telemetry.counters.snapshot()
    drills = {
        "store_flake_retry": drill_store_flake,
        "heartbeat_loss_lease_expiry": drill_heartbeat_loss,
        "checkpoint_corruption_fallback_restore":
            lambda: drill_checkpoint_corruption(tmp),
        "nan_grad_skip_loss_continuity": drill_nan_grad_skip,
        "grad_guard_on_goldens_unchanged": drill_guard_on_goldens,
        "collective_hang_watchdog_recovery": drill_collective_hang,
        "straggler_throughput_degrades":
            lambda: drill_straggler_throughput(tmp),
        "async_partition_staleness_catchup": drill_async_partition_catchup,
        "health_fence_flight_record": lambda: drill_health_fence(tmp),
        # the fleet autopilot's policy matrix (docs/autopilot.md):
        # injected -> detected -> DECIDED -> ACTUATED -> recovered
        "autopilot_straggler_fence_resize":
            lambda: drill_autopilot_straggler_fence(tmp),
        "autopilot_victim_retune_hint":
            lambda: drill_autopilot_victim_retune(tmp),
        "autopilot_slo_escalation_ladder":
            lambda: drill_autopilot_slo_ladder(tmp),
        "autopilot_ckpt_quarantine":
            lambda: drill_autopilot_ckpt_quarantine(tmp),
        "autopilot_trend_rules":
            lambda: drill_autopilot_trend_rules(tmp),
        "autopilot_compress_actuates_codec":
            lambda: drill_autopilot_compress_codec(tmp),
        "autopilot_off_noop": drill_autopilot_off_noop,
    }
    if args.only:
        unknown = [n for n in args.only if n not in drills]
        if unknown:
            ap.error(f"unknown drill(s) {unknown}; choose from "
                     f"{sorted(drills)}")
        drills = {n: drills[n] for n in args.only}
    # the goodput ledger observes every drill's defense path (the span
    # sink is normally installed by the first trainer; install explicitly
    # so span-only drills — the checkpoint walk — feed it too)
    from bagua_tpu.obs import ledger as obs_ledger

    obs_ledger.install()
    results = {}
    for name, fn in drills.items():
        print(f"=== {name} ===", flush=True)
        ledger_before = obs_ledger.ledger.report()
        try:
            results[name] = fn()
        except Exception as e:  # noqa: BLE001 - drill verdicts, not crashes
            results[name] = {"injected": True, "detected": False,
                             "recovered": False,
                             "details": f"drill crashed: "
                                        f"{type(e).__name__}: {e}"}
        expect = FLIGHT_EXPECTATIONS.get(name)
        if expect is not None:
            # the failure mode must have left its post-mortem artifact: a
            # schema-valid flight dump naming the firing fault point
            results[name]["flight_record"] = _flight_record_check(expect)
        ledger_cls = LEDGER_EXPECTATIONS.get(name)
        if ledger_cls is not None:
            # the drill's badput must have SURFACED in its ledger class
            results[name]["ledger"] = _ledger_class_check(
                ledger_cls, ledger_before, obs_ledger.ledger.report())
        print(f"    {results[name]}", flush=True)
        inject.clear_plan()
        bagua_tpu.reset_abort()

    passed = all(
        r["detected"] and r["recovered"]
        and r.get("flight_record", {}).get("schema_valid", True)
        and r.get("ledger", {}).get("surfaced", True)
        for r in results.values()
    )
    record = {
        "drill": "chaos",
        "pass": passed,
        "platform": "cpu-sim",
        "n_devices": len(jax.devices()),
        "elapsed_s": round(time.time() - t0, 1),
        "faults": results,
        "counters": _counter_deltas(counters_before),
    }
    out = args.out or (None if args.only else OUT)
    if out:
        with open(out, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out} (pass={passed})")
    else:
        print(f"subset pass={passed} (no artifact written; use --out)")
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())
