#!/usr/bin/env python
"""Generate docs/env_vars.md from the env-var registry.

The table is emitted straight from ``bagua_tpu.env.ENV_REGISTRY`` — the same
declaration the accessors read — so the reference cannot drift from the code.
``bagua-lint``'s ``raw-env-read`` rule closes the loop: a ``BAGUA_*`` read
outside the registry fails CI, so an undocumented tunable cannot exist.

Usage: python scripts/gen_env_docs.py [--check]
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "env_vars.md")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="verify the committed table matches (exit 1 on drift)")
    args = ap.parse_args()

    from bagua_tpu.env import render_env_vars_md

    text = render_env_vars_md()
    if args.check:
        old = open(OUT).read() if os.path.exists(OUT) else None
        if old != text:
            print("docs/env_vars.md out of date; regenerate with: "
                  "python scripts/gen_env_docs.py")
            return 1
        print("docs/env_vars.md up to date")
        return 0
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
