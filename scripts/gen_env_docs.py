#!/usr/bin/env python
"""Generate docs/env_vars.md and docs/metrics.md from their registries.

Both tables are emitted straight from the declarations the code reads —
``bagua_tpu.env.ENV_REGISTRY`` and ``bagua_tpu.obs.export.METRIC_REGISTRY``
— so the references cannot drift from the code.  ``bagua-lint`` closes each
loop: ``raw-env-read`` fails CI on a ``BAGUA_*`` read outside the env
registry, ``unregistered-counter`` fails it on a counter write site whose
name is not declared in the metric registry.

Usage: python scripts/gen_env_docs.py [--check]
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="verify the committed tables match (exit 1 on drift)")
    args = ap.parse_args()

    from bagua_tpu.env import render_env_vars_md
    from bagua_tpu.obs.export import render_metrics_md

    targets = [
        (os.path.join(REPO, "docs", "env_vars.md"), render_env_vars_md()),
        (os.path.join(REPO, "docs", "metrics.md"), render_metrics_md()),
    ]
    if args.check:
        stale = []
        for out, text in targets:
            old = open(out).read() if os.path.exists(out) else None
            if old != text:
                stale.append(os.path.relpath(out, REPO))
        if stale:
            print(f"{', '.join(stale)} out of date; regenerate with: "
                  "python scripts/gen_env_docs.py")
            return 1
        print("docs/env_vars.md + docs/metrics.md up to date")
        return 0
    for out, text in targets:
        with open(out, "w") as f:
            f.write(text)
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
