#!/usr/bin/env python
"""Generate the markdown API reference from docstrings (autoapi-style).

The reference ships a Sphinx docs site (/root/reference/docs/); this image
has no sphinx, so the generator is stdlib ``inspect`` emitting one markdown
file per module into ``docs/api/``.  Deterministic output — a test
regenerates and diffs, so the committed reference can't go stale.

Usage: python scripts/gen_api_docs.py [--check]
"""

from __future__ import annotations

import argparse
import importlib
import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "api")

# documented module surface (import order = TOC order)
MODULES = [
    "bagua_tpu",
    "bagua_tpu.core.backend",
    "bagua_tpu.communication",
    "bagua_tpu.algorithms.base",
    "bagua_tpu.algorithms.gradient_allreduce",
    "bagua_tpu.algorithms.bytegrad",
    "bagua_tpu.algorithms.q_adam",
    "bagua_tpu.algorithms.decentralized",
    "bagua_tpu.algorithms.async_model_average",
    "bagua_tpu.algorithms.zero",
    "bagua_tpu.bucket",
    "bagua_tpu.tensor",
    "bagua_tpu.checkpoint",
    "bagua_tpu.watchdog",
    "bagua_tpu.faults.inject",
    "bagua_tpu.env",
    "bagua_tpu.telemetry",
    "bagua_tpu.obs.spans",
    "bagua_tpu.obs.recorder",
    "bagua_tpu.obs.export",
    "bagua_tpu.obs.timeline",
    "bagua_tpu.obs.anomaly",
    "bagua_tpu.obs.attribution",
    "bagua_tpu.obs.regress",
    "bagua_tpu.obs.ledger",
    "bagua_tpu.obs.memory",
    "bagua_tpu.obs.historian",
    "bagua_tpu.obs.http",
    "bagua_tpu.autopilot.policy",
    "bagua_tpu.autopilot.engine",
    "bagua_tpu.podsim.util",
    "bagua_tpu.podsim.shaping",
    "bagua_tpu.podsim.collectives",
    "bagua_tpu.podsim.transport",
    "bagua_tpu.podsim.orchestrator",
    "bagua_tpu.profiling",
    "bagua_tpu.parallel.mesh",
    "bagua_tpu.parallel.tensor_parallel",
    "bagua_tpu.parallel.pipeline",
    "bagua_tpu.parallel.ring_attention",
    "bagua_tpu.parallel.ulysses",
    "bagua_tpu.model_parallel.moe.layer",
    "bagua_tpu.model_parallel.moe.gating",
    "bagua_tpu.models.mlp",
    "bagua_tpu.models.resnet",
    "bagua_tpu.models.vgg",
    "bagua_tpu.models.transformer",
    "bagua_tpu.models.generate",
    "bagua_tpu.serve",
    "bagua_tpu.serve.cache",
    "bagua_tpu.serve.engine",
    "bagua_tpu.serve.loader",
    "bagua_tpu.serve.schema",
    "bagua_tpu.ops.flash_attention",
    "bagua_tpu.ops.gmm",
    "bagua_tpu.ops.tiles",
    "bagua_tpu.compression.codecs",
    "bagua_tpu.compression.minmax_uint8",
    "bagua_tpu.compression.pallas_codec",
    "bagua_tpu.contrib.fused_optimizer",
    "bagua_tpu.contrib.load_balancing_data_loader",
    "bagua_tpu.contrib.cache_loader",
    "bagua_tpu.contrib.cached_dataset",
    "bagua_tpu.contrib.sync_batchnorm",
    "bagua_tpu.contrib.digits_data",
    "bagua_tpu.contrib.utils.store",
    "bagua_tpu.contrib.utils.tcp_store",
    "bagua_tpu.contrib.utils.redis_store",
    "bagua_tpu.service.autotune_service",
    "bagua_tpu.service.autotune_task_manager",
    "bagua_tpu.service.bayesian_optimizer",
    "bagua_tpu.distributed.run",
    "bagua_tpu.elastic.membership",
    "bagua_tpu.elastic.coordinator",
    "bagua_tpu.elastic.failover",
    "bagua_tpu.elastic.resize",
    "bagua_tpu.script.baguarun",
    "bagua_tpu.analysis",
    "bagua_tpu.analysis.ast_rules",
    "bagua_tpu.analysis.jaxpr_check",
    "bagua_tpu.analysis.findings",
    "bagua_tpu.analysis.suppressions",
    "bagua_tpu.analysis.concurrency",
    "bagua_tpu.analysis.trace_coherence",
    "bagua_tpu.analysis.lockdep",
    "bagua_tpu.define",
    "bagua_tpu.utils",
]


import re

_ADDR = re.compile(r" at 0x[0-9a-fA-F]+")


def _sig(obj) -> str:
    try:
        # strip memory addresses (flax module defaults embed function reprs)
        return _ADDR.sub("", str(inspect.signature(obj)))
    except (ValueError, TypeError):
        return "(...)"


def _doc(obj) -> str:
    d = inspect.getdoc(obj)
    # flax dataclass auto-docstrings embed object reprs with addresses
    return _ADDR.sub("", (d or "").strip())


def _public_members(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in vars(mod) if not n.startswith("_")]
    out = []
    for n in names:
        obj = getattr(mod, n, None)
        if obj is None or inspect.ismodule(obj):
            continue
        # only objects defined in (or re-exported by) this package
        owner = getattr(obj, "__module__", "") or ""
        if not owner.startswith("bagua_tpu") and mod.__name__ != "bagua_tpu":
            continue
        if inspect.isclass(obj) or inspect.isfunction(obj):
            out.append((n, obj))
    return out


def render_module(name: str) -> str:
    mod = importlib.import_module(name)
    lines = [f"# `{name}`", ""]
    if _doc(mod):
        lines += [_doc(mod), ""]
    for n, obj in _public_members(mod):
        if inspect.isclass(obj):
            lines += [f"## class `{n}{_sig(obj)}`", ""]
            if _doc(obj):
                lines += [_doc(obj), ""]
            for mn, meth in sorted(vars(obj).items()):
                if mn.startswith("_") or not callable(meth):
                    continue
                fn = meth.__func__ if isinstance(meth, (staticmethod, classmethod)) else meth
                if not (inspect.isfunction(fn) or inspect.ismethod(fn)):
                    continue
                lines += [f"### `{n}.{mn}{_sig(fn)}`", ""]
                if _doc(fn):
                    lines += [_doc(fn), ""]
        else:
            lines += [f"## `{n}{_sig(obj)}`", ""]
            if _doc(obj):
                lines += [_doc(obj), ""]
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="verify committed docs match (exit 1 on drift)")
    args = ap.parse_args()

    os.makedirs(OUT, exist_ok=True)
    index = ["# API reference", "",
             "Generated by `scripts/gen_api_docs.py` — do not edit by hand.",
             ""]
    drift = []
    for name in MODULES:
        text = render_module(name)
        fname = name.replace(".", "_") + ".md"
        index.append(f"- [`{name}`]({fname})")
        path = os.path.join(OUT, fname)
        if args.check:
            old = open(path).read() if os.path.exists(path) else None
            if old != text:
                drift.append(name)
        else:
            with open(path, "w") as f:
                f.write(text)
    index_text = "\n".join(index) + "\n"
    index_path = os.path.join(OUT, "index.md")
    if args.check:
        old = open(index_path).read() if os.path.exists(index_path) else None
        if old != index_text:
            drift.append("<index>")
        if drift:
            print("API docs out of date for:", ", ".join(drift))
            print("regenerate with: python scripts/gen_api_docs.py")
            return 1
        print(f"API docs up to date ({len(MODULES)} modules)")
        return 0
    with open(index_path, "w") as f:
        f.write(index_text)
    print(f"wrote {len(MODULES)} module pages to {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
