#!/usr/bin/env python
"""CI smoke for the HTTP status plane (ISSUE 14): scrape the live
endpoints during a real cpu-sim training run and validate every surface.

What it proves, end-to-end in one process:

1. a short ``BaguaTrainer`` run on the 8-device virtual CPU mesh with the
   metrics exporter AND the HTTP server up;
2. ``GET /metrics`` DURING the run parses as Prometheus text, every
   series is registered with ``# HELP``/``# TYPE`` (none untyped), and
   the series set matches the concurrent on-disk ``metrics.prom``
   snapshot series-for-series (both render the same prepared snapshot);
3. ``GET /fleet`` returns a schema-valid ``bagua-obs-fleet-v1`` record
   (built by the production merge, trend-augmented by a live historian);
4. ``GET /history`` returns the historian's windowed samples + slope;
5. ``GET /healthz`` and ``GET /ledger`` answer.

Exit code 0 iff every check holds.  Usage:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/obs_http_smoke.py [--export-dir DIR] [--steps N]
"""

import argparse
import json
import os
import re
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as rsp:
        return rsp.read().decode()


def _series(prom_text):
    return {line.split(" ", 1)[0] for line in prom_text.splitlines()
            if line and not line.startswith("#")}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--export-dir", default=None)
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args(argv)
    export_dir = args.export_dir or tempfile.mkdtemp(prefix="obs_http_")

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import optax

    import bench
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.obs import export as obs_export
    from bagua_tpu.obs.historian import Historian
    from bagua_tpu.obs.http import ObsHTTPServer
    from bagua_tpu.parallel.mesh import build_mesh

    failures = []

    def check(name, ok, detail=""):
        print(f"  {'ok  ' if ok else 'FAIL'} {name}" +
              (f" ({detail})" if detail else ""), flush=True)
        if not ok:
            failures.append(name)

    historian = Historian(capacity=64, window_s=600.0)
    holder = {"record": None}
    server = ObsHTTPServer(port=0, fleet_provider=lambda: holder["record"],
                           historian=historian).start()
    exporter = obs_export.MetricsExporter(export_dir, interval_s=3600)
    os.makedirs(export_dir, exist_ok=True)
    try:
        loss_fn, params, batch = bench.golden_task()
        trainer = BaguaTrainer(
            loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
            mesh=build_mesh({"dp": 8}), autotune=False,
        )
        state = trainer.init(params)
        sharded = trainer.shard_batch(batch)
        loss = None
        for step in range(args.steps):
            state, loss = trainer.train_step(state, sharded)
            # the coordinator-side monitor tick, in miniature: merge the
            # local summary into a fleet record and trend-augment it
            summary = obs_export.local_obs_summary() or {}
            record = obs_export.build_fleet_record(
                0, {0: {"obs": dict(summary)}} if summary else {0: None})
            holder["record"] = historian.ingest(record)
            if step == args.steps // 2:
                # a mid-run scrape: the endpoint must serve while the
                # step loop is hot
                mid = _get(server.url + "/metrics")
                check("mid-run /metrics scrape parses",
                      "# TYPE" in mid and bool(_series(mid)))
            time.sleep(0.01)
        check("training run finite", loss is not None
              and bool(np.isfinite(float(loss))))

        # warm the self-accounting counters, then compare steady state
        _get(server.url + "/metrics")
        exporter.export_once()
        exporter.export_once()
        scraped = _get(server.url + "/metrics")
        on_disk = open(os.path.join(export_dir, "metrics.prom")).read()
        check("/metrics matches metrics.prom series-for-series",
              _series(scraped) == _series(on_disk),
              f"{len(_series(scraped))} series")
        check("no untyped series", "untyped" not in scraped)
        prom_names = {obs_export.prometheus_name(n)
                      for n in obs_export.METRIC_REGISTRY}
        unregistered = _series(scraped) - prom_names
        check("every scraped series is registered", not unregistered,
              ", ".join(sorted(unregistered)) or "all registered")
        helped = set(re.findall(r"^# HELP (\S+)", scraped, re.M))
        typed = set(re.findall(r"^# TYPE (\S+)", scraped, re.M))
        check("every series has HELP and TYPE",
              _series(scraped) <= helped and _series(scraped) <= typed)

        fleet = json.loads(_get(server.url + "/fleet"))
        problems = obs_export.validate_fleet_snapshot(fleet)
        check("/fleet is schema-valid bagua-obs-fleet-v1", not problems,
              "; ".join(problems) or fleet["schema"])

        history = json.loads(_get(server.url +
                                  "/history?metric=step&window=600"))
        entry = (history.get("ranks") or {}).get("0") or {}
        check("/history serves windowed samples",
              len(entry.get("samples") or []) >= 2
              and entry.get("rate_per_s") is not None,
              f"{len(entry.get('samples') or [])} samples")

        health = json.loads(_get(server.url + "/healthz"))
        check("/healthz ok", health.get("status") == "ok")
        json.loads(_get(server.url + "/ledger"))
        check("/ledger answers JSON", True)
    finally:
        server.stop()
    if failures:
        print(f"obs http smoke: {len(failures)} check(s) FAILED: "
              f"{failures}")
        return 1
    print("obs http smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
