"""Watchdog end-to-end drill on REAL TPU hardware (VERDICT r4 #8).

Runs tests/workers/watchdog_drill_worker.py under the launcher on the real
chip: a device program wedges inside ``trainer.train_step`` at step 4; the
hang watchdog must fire at ``BAGUA_COMM_TIMEOUT_S``, flush queued async
checkpoint saves, exit 3; the launcher restarts the gang; the restarted
worker resumes from the orbax checkpoint and completes.  Writes the full
log to ``WATCHDOG_DRILL_TPU.log`` and a verdict line to
``WATCHDOG_DRILL_TPU.json``.

Usage: python scripts/watchdog_drill.py
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main():
    tmp = tempfile.mkdtemp(prefix="watchdog_drill_")
    env = dict(os.environ)
    env["BAGUA_TEST_OUT"] = tmp
    env["BAGUA_TEST_STEPS"] = "8"
    env["BAGUA_TEST_WEDGE_AT_STEP"] = "4"
    env["BAGUA_COMM_TIMEOUT_S"] = "60"  # first TPU compile can take 20-40s
    env.pop("BAGUA_SERVICE_PORT", None)
    env.pop("BAGUA_TEST_FORCE_CPU", None)
    cmd = [
        sys.executable, "-m", "bagua_tpu.distributed.run",
        "--nproc_per_node", "1",
        "--master_port", str(_free_port()),
        "--bagua_service_port", "-1",
        "--max_restarts", "1",
        os.path.join(REPO, "tests", "workers", "watchdog_drill_worker.py"),
    ]
    t0 = time.time()
    out = subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=1200
    )
    log = out.stdout + out.stderr
    with open(os.path.join(REPO, "WATCHDOG_DRILL_TPU.log"), "w") as f:
        f.write(log)
    # two legitimate failure modes funnel into the same
    # abort->restart->resume chain: the watchdog's own timeout
    # (stuck-section message + exit 3), or the TPU runtime faulting the
    # wedged program first (UNAVAILABLE surfacing through the watchdog's
    # readback waiter + the training loop).  Record which one happened —
    # the drill's claim is the CHAIN, and the artifact must not imply the
    # timeout path fired if the runtime won the race.
    timed_out = "stuck for" in log and "dumping stacks" in log
    runtime_fault = "UNAVAILABLE" in log
    checks = {
        "worker_ran_on_tpu": "platform=tpu" in log,
        "wedge_injected": "injecting device wedge at step 4" in log,
        "failure_detected": timed_out or runtime_fault,
        "failure_mode": (
            "watchdog_timeout" if timed_out
            else ("tpu_runtime_fault_via_watchdog_readback" if runtime_fault
                  else "none")
        ),
        "gang_restarted": "gang restart" in log,
        "resumed_from_checkpoint": "resumed from checkpoint step" in log,
        "completed": "drill complete" in log,
        "exit_code": out.returncode,
        "wall_s": round(time.time() - t0, 1),
    }
    checks["ok"] = all(
        v for k, v in checks.items()
        if k not in ("exit_code", "wall_s", "failure_mode")
    ) and out.returncode == 0
    print(json.dumps(checks, indent=1))
    with open(os.path.join(REPO, "WATCHDOG_DRILL_TPU.json"), "w") as f:
        json.dump(checks, f, indent=1)
    sys.exit(0 if checks["ok"] else 1)


if __name__ == "__main__":
    main()
