#!/usr/bin/env python
"""Pod-scale proof drill: drive the REAL coordinator stack with 32-256
worker processes and record how it scales (BENCH_SCALE.json).

Two layers per world size in ``BAGUA_SCALE_RANKS``:

* **live** — :class:`bagua_tpu.podsim.orchestrator.PodSim` spawns that
  many real OS processes through the production rendezvous / lease /
  heartbeat path over loopback TCP.  The first (smallest) size runs the
  FULL scenario: cold-start rendezvous -> shaped hierarchical+compressed
  collectives (link-shaped ICI/DCN physics) -> lease-expiry shrink ->
  standby regrow -> autopilot straggler fence -> teardown, each phase
  asserted.  Larger sizes run the light scenario (rendezvous + monitor
  ticks + teardown) — same control plane, no per-step data plane, so one
  CI core can afford 128 processes.
* **bench** — process-free microbenches of the coordinator hot paths at
  that world size: fleet-record decision latency (autopilot policy
  matrix), historian ingest rate, coordinator ``/fleet`` HTTP p99, and
  the restart-store connect storm.

The connect-storm bench measures the TCPStore listen-backlog bottleneck
before/after (socketserver's default 5-deep accept queue drops SYNs
under a pod-scale reconnect herd; ``_Server.request_queue_size = 256``
is the fix), and the HTTP bench measures ``/fleet`` with the render
cache off/on (per-request ``json.dumps`` of an O(nnodes) record burned
the monitor core under scraper load) — the two coordinator fixes this
drill exists to keep honest.

Usage::

    python scripts/scale_drill.py            # full sweep, writes BENCH_SCALE.json
    python scripts/scale_drill.py --smoke    # 4-process scenario only (CI step)
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __package__ in (None, ""):  # import-light shim: no jax in the drill
    import importlib.util

    sys.path.insert(0, _REPO)
    _spec = importlib.util.spec_from_loader(
        "bagua_tpu", loader=None, is_package=True)
    _pkg = importlib.util.module_from_spec(_spec)
    _pkg.__path__ = [os.path.join(_REPO, "bagua_tpu")]
    sys.modules["bagua_tpu"] = _pkg

import argparse  # noqa: E402
import json  # noqa: E402
import logging  # noqa: E402
import socket  # noqa: E402
import tempfile  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402
import urllib.request  # noqa: E402

from bagua_tpu import env as _env  # noqa: E402
from bagua_tpu.autopilot.engine import AutopilotEngine  # noqa: E402
from bagua_tpu.autopilot.policy import PolicyConfig  # noqa: E402
from bagua_tpu.contrib.utils import tcp_store as _tcp  # noqa: E402
from bagua_tpu.obs.export import build_fleet_record  # noqa: E402
from bagua_tpu.obs.historian import Historian  # noqa: E402
from bagua_tpu.obs.http import ObsHTTPServer  # noqa: E402
from bagua_tpu.podsim.orchestrator import PodSim  # noqa: E402

logger = logging.getLogger("scale_drill")

SCHEMA = "bagua-bench-scale-v1"


def _percentile(values, q):
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return vals[idx]


def _ms(seconds):
    return None if seconds is None else round(seconds * 1e3, 3)


def _policy():
    return PolicyConfig(mode="act", sustain=2, cooldown_s=0.0, budget=8,
                        staleness_s=60.0, suspect_ttl_s=30.0)


# ---------------------------------------------------------------------------
# live scenarios (real processes)
# ---------------------------------------------------------------------------


def full_scenario(world, workdir, shape, seed, steps=1, vec_elems=8192,
                  slice_size=8, timeout_s=180.0,
                  dcn_codec="minmax_uint8"):
    """The end-to-end proof at one world size: every phase of the
    coordinator's life driven against real processes, every phase
    asserted.  Returns (checks, metrics)."""
    checks = {}
    t0 = time.monotonic()
    with PodSim(world, workdir, min_nnodes=2, steps=steps,
                vec_elems=vec_elems, shape=shape, slice_size=slice_size,
                seed=seed, dcn_codec=dcn_codec, lease_ttl_s=4.0,
                join_window_s=60.0,
                timeout_s=timeout_s, policy=_policy()) as sim:
        sim.spawn_all()
        spec = sim.rendezvous(1)
        checks["cold_start_full_world"] = spec.nnodes == world
        verdict, _ = sim.monitor(spec, until="all_ok", max_s=timeout_s)
        checks["shaped_collectives_ok"] = verdict == "all_ok"
        verdicts = sim.ok_verdicts(spec)
        checks["collectives_within_quant_tolerance"] = bool(verdicts) and all(
            v.get("max_err", 0.0) <= v.get("atol", 1.0)
            for v in verdicts.values() if not v.get("skipped")
        )
        dcn_hops = sum(
            v.get("shaping", {}).get("dcn", {}).get("hops", 0)
            for v in verdicts.values())
        checks["dcn_tier_exercised"] = dcn_hops > 0

        # elastic shrink: hard-kill the highest node, lease must expire
        victim = world - 1
        sim.kill(victim)
        verdict, who = sim.monitor(spec, until="stop", max_s=60.0)
        checks["lease_expiry_detected"] = (
            verdict == "expired" and who == [victim])
        survivors = [n for n in range(world) if n != victim]
        spec = sim.rendezvous(2, expect=survivors)
        checks["shrunk_world"] = spec.nnodes == world - 1
        verdict, _ = sim.monitor(spec, until="all_ok", max_s=timeout_s)
        checks["post_shrink_collectives_ok"] = verdict == "all_ok"

        # regrow: relaunch the victim, admit it at the next boundary
        sim.spawn(victim)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not sim.standby_ids():
            time.sleep(0.3)
        checks["standby_detected"] = victim in sim.standby_ids()
        spec = sim.rendezvous(3, expect=list(range(world)))
        checks["regrown_world"] = spec.nnodes == world
        verdict, _ = sim.monitor(spec, until="all_ok", max_s=timeout_s)
        checks["post_regrow_collectives_ok"] = verdict == "all_ok"

        # autopilot observe->act: a chronic straggler profile must be
        # fenced through the real policy matrix + stop/resize machinery
        straggler = world // 2
        sim.set_profile(straggler, "straggler")
        verdict, who = sim.monitor(spec, until="stop", max_s=60.0,
                                   tick_s=0.5)
        checks["autopilot_fenced_straggler"] = (
            verdict == "fenced" and who == [straggler])
        spec = sim.rendezvous(
            4, expect=[n for n in range(world) if n != straggler])
        checks["post_fence_world"] = spec.nnodes == world - 1
        verdict, _ = sim.monitor(spec, until="all_ok", max_s=timeout_s)
        checks["post_fence_collectives_ok"] = verdict == "all_ok"

        # coordinator HTTP plane serves the live fleet + historian trends
        try:
            fleet = json.load(urllib.request.urlopen(
                sim.http.url + "/fleet", timeout=10))
            hist = json.load(urllib.request.urlopen(
                sim.http.url + "/history?metric=goodput_fraction",
                timeout=10))
            checks["http_fleet_live"] = (
                fleet.get("schema") == "bagua-obs-fleet-v1"
                and fleet.get("nnodes") == spec.nnodes)
            checks["http_history_live"] = bool(hist.get("ranks"))
        except Exception as e:  # noqa: BLE001 - recorded, not raised
            logger.warning("http check failed: %s", e)
            checks["http_fleet_live"] = checks["http_history_live"] = False

        sim.halt()
        codes = sim.wait_all(timeout_s=60.0)
        checks["fenced_node_exit_code"] = codes.get(straggler) == 4
        checks["survivors_exit_clean"] = all(
            c == 0 for n, c in codes.items() if n != straggler)
        metrics = _live_metrics(sim)
    metrics["wall_s"] = round(time.monotonic() - t0, 1)
    metrics["scenario"] = "full"
    return checks, metrics


def light_scenario(world, workdir, shape, seed, ticks=5, timeout_s=None):
    """Control-plane-only live run at one world size: real processes,
    real rendezvous/leases/monitor ticks, no per-step data plane."""
    checks = {}
    t0 = time.monotonic()
    # cold start on single-core CI is serial process boot (~1.3 s/worker
    # under load, measured in BENCH_SCALE.json rendezvous_s) — the join
    # window and worker deadline must scale with world or 128 ranks can
    # never all arrive
    join_window_s = max(120.0, 2.0 * world)
    if timeout_s is None:
        timeout_s = max(240.0, 3.0 * world)
    with PodSim(world, workdir, min_nnodes=2, steps=0, shape=shape,
                seed=seed, hb_interval_s=1.0, lease_ttl_s=8.0,
                join_window_s=join_window_s, timeout_s=timeout_s,
                policy=_policy()) as sim:
        sim.spawn_all()
        spec = sim.rendezvous(1)
        checks["cold_start_full_world"] = spec.nnodes == world
        verdict, _ = sim.monitor(spec, until="all_ok", max_s=timeout_s)
        checks["all_members_reported"] = verdict == "all_ok"
        for _ in range(ticks):
            sim._observe_tick(spec)
            time.sleep(0.1)
        sim.halt()
        codes = sim.wait_all(timeout_s=60.0)
        checks["all_exit_clean"] = all(c == 0 for c in codes.values())
        metrics = _live_metrics(sim)
    metrics["wall_s"] = round(time.monotonic() - t0, 1)
    metrics["scenario"] = "light"
    return checks, metrics


def _live_metrics(sim):
    m = sim.metrics
    return {
        "rendezvous_s": [round(v, 3) for v in m["rendezvous_s"]],
        "cold_start_rendezvous_s": round(m["rendezvous_s"][0], 3)
        if m["rendezvous_s"] else None,
        "monitor_tick_p50_ms": _ms(_percentile(m["tick_s"], 0.5)),
        "monitor_tick_p99_ms": _ms(_percentile(m["tick_s"], 0.99)),
        "decide_p99_ms": _ms(_percentile(m["decide_s"], 0.99)),
        "ingest_p99_ms": _ms(_percentile(m["ingest_s"], 0.99)),
    }


# ---------------------------------------------------------------------------
# control-plane microbenches (no processes)
# ---------------------------------------------------------------------------


def synth_record(world, t, straggler=None):
    """A ``bagua-obs-fleet-v1`` record of ``world`` ranks at time ``t``
    with enough numeric freight to make ingest/decide/serialize do real
    per-rank work."""
    members = {}
    for n in range(world):
        obs = {
            "rank": n, "step": int(t), "goodput_fraction": 0.91,
            "step_dt_s": 0.105, "hbm_headroom_bytes": 2.0e9,
            "dcn_device_s": 0.012, "worst_badput_class": "collective_wait",
        }
        if n == straggler:
            obs["straggler_suspect"] = {
                "rank": n, "ratio": 5.0, "detected_at_unix": t,
                "dominant_phase": "dispatch",
            }
        members[n] = {"obs": obs}
    record = build_fleet_record(0, members)
    record["time_unix"] = float(t)
    return record


def bench_decision_latency(world, samples=60):
    """Autopilot decide() wall time per fleet snapshot at this world
    size (the monitor loop pays this every tick)."""
    engine = AutopilotEngine(config=_policy())
    base = time.time()
    times = []
    for i in range(samples):
        record = synth_record(world, base + i,
                              straggler=(world // 2 if i % 7 == 0 else None))
        t0 = time.monotonic()
        engine.observe_snapshot(record, now=base + i)
        times.append(time.monotonic() - t0)
    return {"p50_ms": _ms(_percentile(times, 0.5)),
            "p99_ms": _ms(_percentile(times, 0.99)),
            "samples": samples}


def bench_historian_ingest(world, samples=60):
    """Historian records/second at this world size (every rank of every
    record feeds per-metric ring buffers + trend publication)."""
    historian = Historian(capacity=4096, window_s=300.0)
    base = time.time()
    records = [synth_record(world, base + i) for i in range(samples)]
    t0 = time.monotonic()
    for r in records:
        historian.ingest(r)
    wall = time.monotonic() - t0
    return {"records_per_s": round(samples / wall, 1) if wall > 0 else None,
            "per_record_ms": _ms(wall / samples)}


def bench_http_fleet(world, requests=120, threads=4, cache=True):
    """Coordinator ``/fleet`` latency under concurrent scrapers.
    ``cache=False`` re-renders the JSON per request — the pre-fix
    behavior, kept measurable as the before branch."""
    record = synth_record(world, time.time())
    server = ObsHTTPServer(port=0, addr="127.0.0.1",
                           fleet_provider=lambda: record,
                           cache_fleet_json=cache).start()
    url = server.url + "/fleet"
    times, errors = [], []
    lock = threading.Lock()

    def scrape(n):
        for _ in range(n):
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(url, timeout=30) as resp:
                    resp.read()
            except Exception as e:  # noqa: BLE001 - recorded
                with lock:
                    errors.append(str(e))
                continue
            with lock:
                times.append(time.monotonic() - t0)

    pool = [threading.Thread(target=scrape, args=(requests // threads,))
            for _ in range(threads)]
    t_all = time.monotonic()
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    wall = time.monotonic() - t_all
    server.stop()
    return {"p50_ms": _ms(_percentile(times, 0.5)),
            "p99_ms": _ms(_percentile(times, 0.99)),
            "requests_per_s": round(len(times) / wall, 1) if wall else None,
            "errors": len(errors)}


def bench_connect_storm(clients, backlog):
    """``clients`` concurrent TCPStore connect+set+get against one
    python store server with the given listen backlog — the pod
    cold-start fan-in.  Returns wall time and worst connect latency
    (SYN drops surface as >= 1 s retransmit stalls)."""
    old = _tcp._Server.request_queue_size
    _tcp._Server.request_queue_size = backlog
    try:
        server = _tcp.TCPStoreServer("127.0.0.1", 0, backend="python")
    finally:
        _tcp._Server.request_queue_size = old
    addr, port = server.address
    times, errors = [], []
    lock = threading.Lock()
    gate = threading.Barrier(clients + 1)

    def dial(i):
        try:
            gate.wait(timeout=30)
            t0 = time.monotonic()
            client = _tcp.TCPStore(addr, port, timeout_s=30.0)
            dt = time.monotonic() - t0
            client.set(f"storm/{i}", b"1")
            assert client.get(f"storm/{i}") == b"1"
            client._sock.close()
            with lock:
                times.append(dt)
        except Exception as e:  # noqa: BLE001 - recorded
            with lock:
                errors.append(str(e))

    pool = [threading.Thread(target=dial, args=(i,))
            for i in range(clients)]
    for t in pool:
        t.start()
    gate.wait(timeout=30)
    t_all = time.monotonic()
    for t in pool:
        t.join(timeout=120)
    wall = time.monotonic() - t_all
    server.stop()
    return {"backlog": backlog, "clients": clients,
            "wall_s": round(wall, 3),
            "connect_p99_ms": _ms(_percentile(times, 0.99)),
            "connect_max_ms": _ms(max(times) if times else None),
            "errors": len(errors)}


# ---------------------------------------------------------------------------
# the drill
# ---------------------------------------------------------------------------


def run_smoke(args):
    workdir = tempfile.mkdtemp(prefix="podsim_smoke_")
    checks, metrics = full_scenario(
        4, workdir, shape=args.shape, seed=args.seed, steps=2,
        vec_elems=4096, slice_size=2, timeout_s=90.0,
        dcn_codec=args.dcn_codec)
    verdict = {"drill": "scale-smoke", "world": 4,
               "dcn_codec": args.dcn_codec, "checks": checks,
               "metrics": metrics, "log_dir": workdir,
               "ok": all(checks.values())}
    print(json.dumps(verdict, indent=1, sort_keys=True))
    return 0 if verdict["ok"] else 1


def run_full(args):
    t0 = time.monotonic()
    worlds = {}
    all_checks = {}
    base_dir = tempfile.mkdtemp(prefix="podsim_drill_")
    for i, world in enumerate(args.ranks):
        workdir = os.path.join(base_dir, f"w{world}")
        logger.info("=== world %d: live %s scenario ===", world,
                    "full" if i == 0 else "light")
        if i == 0:
            checks, live = full_scenario(
                world, workdir, shape=args.shape, seed=args.seed,
                steps=args.steps, slice_size=args.slice_size,
                dcn_codec=args.dcn_codec)
        else:
            checks, live = light_scenario(
                world, workdir, shape=args.shape, seed=args.seed)
        for name, ok in checks.items():
            all_checks[f"w{world}/{name}"] = ok
        logger.info("=== world %d: control-plane benches ===", world)
        worlds[str(world)] = {
            "live": {**live, "checks": checks},
            "decision_latency": bench_decision_latency(world),
            "historian_ingest": bench_historian_ingest(world),
            "http_fleet": bench_http_fleet(world, cache=True),
        }

    # bottleneck before/after: measured once at the largest swept size
    top = max(args.ranks)
    logger.info("=== bottleneck before/after @ %d ===", top)
    storm_clients = min(2 * top, 256)
    backlog_before = bench_connect_storm(storm_clients, backlog=5)
    backlog_after = bench_connect_storm(
        storm_clients, backlog=_tcp._Server.request_queue_size)
    http_before = bench_http_fleet(top, cache=False)
    http_after = bench_http_fleet(top, cache=True)
    bottlenecks = {
        "tcp_store_listen_backlog": {
            "problem": "socketserver default backlog 5 drops cold-start "
                       "connect-storm SYNs; clients stall >= 1s on "
                       "retransmit",
            "fix": "contrib/utils/tcp_store.py: _Server.request_queue_size "
                   f"= {_tcp._Server.request_queue_size}",
            "before": backlog_before, "after": backlog_after,
        },
        "fleet_json_rerender": {
            "problem": "/fleet re-ran json.dumps of the O(nnodes) record "
                       "per request, burning the monitor core under "
                       "concurrent scrapers",
            "fix": "obs/http.py: render cache keyed on record identity "
                   "(cache_fleet_json=False restores the old path)",
            "before": http_before, "after": http_after,
        },
    }
    all_checks["backlog_fix_no_slower"] = (
        backlog_after["errors"] == 0
        and (backlog_before["connect_max_ms"] is None
             or backlog_after["connect_max_ms"]
             <= backlog_before["connect_max_ms"] * 1.5 + 50.0))
    all_checks["fleet_cache_no_slower"] = (
        http_after["errors"] == 0
        and http_after["p99_ms"] <= http_before["p99_ms"] * 1.5 + 5.0)

    record = {
        "schema": SCHEMA,
        "drill": "scale",
        "platform": "cpu-sim",
        "host_cores": os.cpu_count(),
        "shape": args.shape,
        "seed": args.seed,
        "dcn_codec": args.dcn_codec,
        "worlds": worlds,
        "bottlenecks": bottlenecks,
        "checks": all_checks,
        "log_dir": base_dir,
        "wall_s": round(time.monotonic() - t0, 1),
        "ok": all(all_checks.values()),
    }
    out = os.path.abspath(args.out)
    with open(out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({k: record[k] for k in
                      ("schema", "checks", "wall_s", "ok")},
                     indent=1, sort_keys=True))
    print(f"wrote {out}")
    return 0 if record["ok"] else 1


def main(argv=None):
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="4-process full scenario only (the CI gate)")
    ap.add_argument("--ranks", default=None,
                    help="comma-separated world sizes "
                         "(default: BAGUA_SCALE_RANKS)")
    ap.add_argument("--shape", default=None,
                    help="link shape preset/JSON (default: "
                         "BAGUA_SCALE_SHAPE)")
    ap.add_argument("--seed", type=int, default=None,
                    help="determinism seed (default: BAGUA_SCALE_SEED)")
    ap.add_argument("--steps", type=int, default=1,
                    help="collective steps per epoch in the full scenario")
    ap.add_argument("--slice-size", type=int, default=8)
    ap.add_argument("--dcn-codec", default=None,
                    choices=("minmax_uint8", "f32", "onebit_ef", "topk"),
                    help="wire codec of the shaped DCN tier (default: "
                         "BAGUA_SCALE_DCN_CODEC)")
    ap.add_argument("--out", default=os.path.join(_REPO, "BENCH_SCALE.json"))
    args = ap.parse_args(argv)
    args.shape = _env.get_scale_shape() if args.shape is None else args.shape
    args.seed = _env.get_scale_seed() if args.seed is None else args.seed
    if args.dcn_codec is None:
        args.dcn_codec = _env.get_scale_dcn_codec()
    if args.ranks is None:
        args.ranks = _env.get_scale_ranks()
    else:
        args.ranks = [int(p) for p in str(args.ranks).split(",") if p.strip()]
    if args.smoke:
        return run_smoke(args)
    if len(args.ranks) < 3:
        ap.error(f"need >= 3 world sizes for the sweep, got {args.ranks}")
    return run_full(args)


if __name__ == "__main__":
    sys.exit(main())
