"""Elastic membership drill (`WATCHDOG_DRILL`-style): kill a node, watch
the world resize, let it rejoin, watch the world grow back.

Two launchers form a ``--nnodes 1:2`` elastic job on CPU:

1. both nodes train at world size 2;
2. node 1's WHOLE process group is SIGKILLed (launcher + worker — the
   "permanently lost node" the fixed-size restart path could never survive);
3. node 0's coordinator expires node 1's lease, the gang regroups at world
   size 1 within one join window and resumes from the checkpoint;
4. node 1 is relaunched, registers as a standby, the coordinator forces a
   coordinated resize at the attempt boundary, and the job finishes at
   world size 2 again.

Membership transitions and counters come from the launcher's telemetry
dump (``BAGUA_ELASTIC_TELEMETRY_OUT``); the verdict is written to
``ELASTIC_DRILL.json``.

Usage: python scripts/elastic_drill.py
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bagua_tpu.podsim.util import reserve_port as _free_port  # noqa: E402


def _wait_for(path: str, needle: str, timeout_s: float) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(path) and needle in open(path).read():
            return True
        time.sleep(0.3)
    return False


def main():
    tmp = tempfile.mkdtemp(prefix="elastic_drill_")
    master_port, coord_port = _free_port(), _free_port()
    env = dict(os.environ)
    env["BAGUA_TEST_OUT"] = tmp
    env["BAGUA_TEST_STEPS"] = "45"
    env["BAGUA_TEST_STEP_DELAY"] = "0.4"
    env["BAGUA_COMM_TIMEOUT_S"] = "60"  # backstop; the lease should win
    env.pop("BAGUA_SERVICE_PORT", None)

    logs = {r: os.path.join(tmp, f"node{r}.log") for r in (0, 1)}

    def launch(node_id: int):
        e = dict(env)
        e["BAGUA_ELASTIC_TELEMETRY_OUT"] = os.path.join(
            tmp, f"telemetry_node{node_id}.json")
        cmd = [
            sys.executable, "-m", "bagua_tpu.distributed.run",
            "--nnodes", "1:2", "--node_rank", str(node_id),
            "--nproc_per_node", "1",
            "--simulate_cpu_devices", "1",
            "--master_port", str(master_port),
            "--restart_coordinator_port", str(coord_port),
            "--bagua_service_port", "-1",
            "--max_restarts", "3",
            "--join_window", "8",
            "--lease_ttl", "5",
            "--monitor_interval", "0.3",
            os.path.join(REPO, "tests", "workers", "elastic_worker.py"),
        ]
        # own session: SIGKILLing the group takes launcher AND worker down,
        # like losing the host
        return subprocess.Popen(
            cmd, cwd=REPO, env=e, stdout=open(logs[node_id], "w"),
            stderr=subprocess.STDOUT, start_new_session=True,
        )

    t0 = time.time()
    checks = {}
    p0 = launch(0)
    time.sleep(1.0)
    p1 = launch(1)

    try:
        checks["trained_at_world_2"] = _wait_for(
            logs[0], "loss", 180) and _wait_for(logs[0], "world 2", 60)

        print("# killing node 1's process group", flush=True)
        os.killpg(p1.pid, signal.SIGKILL)
        p1.wait()

        checks["lease_expired_detected"] = _wait_for(
            logs[0], "lease_expired", 120)
        checks["resumed_at_world_1"] = _wait_for(
            logs[0], "resumed from checkpoint step", 120
        ) and _wait_for(logs[0], "world 1", 120)

        print("# relaunching node 1 (standby rejoin)", flush=True)
        p1 = launch(1)
        checks["resize_on_rejoin"] = _wait_for(logs[0], "resize", 120)
        checks["resumed_at_world_2"] = _wait_for(
            logs[1], "world 2", 180)

        rc0 = p0.wait(timeout=300)
        rc1 = p1.wait(timeout=120)
        checks["exit_codes"] = [rc0, rc1]
        checks["completed"] = (
            rc0 == 0 and rc1 == 0
            and "final_loss" in open(logs[0]).read()
        )
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass

    telemetry = {}
    tpath = os.path.join(tmp, "telemetry_node0.json")
    if os.path.exists(tpath):
        telemetry = json.load(open(tpath))
    counters = telemetry.get("counters", {})
    transitions = telemetry.get("transitions", [])
    world_sizes = [t["nnodes"] for t in transitions]
    checks["membership_counters"] = counters
    checks["world_size_transitions"] = world_sizes
    checks["counters_show_lease_expiry"] = counters.get(
        "elastic/lease_expired", 0) >= 1
    checks["counters_show_resize"] = counters.get("elastic/resizes", 0) >= 1
    checks["world_shrank_and_regrew"] = (
        2 in world_sizes and 1 in world_sizes
        and world_sizes and world_sizes[-1] == 2
    )
    checks["wall_s"] = round(time.time() - t0, 1)
    checks["log_dir"] = tmp
    checks["ok"] = all(
        v for k, v in checks.items()
        if k not in ("exit_codes", "wall_s", "log_dir",
                     "membership_counters", "world_size_transitions")
    )
    print(json.dumps(checks, indent=1))
    with open(os.path.join(REPO, "ELASTIC_DRILL.json"), "w") as f:
        json.dump(checks, f, indent=1)
    sys.exit(0 if checks["ok"] else 1)


if __name__ == "__main__":
    main()
