#!/usr/bin/env python
"""Coordinator-failover proof drill: kill the real coordinator process
mid-training and prove the fleet survives (FAILOVER_DRILL.json).

Every scenario runs the REAL cross-process stack: replicated restart
store (primary + follower :class:`TCPStoreServer` processes, op-log
replication, generation fence), a killable coordinator process
(:mod:`bagua_tpu.podsim.coordinator`) renewing the ``coord/lease``
leadership lease, a standby coordinator process watching it, and real
worker processes (:mod:`bagua_tpu.podsim.worker`) whose membership,
heartbeats and shaped collectives all ride a
:class:`~bagua_tpu.elastic.failover.FailoverStore` over the replica
group.  The fault matrix:

* **coordinator_failover** — SIGKILL the primary coordinator (which also
  hosts the primary store) mid-training at ``--world`` ranks.  The
  standby must promote within the member lease TTL, ZERO healthy workers
  may restart (same pids, same epoch, no stop event), and the promoted
  coordinator's status must prove the autopilot policy state and the
  historian trend rings RESUMED from the replicated store, not reset.
* **partition_fence** — SIGSTOP the primary (a partition, not a death);
  after the standby takes over, SIGCONT it.  The thawed ex-primary's
  late writes bounce off the generation fence (its replication links get
  ``ACK_FENCED``), it demotes itself and exits ``5``; the lease stays
  with the standby.  This is the double-primary row of the failure
  matrix.
* **store_flake** — workers run with an armed ``store.failover`` fault
  plan: injected endpoint failures walk their clients down the replica
  list mid-epoch; the fleet still reaches every verdict.
* **heartbeat_loss** — SIGSTOP one worker past the lease TTL: the
  coordinator (over the replicated store) expires it, survivors regroup
  at n-1, the thawed worker is fenced out.

Usage::

    python scripts/failover_drill.py           # full matrix at 32 ranks,
                                               # writes FAILOVER_DRILL.json
    python scripts/failover_drill.py --smoke   # 4-rank kill scenario (CI)
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __package__ in (None, ""):  # import-light shim: no jax in the drill
    import importlib.util

    sys.path.insert(0, _REPO)
    _spec = importlib.util.spec_from_loader(
        "bagua_tpu", loader=None, is_package=True)
    _pkg = importlib.util.module_from_spec(_spec)
    _pkg.__path__ = [os.path.join(_REPO, "bagua_tpu")]
    sys.modules["bagua_tpu"] = _pkg

import argparse  # noqa: E402
import json  # noqa: E402
import logging  # noqa: E402
import signal  # noqa: E402
import socket  # noqa: E402
import subprocess  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

from bagua_tpu.elastic.failover import (  # noqa: E402
    FailoverStore,
    read_coord_lease,
)
from bagua_tpu.elastic.membership import MembershipClient  # noqa: E402
from bagua_tpu.podsim.coordinator import STATUS_KEY  # noqa: E402
from bagua_tpu.podsim.orchestrator import (  # noqa: E402
    COORDINATOR_PATH,
    worker_argv,
)

logger = logging.getLogger("failover_drill")

SCHEMA = "bagua-failover-drill-v1"


def _free_ports(n):
    """Reserve n distinct loopback ports (bind-then-close; the drill
    respawns servers on them immediately, so collisions are unlikely on a
    CI host and a collision fails loudly at server bind)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait(pred, timeout_s, poll_s=0.2, what="condition"):
    """Poll ``pred`` until truthy; returns its value.  Raises on timeout —
    a drill that can't observe its precondition must fail loudly."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            value = pred()
        except ConnectionError:
            value = None
        if value:
            return value
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out after {timeout_s:.0f}s "
                               f"waiting for {what}")
        time.sleep(poll_s)


class Fleet:
    """One drill fleet: coordinator processes (primary + standbys, each
    hosting a store replica), worker processes, and an observer client."""

    def __init__(self, base, name, world, *, standbys=1, steps=0,
                 vec_elems=2048, slice_size=2, lease_ttl=3.0,
                 coord_ttl=1.5, join_window=20.0, timeout=90.0,
                 worker_env=None):
        self.dir = os.path.join(base, name)
        os.makedirs(self.dir, exist_ok=True)
        self.world = world
        self.lease_ttl = lease_ttl
        self.coord_ttl = coord_ttl
        ports = _free_ports(1 + standbys)
        self.endpoints = [("127.0.0.1", p) for p in ports]
        self.ep_str = ",".join(f"{h}:{p}" for h, p in self.endpoints)
        self.coords = {}
        for cid in range(1 + standbys):
            self.coords[cid] = self._spawn(f"coord{cid}", [
                sys.executable, COORDINATOR_PATH,
                "--store-endpoints", self.ep_str,
                "--coord-id", str(cid), "--world", str(world),
                "--min-nnodes", "1", "--join-window", str(join_window),
                "--timeout", str(timeout),
                "--lease-ttl", str(lease_ttl),
                "--coord-lease-ttl", str(coord_ttl),
            ])
        self.workers = {}
        for nid in range(world):
            self.workers[nid] = self._spawn(f"node{nid}", worker_argv(
                "127.0.0.1", ports[0], nid, world, steps=steps,
                vec_elems=vec_elems, slice_size=slice_size,
                timeout_s=timeout, store_endpoints=self.ep_str,
            ), env=worker_env)
        self.store = FailoverStore(self.endpoints, connect_timeout_s=60.0)
        self.client = MembershipClient(self.store, 0, world)

    def _spawn(self, name, argv, env=None):
        full_env = dict(os.environ)
        full_env.update(env or {})
        log = open(os.path.join(self.dir, f"{name}.log"), "ab")
        try:
            return subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True, env=full_env,
            )
        finally:
            log.close()

    # ---- observation ---------------------------------------------------

    def lease(self):
        return read_coord_lease(self.store)

    def status(self):
        raw = self.store.get(STATUS_KEY)
        return json.loads(raw) if raw else None

    def world_spec(self, epoch):
        return self.client.read_world(epoch)

    def ok_count(self, epoch, members):
        vals = self.store.mget(
            [f"podsim/{epoch}/ok/{n}" for n in members])
        return sum(1 for v in vals if v is not None)

    def workers_alive(self):
        return sorted(n for n, p in self.workers.items()
                      if p.poll() is None)

    # ---- scenario primitives -------------------------------------------

    def kill_coord(self, cid):
        self.coords[cid].kill()
        self.coords[cid].wait(timeout=10)

    def pause(self, proc):
        os.kill(proc.pid, signal.SIGSTOP)

    def resume(self, proc):
        os.kill(proc.pid, signal.SIGCONT)

    # ---- teardown ------------------------------------------------------

    def halt_and_reap(self, timeout_s=30.0):
        """Publish the halt verdict and reap everything; returns
        ``{"workers": {nid: code}, "coords": {cid: code}}`` (None = had
        to be killed)."""
        try:
            self.client.publish_halt(0, "drill complete")
        except ConnectionError:
            pass
        codes = {"workers": {}, "coords": {}}
        deadline = time.monotonic() + timeout_s
        for group, procs in (("workers", self.workers),
                             ("coords", self.coords)):
            for pid, proc in sorted(procs.items()):
                try:
                    codes[group][pid] = proc.wait(
                        timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
                    codes[group][pid] = None
        return codes

    def shutdown(self):
        for procs in (self.workers, self.coords):
            for proc in procs.values():
                if proc.poll() is None:
                    # a SIGSTOPped process ignores SIGKILL until resumed
                    try:
                        os.kill(proc.pid, signal.SIGCONT)
                    except OSError:
                        pass
                    proc.kill()
        for procs in (self.workers, self.coords):
            for proc in procs.values():
                try:
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
        self.store.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def coordinator_failover(base, world, *, steps, vec_elems=1024,
                         slice_size=8):
    """SIGKILL the primary coordinator mid-training; the standby promotes,
    zero healthy workers restart, autopilot+historian state resumes."""
    t0 = time.monotonic()
    with Fleet(base, "coordinator_failover", world, standbys=1,
               steps=steps, vec_elems=vec_elems,
               slice_size=slice_size) as fleet:
        spec = _wait(lambda: fleet.world_spec(0), 60, what="epoch 0 world")
        members = sorted(spec.ranks)
        # let the primary's monitor run long enough to persist autopilot
        # policy state + historian rings into the replicated store — the
        # state the takeover must prove it resumed
        pre = _wait(
            lambda: (lambda s: s if s and s["ticks"] >= 8 else None)(
                fleet.status()),
            60, what="primary coordinator status (>=8 ticks)")
        pre_lease = fleet.lease()
        pre_alive = fleet.workers_alive()
        pre_pids = {n: p.pid for n, p in fleet.workers.items()}

        t_kill = time.monotonic()
        fleet.kill_coord(0)  # SIGKILL: primary store AND coordinator die
        lease = _wait(
            lambda: (lambda le: le if le and le.get("node") == 1
                     and le.get("gen", 0) >= 1 else None)(fleet.lease()),
            fleet.lease_ttl * 4 + 15, what="standby lease claim")
        takeover_s = time.monotonic() - t_kill
        post = _wait(
            lambda: (lambda s: s if s and s["role"] == "promoted"
                     else None)(fleet.status()),
            30, what="promoted coordinator status")

        # the training epoch must be undisturbed: same epoch, no stop
        # event, every pre-kill worker process still the SAME pid
        ok_all = _wait(
            lambda: fleet.ok_count(spec.epoch, members) == len(members),
            90, what="all epoch verdicts after takeover")
        stop = fleet.client.read_stop(spec.epoch)
        checks = {
            "boot_lease_was_primary": bool(pre_lease
                                           and pre_lease["node"] == 0),
            "promoted_within_member_ttl": takeover_s <= fleet.lease_ttl,
            "generation_advanced": post["generation"] >= 1,
            "epoch_unchanged": post["epoch"] == spec.epoch,
            "no_stop_event": stop is None,
            "zero_worker_restarts": (
                fleet.workers_alive() == pre_alive == members
                and {n: p.pid for n, p in fleet.workers.items()}
                == pre_pids),
            "autopilot_state_resumed": post["autopilot_resumed"] is True,
            "historian_rings_resumed": post["historian_loaded_series"] >= 1,
            "autopilot_not_reset": (post["autopilot_actions_taken"]
                                    >= pre["autopilot_actions_taken"]),
            "all_verdicts_after_takeover": bool(ok_all),
        }
        codes = fleet.halt_and_reap()
        checks["workers_exit_clean"] = all(
            c == 0 for c in codes["workers"].values())
        checks["standby_exit_clean"] = codes["coords"][1] == 0
    return {
        "world": world, "steps": steps, "takeover_s": round(takeover_s, 2),
        "member_lease_ttl_s": fleet.lease_ttl,
        "coord_lease_ttl_s": fleet.coord_ttl,
        "pre_status": pre, "post_status": post,
        "exit_codes": codes, "checks": checks,
        "wall_s": round(time.monotonic() - t0, 1),
        "ok": all(checks.values()),
    }


def partition_fence(base):
    """SIGSTOP the primary (partition), let the standby take over, then
    SIGCONT: the generation fence rejects the thawed ex-primary's late
    writes and it exits demoted — no double primary."""
    from bagua_tpu.podsim.coordinator import EXIT_DEMOTED

    t0 = time.monotonic()
    with Fleet(base, "partition_fence", 4, standbys=1, steps=0) as fleet:
        spec = _wait(lambda: fleet.world_spec(0), 60, what="epoch 0 world")
        members = sorted(spec.ranks)
        _wait(lambda: fleet.ok_count(0, members) == len(members),
              60, what="epoch 0 verdicts")
        _wait(lambda: (s := fleet.status()) and s["ticks"] >= 4,
              30, what="primary status")
        fleet.pause(fleet.coords[0])
        lease = _wait(
            lambda: (lambda le: le if le and le.get("node") == 1
                     and le.get("gen", 0) >= 1 else None)(fleet.lease()),
            fleet.lease_ttl * 4 + 15, what="standby takeover")
        gen_after_takeover = lease["gen"]
        fleet.resume(fleet.coords[0])
        # the thawed ex-primary replicates its buffered writes, gets
        # ACK_FENCED, demotes its server and exits with the demoted code
        _wait(lambda: fleet.coords[0].poll() is not None, 30,
              what="ex-primary exit")
        ex_code = fleet.coords[0].poll()
        time.sleep(1.0)  # give a hypothetical double-primary time to act
        lease_now = fleet.lease()
        post = fleet.status()
        checks = {
            "standby_promoted": gen_after_takeover >= 1,
            "ex_primary_demoted_exit": ex_code == EXIT_DEMOTED,
            "lease_stays_with_standby": bool(lease_now
                                             and lease_now["node"] == 1),
            "promoted_still_acting": bool(post
                                          and post["role"] == "promoted"),
            "no_stop_event": fleet.client.read_stop(spec.epoch) is None,
            "workers_all_alive": fleet.workers_alive() == members,
        }
        codes = fleet.halt_and_reap()
        checks["workers_exit_clean"] = all(
            c == 0 for c in codes["workers"].values())
    return {
        "world": 4, "ex_primary_exit": ex_code,
        "exit_codes": codes, "checks": checks,
        "wall_s": round(time.monotonic() - t0, 1),
        "ok": all(checks.values()),
    }


def store_flake(base):
    """Armed ``store.failover`` faults in every worker: injected endpoint
    failures force their clients across the replica list mid-epoch; the
    control plane never notices."""
    t0 = time.monotonic()
    plan = json.dumps([{"point": "store.failover", "op": 6, "count": 2}])
    with Fleet(base, "store_flake", 4, standbys=1, steps=1,
               worker_env={"BAGUA_FAULT_PLAN": plan}) as fleet:
        spec = _wait(lambda: fleet.world_spec(0), 60, what="epoch 0 world")
        members = sorted(spec.ranks)
        _wait(lambda: fleet.ok_count(0, members) == len(members),
              90, what="verdicts under armed store faults")
        lease = fleet.lease()
        checks = {
            "all_verdicts_under_faults": True,
            "primary_kept_leadership": bool(lease and lease["node"] == 0),
            "no_stop_event": fleet.client.read_stop(0) is None,
        }
        codes = fleet.halt_and_reap()
        checks["workers_exit_clean"] = all(
            c == 0 for c in codes["workers"].values())
    return {
        "world": 4, "fault_plan": json.loads(plan),
        "exit_codes": codes, "checks": checks,
        "wall_s": round(time.monotonic() - t0, 1),
        "ok": all(checks.values()),
    }


def heartbeat_loss(base):
    """SIGSTOP one worker past the lease TTL: the coordinator (over the
    replicated store) expires its lease and the survivors regroup at
    n-1 — member-failure handling is intact under replication."""
    t0 = time.monotonic()
    with Fleet(base, "heartbeat_loss", 4, standbys=1, steps=0,
               join_window=4.0) as fleet:
        spec = _wait(lambda: fleet.world_spec(0), 60, what="epoch 0 world")
        members = sorted(spec.ranks)
        _wait(lambda: fleet.ok_count(0, members) == len(members),
              60, what="epoch 0 verdicts")
        fleet.pause(fleet.workers[3])
        stop = _wait(lambda: fleet.client.read_stop(0),
                     fleet.lease_ttl * 4 + 20, what="lease-expiry stop")
        spec1 = _wait(lambda: fleet.world_spec(1), 60,
                      what="regrouped epoch 1 world")
        fleet.resume(fleet.workers[3])
        checks = {
            "stop_is_lease_expired": stop.get("kind") == "lease_expired",
            "stopped_node_named": 3 in (stop.get("nodes") or []),
            "regrouped_at_n_minus_1": spec1.nnodes == 3
            and 3 not in spec1.ranks,
        }
        _wait(lambda: fleet.ok_count(1, sorted(spec1.ranks))
              == spec1.nnodes, 60, what="epoch 1 verdicts")
        checks["survivors_all_ok"] = True
        codes = fleet.halt_and_reap()
        # the thawed worker sees itself fenced (4) or halts cleanly (0),
        # depending on which it reads first — both are orderly exits
        checks["survivor_exits_clean"] = all(
            c == 0 for n, c in codes["workers"].items() if n != 3)
        checks["expired_worker_orderly_exit"] = (
            codes["workers"][3] in (0, 4))
    return {
        "world": 4, "stop": stop, "regrouped_nnodes": spec1.nnodes,
        "exit_codes": codes, "checks": checks,
        "wall_s": round(time.monotonic() - t0, 1),
        "ok": all(checks.values()),
    }


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def run_smoke(args):
    base = tempfile.mkdtemp(prefix="failover_smoke_")
    result = coordinator_failover(base, 4, steps=1, vec_elems=4096,
                                  slice_size=2)
    verdict = {"drill": "failover-smoke", "world": 4,
               "takeover_s": result["takeover_s"],
               "checks": result["checks"], "log_dir": base,
               "ok": result["ok"]}
    print(json.dumps(verdict, indent=1, sort_keys=True))
    return 0 if verdict["ok"] else 1


def run_full(args):
    t0 = time.monotonic()
    base = tempfile.mkdtemp(prefix="failover_drill_")
    scenarios = {}
    logger.info("=== coordinator_failover (SIGKILL at %d ranks) ===",
                args.world)
    scenarios["coordinator_failover"] = coordinator_failover(
        base, args.world, steps=args.steps)
    logger.info("=== partition_fence (SIGSTOP/SIGCONT double-primary) ===")
    scenarios["partition_fence"] = partition_fence(base)
    logger.info("=== store_flake (armed store.failover fault plan) ===")
    scenarios["store_flake"] = store_flake(base)
    logger.info("=== heartbeat_loss (member lease expiry) ===")
    scenarios["heartbeat_loss"] = heartbeat_loss(base)

    all_checks = {
        f"{scen}/{name}": ok
        for scen, result in scenarios.items()
        for name, ok in result["checks"].items()
    }
    record = {
        "schema": SCHEMA,
        "drill": "failover",
        "platform": "cpu-sim",
        "host_cores": os.cpu_count(),
        "world": args.world,
        "takeover_s": scenarios["coordinator_failover"]["takeover_s"],
        "scenarios": scenarios,
        "checks": all_checks,
        "log_dir": base,
        "wall_s": round(time.monotonic() - t0, 1),
        "ok": all(all_checks.values()),
    }
    out = os.path.abspath(args.out)
    with open(out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({k: record[k] for k in
                      ("schema", "checks", "takeover_s", "wall_s", "ok")},
                     indent=1, sort_keys=True))
    print(f"wrote {out}")
    return 0 if record["ok"] else 1


def main(argv=None):
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="4-rank SIGKILL scenario only (the CI gate)")
    ap.add_argument("--world", type=int, default=32,
                    help="ranks for the coordinator_failover scenario")
    ap.add_argument("--steps", type=int, default=1,
                    help="collective steps per epoch in the kill scenario "
                         "(training runs THROUGH the takeover)")
    ap.add_argument("--out",
                    default=os.path.join(_REPO, "FAILOVER_DRILL.json"))
    args = ap.parse_args(argv)
    if args.smoke:
        return run_smoke(args)
    return run_full(args)


if __name__ == "__main__":
    sys.exit(main())
