"""Golden-model equivalence for decentralized SGD.

Mirrors /root/reference/tests/torch_api/test_decentralized.py: a pure
reimplementation of the same math (per-rank host loop, same peer formula)
compared elementwise, plus the ``all``-mode invariant that all ranks end up
identical (:290-315)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu import BaguaTrainer
from bagua_tpu.algorithms import DecentralizedAlgorithm, shift_one_peer
from bagua_tpu.models import MLP

N = 8
DIM, NCLASS = 10, 5
LR = 0.05


def _setup(seed=0):
    model = MLP(features=(12, NCLASS))
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, DIM)))["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"]).mean()

    return model, params, loss_fn


def _batches(steps, seed=0):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(DIM, NCLASS))
    out = []
    for _ in range(steps):
        x = rng.normal(size=(N * 4, DIM)).astype(np.float32)
        y = np.argmax(x @ W, 1).astype(np.int32)
        out.append({"x": x, "y": y})
    return out


def test_shift_one_peer_formula_is_symmetric_pairing():
    for n in (4, 8, 16):
        for step in range(2 * n):
            peers = [shift_one_peer(r, n, step) for r in range(n)]
            for r in range(n):
                assert peers[peers[r]] == r, (n, step, peers)
            assert sorted(peers) == list(range(n))


@pytest.mark.parametrize("mode", ["all", "shift_one"])
def test_matches_per_rank_golden(mode):
    model, params, loss_fn = _setup()
    steps = 4
    batches = _batches(steps)

    algo = DecentralizedAlgorithm(hierarchical=False, peer_selection_mode=mode)
    # leaf layout: this golden reads PER-RANK leaf weights straight off the
    # stacked state; flat-vs-leaf step equality is pinned in
    # tests/test_flat_resident.py
    trainer = BaguaTrainer(loss_fn, optax.sgd(LR), algo, bucket_bytes=10 ** 9,
                           flat_resident="off")
    st = trainer.init(params)
    for b in batches:
        st, _ = trainer.train_step(st, {"x": jnp.asarray(b["x"]), "y": jnp.asarray(b["y"])})

    # golden: explicit per-rank host loop with the same math
    grad_fn = jax.jit(jax.grad(loss_fn))
    ranks = [params for _ in range(N)]
    per = len(batches[0]["x"]) // N
    for step, b in enumerate(batches):
        grads = []
        for r in range(N):
            shard = {
                "x": jnp.asarray(b["x"][r * per:(r + 1) * per]),
                "y": jnp.asarray(b["y"][r * per:(r + 1) * per]),
            }
            grads.append(grad_fn(ranks[r], shard))
        if mode == "all":
            mean = jax.tree.map(lambda *xs: sum(xs) / N, *ranks)
            averaged = [mean] * N
        else:
            averaged = [None] * N
            for r in range(N):
                p = shift_one_peer(r, N, step)
                averaged[r] = jax.tree.map(lambda a, b_: (a + b_) * 0.5, ranks[r], ranks[p])
        ranks = [
            jax.tree.map(lambda p_, g: p_ - LR * g, averaged[r], grads[r])
            for r in range(N)
        ]

    got = np.stack([np.concatenate([np.ravel(l) for l in jax.tree.leaves(
        jax.tree.map(lambda x: x[r], st.params))]) for r in range(N)])
    want = np.stack([np.concatenate([np.ravel(np.asarray(l)) for l in jax.tree.leaves(ranks[r])])
                     for r in range(N)])
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-5)


def test_all_mode_ranks_identical():
    """Exact peer-equality at the communication point (reference
    test_decentralized.py:290-315): in "all" mode, the post-communication
    weights every rank holds must be IDENTICAL — pmean returns the same
    reduction result on all ranks.  track_peer_weights exposes those weights
    (the analog of the reference's peer_weight bucket tensor)."""
    model, params, loss_fn = _setup(1)
    trainer = BaguaTrainer(
        loss_fn, optax.sgd(LR),
        DecentralizedAlgorithm(hierarchical=False, peer_selection_mode="all",
                               track_peer_weights=True),
    )
    st = trainer.init(params)
    for b in _batches(3, seed=1):
        st, _ = trainer.train_step(st, {"x": jnp.asarray(b["x"]), "y": jnp.asarray(b["y"])})
    for flat in st.algo_state["peer_weights"]:
        arr = np.asarray(flat)  # [nranks, bucket_elems]
        for r in range(1, arr.shape[0]):
            np.testing.assert_array_equal(arr[r], arr[0])
    # and the post-step weights differ from peer weights only by one local
    # SGD step (each rank applied its own grads to the common average)
    leaves = jax.tree.leaves(st.params)
    for leaf in leaves:
        arr = np.asarray(leaf)
        assert np.abs(arr - arr.mean(axis=0, keepdims=True)).max() < LR * 50


def test_hierarchical_single_host_equals_all_average():
    model, params, loss_fn = _setup(2)
    batches = _batches(3, seed=2)

    outs = []
    for algo in [
        DecentralizedAlgorithm(hierarchical=True, peer_selection_mode="all"),
        DecentralizedAlgorithm(hierarchical=False, peer_selection_mode="all"),
    ]:
        trainer = BaguaTrainer(loss_fn, optax.sgd(LR), algo)
        st = trainer.init(params)
        for b in batches:
            st, _ = trainer.train_step(st, {"x": jnp.asarray(b["x"]), "y": jnp.asarray(b["y"])})
        outs.append(st.params)

    for a, b in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(outs[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_communication_interval():
    model, params, loss_fn = _setup(3)
    algo = DecentralizedAlgorithm(
        hierarchical=False, peer_selection_mode="all", communication_interval=2
    )
    trainer = BaguaTrainer(loss_fn, optax.sgd(LR), algo)
    st = trainer.init(params)
    for b in _batches(4, seed=3):
        st, loss = trainer.train_step(st, {"x": jnp.asarray(b["x"]), "y": jnp.asarray(b["y"])})
    assert np.isfinite(float(loss))


def test_track_peer_weights_survives_skip_steps():
    """With communication_interval > 1, non-communication steps must KEEP the
    last communicated peer weights instead of overwriting them with local
    (divergent) weights."""
    model, params, loss_fn = _setup(1)
    trainer = BaguaTrainer(
        loss_fn, optax.sgd(LR),
        DecentralizedAlgorithm(hierarchical=False, peer_selection_mode="all",
                               communication_interval=2,
                               track_peer_weights=True),
    )
    st = trainer.init(params)
    # 3 steps: comm at step 0 and 2; step 1 skips — peer_weights must stay
    # rank-identical after every step
    for b in _batches(3, seed=3):
        st, _ = trainer.train_step(st, {"x": jnp.asarray(b["x"]), "y": jnp.asarray(b["y"])})
        for flat in st.algo_state["peer_weights"]:
            arr = np.asarray(flat)
            for r in range(1, arr.shape[0]):
                np.testing.assert_array_equal(arr[r], arr[0])
