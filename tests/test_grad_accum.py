"""Gradient accumulation (``BaguaTrainer(accum_steps=k)``).

Equivalence invariant: with a mean-reduced loss and equal microbatch sizes,
accumulating k microbatches must produce exactly the step a single pass over
the full batch would have produced (mean of microbatch means == full-batch
mean), so the two trainings match elementwise — on top of any algorithm,
since accumulation runs before the algorithm stages.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu import BaguaTrainer
from bagua_tpu.algorithms import (
    GradientAllReduceAlgorithm,
    QAdamAlgorithm,
    ZeroOptimizerAlgorithm,
)
from bagua_tpu.models import MLP

N = 8
DIM = 12
NCLASS = 10


def _loss_fn(model):
    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()

    return loss_fn


def _data(steps, batch_rows, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(steps, batch_rows, DIM)).astype(np.float32)
    ys = rng.integers(0, NCLASS, size=(steps, batch_rows)).astype(np.int32)
    return xs, ys


def _train(trainer, params, xs, ys):
    state = trainer.init(params)
    losses = []
    for s in range(xs.shape[0]):
        state, loss = trainer.train_step(state, {"x": xs[s], "y": ys[s]})
        losses.append(float(loss))
    return state, losses


def _make(algo_factory, optimizer):
    return lambda accum: BaguaTrainer(
        _loss_fn(MODEL), optimizer, algo_factory(),
        bucket_bytes=256, accum_steps=accum,
    )


MODEL = MLP(features=(16, NCLASS))


@pytest.mark.parametrize(
    "algo_factory,optimizer,tol",
    [
        (GradientAllReduceAlgorithm, optax.sgd(0.1), 2e-5),
        (lambda: ZeroOptimizerAlgorithm(optax.adam(1e-2)), None, 2e-5),
        # QAdam crosses its warmup boundary mid-run; the compressed phase
        # quantizes momentum, where a 1-ulp input difference can flip a
        # quantization level — hence the looser tolerance
        (lambda: QAdamAlgorithm(warmup_steps=2, lr=1e-2), None, 1e-3),
    ],
    ids=["gradient_allreduce", "zero", "qadam"],
)
def test_accum_equals_full_batch(algo_factory, optimizer, tol):
    params = MODEL.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    accum = 4
    xs, ys = _data(steps=4, batch_rows=N * 2 * accum)

    make = _make(algo_factory, optimizer)
    t_full, t_acc = make(1), make(accum)
    st_full, losses_full = _train(t_full, params, xs, ys)
    st_acc, losses_acc = _train(t_acc, params, xs, ys)

    np.testing.assert_allclose(losses_acc, losses_full, rtol=1e-5, atol=1e-6)
    # compare via the leaf views: flat-resident raw state is plan-laid-out,
    # and the overlap readiness re-bucket gives the accum trainer its own plan
    for a, b in zip(jax.tree.leaves(t_acc.unstack_params(st_acc)),
                    jax.tree.leaves(t_full.unstack_params(st_full))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=tol, atol=tol)


def test_rejects_indivisible_batch():
    params = MODEL.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    trainer = BaguaTrainer(
        _loss_fn(MODEL), optax.sgd(0.1), GradientAllReduceAlgorithm(),
        accum_steps=3,
    )
    state = trainer.init(params)
    xs, ys = _data(steps=1, batch_rows=N * 4)  # 4 rows/rank, not divisible by 3
    with pytest.raises(ValueError, match="not divisible"):
        trainer.train_step(state, {"x": xs[0], "y": ys[0]})


def test_rejects_bad_accum_steps():
    with pytest.raises(ValueError):
        BaguaTrainer(
            _loss_fn(MODEL), optax.sgd(0.1), GradientAllReduceAlgorithm(),
            accum_steps=0,
        )
