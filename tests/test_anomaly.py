"""Step-time anomaly detection (ISSUE 9): warmup grace, MAD robustness,
dump throttling, the straggler_suspect beacon payload, perf hints, the
coordinator-side straggler naming, and the trainer integration."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bagua_tpu import telemetry  # noqa: E402
from bagua_tpu.obs import anomaly as an  # noqa: E402
from bagua_tpu.obs import export as obs_export  # noqa: E402
from bagua_tpu.obs import recorder as obs_recorder  # noqa: E402


@pytest.fixture()
def clean_obs():
    obs_export.reset_local_summary()
    an.drain_perf_hints()
    yield
    obs_export.reset_local_summary()
    an.drain_perf_hints()


def _detector(**kw):
    kw.setdefault("window", 32)
    kw.setdefault("warmup", 6)
    kw.setdefault("threshold", 5.0)
    kw.setdefault("rank", 0)
    return an.StepAnomalyDetector(**kw)


def test_warmup_grace_no_flags(clean_obs):
    """Even a grotesque spike during warmup must not flag: compile steps
    and cold caches are not anomalies."""
    d = _detector(warmup=6)
    for i in range(5):
        assert d.observe(i, 5.0 if i == 2 else 0.01) is None
    assert list(d.suspects) == []


def test_detects_after_warmup_with_phase_breakdown(clean_obs):
    d = _detector()
    for i in range(10):
        assert d.observe(i, 0.010, {"dispatch": 0.009}) is None
    s = d.observe(10, 0.100, {"dispatch": 0.009, "collective": 0.090})
    assert s is not None
    assert s["dominant_phase"] == "collective"
    assert s["ratio"] == pytest.approx(10.0, rel=0.05)
    assert s["baseline_p50"] == pytest.approx(0.010, rel=0.01)
    assert set(s["phases"]) == {"dispatch", "collective", "optimizer",
                                "other"}
    assert s["rank"] == 0 and s["step"] == 10


def test_mad_robust_to_single_spike(clean_obs):
    """One historic spike must not inflate the baseline enough to mask the
    next one, nor to flag normal steps afterwards."""
    d = _detector(warmup=6)
    for i in range(8):
        d.observe(i, 0.010)
    assert d.observe(8, 0.200) is not None        # spike 1 flagged
    for i in range(9, 15):                        # normal steps stay quiet
        assert d.observe(i, 0.0105) is None
    assert d.observe(15, 0.200) is not None       # spike 2 STILL flagged


def test_steady_cadence_zero_mad_guard(clean_obs):
    """A perfectly steady host (MAD ~ 0) must not flag microsecond jitter:
    the min_ratio guard holds the floor."""
    d = _detector()
    for i in range(10):
        d.observe(i, 0.010)
    assert d.observe(10, 0.0115) is None          # +15% < min_ratio 1.3
    assert d.observe(11, 0.014) is not None       # +40% is real


def test_dump_throttling(clean_obs, tmp_path, monkeypatch):
    """Anomaly dumps are throttled: the first flags a flight record, a
    burst within the interval does not write per-anomaly."""
    from bagua_tpu.obs import spans as obs_spans

    monkeypatch.setenv("BAGUA_OBS_DUMP_DIR", str(tmp_path))
    obs_spans.set_enabled(True)
    try:
        d = _detector(dump_min_interval_s=60.0)
        for i in range(10):
            d.observe(i, 0.010)
        for i in range(10, 14):
            d.observe(i, 0.100)
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("flight_step_anomaly")]
        assert len(dumps) == 1
        rec = json.load(open(tmp_path / dumps[0]))
        assert obs_recorder.validate_flight_record(rec) == []
        assert rec["extra"]["straggler_suspect"]["step"] == 10
        assert len(d.suspects) == 4               # all flagged, one dumped
        assert telemetry.counters.get("obs/step_anomalies") >= 4
    finally:
        obs_spans.set_enabled(None)


def test_suspect_rides_beacon_payload(clean_obs, tmp_path, monkeypatch):
    """Beacon payload shape: the latest suspect lands in the per-rank obs
    summary, survives the beacon file round trip, and the fence scalar
    ignores it."""
    from bagua_tpu.elastic.membership import (
        file_health_source,
        health_event_count,
        local_health_snapshot,
        write_health_beacon,
    )

    for step in range(1, 4):
        obs_export.note_step(step, 0.01)
    d = _detector(warmup=2)
    for i in range(4):
        d.observe(i, 0.010)
    d.observe(4, 0.100, {"collective": 0.09})
    summary = obs_export.local_obs_summary()
    suspect = summary["straggler_suspect"]
    assert suspect["dominant_phase"] == "collective"
    assert suspect["step"] == 4
    path = str(tmp_path / "beacon.json")
    monkeypatch.setenv("BAGUA_ELASTIC_HEALTH_FILE", path)
    assert write_health_beacon() is True
    read = file_health_source(path)()
    assert read["obs"]["straggler_suspect"]["step"] == 4
    snap = local_health_snapshot()
    assert health_event_count(snap) == health_event_count(
        {k: v for k, v in snap.items() if k != "obs"})


def test_perf_hints_drain(clean_obs):
    d = _detector(warmup=2)
    for i in range(4):
        d.observe(i, 0.010)
    d.observe(4, 0.100)
    hints = an.drain_perf_hints()
    assert hints and hints[-1]["kind"] == "step_time_anomaly"
    assert hints[-1]["step"] == 4
    assert an.drain_perf_hints() == []            # drained
    assert an.peek_perf_hints() == []


def test_autotune_service_remeasures_hinted_window(clean_obs):
    """Service-side consumption: a sample window that carried perf hints
    is re-measured once instead of scored."""
    from bagua_tpu.service.autotune_service import AutotuneService

    svc = AutotuneService(world_size=1, autotune_level=1,
                          warmup_time_s=0.0,
                          sampling_confidence_time_s=0.0)
    svc._task("m")  # materialize
    svc.register_tensors({"model_name": "m", "tensor_list": []})
    svc.report_metrics({"model_name": "m", "rank": 0, "speed": 100.0,
                        "perf_hints": [{"kind": "step_time_anomaly",
                                        "ratio": 9.0}]})
    task = svc._task("m")
    assert task.perf_hints and task.perf_hints[0]["reported_by"] == 0
    svc.ask_hyperparameters({"model_name": "m", "rank": 0, "train_iter": 1})
    before = task.n_samples
    # the hinted window was reset, not scored
    assert before == 0 and task.sample_retried is True
    # the retry window (no new hints) scores normally
    svc.ask_hyperparameters({"model_name": "m", "rank": 0, "train_iter": 2})
    assert task.n_samples == 1


def test_autotune_service_absorbs_warmup_hints(clean_obs):
    """Hints reported during the warmup period describe windows that are
    never scored — they must not burn the first sampling window's one
    re-measure."""
    from bagua_tpu.service.autotune_service import AutotuneService

    svc = AutotuneService(world_size=1, autotune_level=1,
                          warmup_time_s=3600.0,
                          sampling_confidence_time_s=0.0)
    svc.register_tensors({"model_name": "m", "tensor_list": []})
    svc.report_metrics({"model_name": "m", "rank": 0, "speed": 100.0,
                        "perf_hints": [{"kind": "step_time_anomaly",
                                        "ratio": 9.0}]})
    svc.ask_hyperparameters({"model_name": "m", "rank": 0, "train_iter": 1})
    task = svc._task("m")
    assert task.sample_hint_mark == task.perf_hints_total == 1
    svc.warmup_time_s = 0.0  # warmup ends; no new hints since
    svc.ask_hyperparameters({"model_name": "m", "rank": 0, "train_iter": 2})
    assert task.n_samples == 1 and task.sample_retried is False


def test_fleet_straggler_naming():
    """Coordinator half: dispatch-dominant suspects are stragglers,
    collective-dominant ones their victims."""
    def summary(rank, phase, ratio):
        return {"rank": rank, "step": 50,
                "straggler_suspect": {"rank": rank, "step": 50,
                                      "ratio": ratio,
                                      "dominant_phase": phase}}

    fleet = {"schema": "bagua-obs-fleet-v1", "ranks": {
        "0": {"health": {}, "obs": {"0": summary(0, "collective", 4.0)}},
        "1": {"health": {}, "obs": {"1": summary(1, "dispatch", 9.0),
                                    "2": {"rank": 2, "step": 50}}},
    }}
    out = an.fleet_straggler_suspects(fleet)
    assert [s["rank"] for s in out["stragglers"]] == [1]
    assert [s["rank"] for s in out["victims"]] == [0]


def test_trainer_flags_injected_straggle(clean_obs, monkeypatch):
    """End-to-end on the 8-dev cpu-sim mesh: a gated step.straggle window
    after a clean baseline is flagged collective-dominant by the
    trainer-integrated detector (the chaos drill runs the larger version
    with the fleet plumbing)."""
    import optax

    import bench
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.faults.inject import FaultSpec, fault_scope
    from bagua_tpu.parallel.mesh import build_mesh

    monkeypatch.setenv("BAGUA_OBS_ANOMALY_WARMUP", "4")
    loss_fn, params, batch = bench.golden_task()
    t = BaguaTrainer(loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
                     mesh=build_mesh({"dp": 8}), autotune=False)
    assert t.anomaly_detector is not None
    s = t.init(params)
    b = t.shard_batch(batch)
    for _ in range(8):
        s, _ = t.train_step(s, b)
    start = t._step_counter
    with fault_scope(FaultSpec("step.straggle", rank=1, count=-1,
                               base_ms=20.0, factor=10.0)):
        for _ in range(4):
            s, _ = t.train_step(s, b)
    # drain the async dispatch queue before the observe step: its cadence
    # sample must measure the step, not 12 queued steps' device backlog
    import jax

    jax.block_until_ready(s.params)
    s, _ = t.train_step(s, b)  # observe the last straggled window
    flagged = [sp for sp in t.anomaly_detector.suspects
               if sp["step"] >= start]
    assert flagged, list(t.anomaly_detector.suspects)
    assert flagged[-1]["dominant_phase"] == "collective"
    # measured_step_dt stays an honest dilation base (stall subtracted)
    assert t.measured_step_dt() < 0.1


def test_anomaly_off_knob(monkeypatch):
    import optax

    import bench
    from bagua_tpu.algorithms import GradientAllReduceAlgorithm
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.parallel.mesh import build_mesh

    monkeypatch.setenv("BAGUA_OBS_ANOMALY", "off")
    loss_fn, params, _ = bench.golden_task()
    t = BaguaTrainer(loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
                     mesh=build_mesh({"dp": 8}), autotune=False)
    assert t.anomaly_detector is None
