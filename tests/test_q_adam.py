"""QAdam golden equivalence + phase-switch behavior (reference
q_adam.py:74-125: warmup Adam on averaged grads, then compressed momentum with
frozen second moment; need_reset at the warmup boundary)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu import BaguaTrainer
from bagua_tpu.algorithms import QAdamAlgorithm
from bagua_tpu.models import MLP

N = 8
DIM, NCLASS = 10, 5
LR, BETAS, EPS = 1e-2, (0.9, 0.999), 1e-8


def _setup(seed=0):
    model = MLP(features=(12, NCLASS))
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, DIM)))["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"]).mean()

    return params, loss_fn


def _batches(steps, seed=0):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(DIM, NCLASS))
    for _ in range(steps):
        x = rng.normal(size=(N * 8, DIM)).astype(np.float32)
        y = np.argmax(x @ W, 1).astype(np.int32)
        yield {"x": jnp.asarray(x), "y": jnp.asarray(y)}


def _golden_qadam_step(params, grads, m, v, step_id):
    """Reference QAdamOptimizer.step math (q_adam.py:76-100), warmup phase."""
    beta1, beta2 = BETAS
    m = jax.tree.map(lambda a, g: a * beta1 + (1 - beta1) * g, m, grads)
    v = jax.tree.map(lambda a, g: a * beta2 + (1 - beta2) * g * g, v, grads)
    b1 = 1 - beta1 ** step_id
    b2 = 1 - beta2 ** step_id
    params = jax.tree.map(
        lambda p, mm, vv: p - (LR / b1) * mm / (jnp.sqrt(vv) / math.sqrt(b2) + EPS),
        params, m, v,
    )
    return params, m, v


def test_warmup_matches_reference_adam_math():
    params, loss_fn = _setup()
    steps = 5
    trainer = BaguaTrainer(
        loss_fn, None,
        QAdamAlgorithm(warmup_steps=100, lr=LR, betas=BETAS, eps=EPS),
        bucket_bytes=512,
    )
    st = trainer.init(params)
    batches = list(_batches(steps))
    for b in batches:
        st, _ = trainer.train_step(st, b)

    # golden: full-batch grads (mean over the global batch) + reference math
    gp = params
    m = jax.tree.map(jnp.zeros_like, params)
    v = jax.tree.map(jnp.zeros_like, params)
    grad_fn = jax.jit(jax.grad(loss_fn))
    for i, b in enumerate(batches):
        grads = grad_fn(gp, b)
        gp, m, v = _golden_qadam_step(gp, grads, m, v, i + 1)

    # leaf view: flat-resident raw state holds bucket flats, not leaves
    for a, b_ in zip(jax.tree.leaves(trainer.unstack_params(st)),
                     jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5)


def test_phase_switch_and_convergence():
    params, loss_fn = _setup(1)
    algo = QAdamAlgorithm(warmup_steps=3, lr=LR, betas=BETAS, eps=EPS,
                          hierarchical=False)
    trainer = BaguaTrainer(loss_fn, None, algo, bucket_bytes=512)
    st = trainer.init(params)
    losses = []
    for b in _batches(12, seed=1):
        st, loss = trainer.train_step(st, b)
        losses.append(float(loss))
    assert algo._compressed, "phase switch did not happen"
    assert trainer._phase == 1
    assert all(np.isfinite(losses))
    assert min(losses[6:]) < losses[0], "no progress after phase switch"


def test_compressed_phase_tracks_uncompressed_on_identical_shards():
    """With identical data on every rank the compressed momentum average is
    just a quantize/dequantize round-trip; the trajectory must stay close to
    local (uncompressed) QAdam math."""
    params, loss_fn = _setup(2)
    algo = QAdamAlgorithm(warmup_steps=2, lr=LR, betas=BETAS, eps=EPS,
                          hierarchical=False)
    trainer = BaguaTrainer(loss_fn, None, algo, bucket_bytes=512)
    st = trainer.init(params)

    rng = np.random.default_rng(7)
    W = rng.normal(size=(DIM, NCLASS))
    x1 = rng.normal(size=(8, DIM)).astype(np.float32)
    y1 = np.argmax(x1 @ W, 1).astype(np.int32)
    batch = {"x": jnp.asarray(np.tile(x1, (N, 1))), "y": jnp.asarray(np.tile(y1, N))}

    beta1, beta2 = BETAS
    gp = params
    m = jax.tree.map(jnp.zeros_like, params)
    v = jax.tree.map(jnp.zeros_like, params)
    grad_fn = jax.jit(jax.grad(loss_fn))
    small = {"x": jnp.asarray(x1), "y": jnp.asarray(y1)}
    for i in range(4):
        st, _ = trainer.train_step(st, batch)
        grads = grad_fn(gp, small)
        m = jax.tree.map(lambda a, g: a * beta1 + (1 - beta1) * g, m, grads)
        if i < 2:  # warmup: v updates; afterwards frozen
            v = jax.tree.map(lambda a, g: a * beta2 + (1 - beta2) * g * g, v, grads)
        b1 = 1 - beta1 ** (i + 1)
        b2 = 1 - beta2 ** (i + 1)
        gp = jax.tree.map(
            lambda p, mm, vv: p - (LR / b1) * mm / (jnp.sqrt(vv) / math.sqrt(b2) + EPS),
            gp, m, v,
        )

    # where the frozen second moment is tiny, Adam's 1/sqrt(v) amplifies
    # quantization noise — bound the bulk tightly and the tail loosely
    diffs = np.concatenate([
        np.abs(np.asarray(a) - np.asarray(b_)).ravel()
        for a, b_ in zip(jax.tree.leaves(trainer.unstack_params(st)),
                         jax.tree.leaves(gp))
    ])
    assert np.percentile(diffs, 95) < 3e-2, np.percentile(diffs, 95)
    assert diffs.max() < 0.2, diffs.max()
