"""Profiler integration (SURVEY.md §5.1: jax.profiler traces are the
TPU-native form of the reference's profiling role)."""

import glob
import os

import jax
import jax.numpy as jnp
import optax

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.core.backend import BaguaTrainer
from bagua_tpu.models.mlp import MLP
from bagua_tpu.profiling import StepProfiler, trace


def _trace_files(d):
    return glob.glob(os.path.join(d, "**", "*.trace.json*"), recursive=True) \
        + glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)


def test_trace_context_writes_files(tmp_path):
    with trace(str(tmp_path)):
        jnp.ones((64, 64)).sum().block_until_ready()
    assert _trace_files(str(tmp_path)), os.listdir(tmp_path)


def test_trainer_auto_capture(tmp_path, monkeypatch):
    monkeypatch.setenv("BAGUA_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("BAGUA_PROFILE_STEPS", "1:3")

    model = MLP(features=(8, 4))
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    y = jnp.zeros((16,), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x[:2])["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]
        ).mean()

    trainer = BaguaTrainer(loss_fn, optax.sgd(0.1),
                           GradientAllReduceAlgorithm(), autotune=False)
    assert isinstance(trainer._profiler, StepProfiler)
    state = trainer.init(params)
    batch = trainer.shard_batch({"x": x, "y": y})
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
    assert trainer._profiler._done
    assert _trace_files(str(tmp_path)), os.listdir(tmp_path)
