"""Profiler integration (SURVEY.md §5.1: jax.profiler traces are the
TPU-native form of the reference's profiling role)."""

import glob
import os

import pytest

import jax
import jax.numpy as jnp
import optax

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.core.backend import BaguaTrainer
from bagua_tpu.models.mlp import MLP
from bagua_tpu.profiling import StepProfiler, trace


def _trace_files(d):
    return glob.glob(os.path.join(d, "**", "*.trace.json*"), recursive=True) \
        + glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)


def test_trace_context_writes_files(tmp_path):
    with trace(str(tmp_path)):
        jnp.ones((64, 64)).sum().block_until_ready()
    assert _trace_files(str(tmp_path)), os.listdir(tmp_path)


def test_trainer_auto_capture(tmp_path, monkeypatch):
    monkeypatch.setenv("BAGUA_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("BAGUA_PROFILE_STEPS", "1:3")

    model = MLP(features=(8, 4))
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    y = jnp.zeros((16,), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x[:2])["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]
        ).mean()

    trainer = BaguaTrainer(loss_fn, optax.sgd(0.1),
                           GradientAllReduceAlgorithm(), autotune=False)
    assert isinstance(trainer._profiler, StepProfiler)
    state = trainer.init(params)
    batch = trainer.shard_batch({"x": x, "y": y})
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
    assert trainer._profiler._done
    assert _trace_files(str(tmp_path)), os.listdir(tmp_path)


def test_parse_xplane_memory_traffic_synthetic(tmp_path):
    """Parser coverage without a TPU: synthesize an XSpace with a device
    plane carrying Steps + XLA Ops lines and per-op memory breakdowns
    (memory_space 1=HBM, 3=VMEM per op_metrics.proto)."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    from xprof.protobuf import op_metrics_pb2

    from bagua_tpu.profiling import parse_xplane_memory_traffic

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    # stat metadata
    sm = plane.stat_metadata
    sm[1].id = 1
    sm[1].name = "memory_access_breakdown"
    # event metadata: one op moving 2 GB HBM + 1 GB VMEM per occurrence
    em = plane.event_metadata
    em[10].id = 10
    em[10].name = "fusion.1"
    mab = op_metrics_pb2.MemoryAccessBreakdown()
    a = mab.memory_accessed.add()
    a.memory_space, a.bytes_accessed = 1, 2_000_000_000
    b = mab.memory_accessed.add()
    b.memory_space, b.bytes_accessed = 3, 1_000_000_000
    st = em[10].stats.add()
    st.metadata_id = 1
    st.bytes_value = mab.SerializeToString()

    steps = plane.lines.add(name="Steps")
    for i in range(2):
        ev = steps.events.add()
        ev.duration_ps = int(0.05e12)  # 50 ms per step
    ops = plane.lines.add(name="XLA Ops")
    for i in range(4):  # the op runs twice per step
        ev = ops.events.add()
        ev.metadata_id = 10
        ev.duration_ps = int(0.01e12)

    path = tmp_path / "t.xplane.pb"
    path.write_bytes(xs.SerializeToString())
    out = parse_xplane_memory_traffic(str(path))
    assert out["step_s"] == 0.05
    assert out["hbm_gb_per_step"] == 4.0   # 2 occurrences x 2 GB
    assert out["vmem_gb_per_step"] == 2.0
    assert out["hbm_gbps_measured"] == 80  # 4 GB / 50 ms


def test_newest_xplane_is_mtime_ordered(tmp_path):
    """The satellite fix: trace selection must follow mtime, not
    lexicographic filename order — jax names traces host+timestamp, and a
    directory holding two captures sorted the OLD one last."""
    from bagua_tpu.profiling import _newest_xplane

    sub = tmp_path / "plugins" / "profile"
    sub.mkdir(parents=True)
    # lexicographically LAST file is the OLDEST capture
    old = sub / "zzz_host.xplane.pb"
    new = tmp_path / "aaa_host.xplane.pb"
    old.write_bytes(b"old")
    new.write_bytes(b"new")
    past = os.path.getmtime(str(new)) - 100
    os.utime(str(old), (past, past))
    assert _newest_xplane(str(tmp_path)) == str(new)
    assert _newest_xplane(str(tmp_path / "plugins")) == str(old)
    assert _newest_xplane(str(tmp_path / "nope")) is None


def _comm_xplane(tmp_path, n_steps=2, buckets=(4096, 8192, 1024)):
    """Synthetic TPU plane: per step, one comm op occurrence per bucket
    (duration scaled by bucket bytes) plus one compute fusion."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    em = plane.event_metadata
    em[1].id = 1
    em[1].name = "all-reduce-start.1"
    em[2].id = 2
    em[2].name = "fusion.7"
    steps = plane.lines.add(name="Steps")
    for _ in range(n_steps):
        ev = steps.events.add()
        ev.duration_ps = int(0.010e12)
    ops = plane.lines.add(name="XLA Ops")
    t = 0
    for _ in range(n_steps):
        for nbytes in buckets:
            ev = ops.events.add()
            ev.metadata_id = 1
            ev.offset_ps = t
            ev.duration_ps = int(nbytes * 1e5)  # dur proportional to bytes
            t += ev.duration_ps
        ev = ops.events.add()
        ev.metadata_id = 2
        ev.offset_ps = t
        ev.duration_ps = int(0.006e12)
        t += ev.duration_ps
    path = tmp_path / "comm.xplane.pb"
    path.write_bytes(xs.SerializeToString())
    return str(path)


def test_parse_xplane_comm_events_synthetic(tmp_path):
    from bagua_tpu.profiling import parse_xplane_comm_events

    path = _comm_xplane(tmp_path)
    out = parse_xplane_comm_events(path)
    assert out["n_steps"] == 2
    assert len(out["events"]) == 6            # 3 buckets x 2 steps
    assert [e["t0_s"] for e in out["events"]] == sorted(
        e["t0_s"] for e in out["events"])
    assert all(e["name"].startswith("all-reduce") for e in out["events"])


def test_device_attribution_per_bucket(tmp_path):
    """Host bucket launches x device comm occurrences -> per-bucket device
    comm seconds; occurrence durations scale with bucket bytes, so the
    positional match must assign the big bucket the big time."""
    from bagua_tpu.obs.attribution import attribute_device_comm

    _comm_xplane(tmp_path, buckets=(4096, 8192, 1024))
    launches = [{"bucket": 0, "bytes": 4096}, {"bucket": 1, "bytes": 8192},
                {"bucket": 2, "bytes": 1024}]
    out = attribute_device_comm(str(tmp_path), bucket_launches=launches)
    assert out["available"] is True
    per = {b["bucket"]: b for b in out["per_bucket"]}
    assert per[1]["device_comm_s"] > per[0]["device_comm_s"] \
        > per[2]["device_comm_s"]
    assert per[1]["device_comm_s"] == pytest.approx(8192 * 1e5 / 1e12)
    assert out["per_op"][0]["occurrences"] == 6
    # mismatched bucket count degrades to per-op with a rationale
    out2 = attribute_device_comm(str(tmp_path),
                                 bucket_launches=launches[:2])
    assert out2["available"] is True and out2["per_bucket"] is None
    assert "do not map" in out2["per_bucket_rationale"]


def test_device_attribution_null_with_rationale(tmp_path):
    """cpu-sim convention: no TPU plane -> available False plus a human
    rationale (like trace_overlap's bench records), and the summary path
    carries it."""
    from bagua_tpu.obs import export as obs_export
    from bagua_tpu.obs.attribution import attribute_device_comm

    out = attribute_device_comm(str(tmp_path))
    assert out["available"] is False and out["rationale"]
    obs_export.reset_local_summary()
    try:
        obs_export.note_step(5, 0.01)
        obs_export.note_device_attribution(out)
        summary = obs_export.local_obs_summary()
        assert summary["device_comm_s_per_step"] is None
        assert summary["device_attribution_rationale"] == out["rationale"]
    finally:
        obs_export.reset_local_summary()


def test_bucket_launches_from_ring():
    from bagua_tpu.obs import spans as obs_spans
    from bagua_tpu.obs.attribution import bucket_launches_from_ring

    spans = [
        {"name": "trace/bucket_collective", "t0": 1.0, "t1": 1.1,
         "attrs": {"bucket": 1, "bytes": 10}},
        {"name": "trace/bucket_collective", "t0": 0.5, "t1": 0.6,
         "attrs": {"bucket": 0, "bytes": 20}},
        {"name": "step/dispatch", "t0": 0.4, "t1": 2.0},
        # a re-trace of bucket 0 supersedes the earlier record
        {"name": "trace/bucket_collective", "t0": 3.0, "t1": 3.1,
         "attrs": {"bucket": 0, "bytes": 30}},
    ]
    out = bucket_launches_from_ring(spans)
    # tier defaults: spans without tier attrs are flat single-collective
    # launches (ici_bytes = the full operand, nothing on DCN)
    assert out == [
        {"bucket": 1, "bytes": 10, "tier": "flat", "ici_bytes": 10,
         "dcn_bytes": 0},
        {"bucket": 0, "bytes": 30, "tier": "flat", "ici_bytes": 30,
         "dcn_bytes": 0},
    ]
    obs_spans.recorder.clear()
    assert bucket_launches_from_ring() == []
