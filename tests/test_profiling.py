"""Profiler integration (SURVEY.md §5.1: jax.profiler traces are the
TPU-native form of the reference's profiling role)."""

import glob
import os

import pytest

import jax
import jax.numpy as jnp
import optax

from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.core.backend import BaguaTrainer
from bagua_tpu.models.mlp import MLP
from bagua_tpu.profiling import StepProfiler, trace


def _trace_files(d):
    return glob.glob(os.path.join(d, "**", "*.trace.json*"), recursive=True) \
        + glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)


def test_trace_context_writes_files(tmp_path):
    with trace(str(tmp_path)):
        jnp.ones((64, 64)).sum().block_until_ready()
    assert _trace_files(str(tmp_path)), os.listdir(tmp_path)


def test_trainer_auto_capture(tmp_path, monkeypatch):
    monkeypatch.setenv("BAGUA_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("BAGUA_PROFILE_STEPS", "1:3")

    model = MLP(features=(8, 4))
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    y = jnp.zeros((16,), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), x[:2])["params"]

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, b["y"]
        ).mean()

    trainer = BaguaTrainer(loss_fn, optax.sgd(0.1),
                           GradientAllReduceAlgorithm(), autotune=False)
    assert isinstance(trainer._profiler, StepProfiler)
    state = trainer.init(params)
    batch = trainer.shard_batch({"x": x, "y": y})
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
    assert trainer._profiler._done
    assert _trace_files(str(tmp_path)), os.listdir(tmp_path)


def test_parse_xplane_memory_traffic_synthetic(tmp_path):
    """Parser coverage without a TPU: synthesize an XSpace with a device
    plane carrying Steps + XLA Ops lines and per-op memory breakdowns
    (memory_space 1=HBM, 3=VMEM per op_metrics.proto)."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    from xprof.protobuf import op_metrics_pb2

    from bagua_tpu.profiling import parse_xplane_memory_traffic

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    # stat metadata
    sm = plane.stat_metadata
    sm[1].id = 1
    sm[1].name = "memory_access_breakdown"
    # event metadata: one op moving 2 GB HBM + 1 GB VMEM per occurrence
    em = plane.event_metadata
    em[10].id = 10
    em[10].name = "fusion.1"
    mab = op_metrics_pb2.MemoryAccessBreakdown()
    a = mab.memory_accessed.add()
    a.memory_space, a.bytes_accessed = 1, 2_000_000_000
    b = mab.memory_accessed.add()
    b.memory_space, b.bytes_accessed = 3, 1_000_000_000
    st = em[10].stats.add()
    st.metadata_id = 1
    st.bytes_value = mab.SerializeToString()

    steps = plane.lines.add(name="Steps")
    for i in range(2):
        ev = steps.events.add()
        ev.duration_ps = int(0.05e12)  # 50 ms per step
    ops = plane.lines.add(name="XLA Ops")
    for i in range(4):  # the op runs twice per step
        ev = ops.events.add()
        ev.metadata_id = 10
        ev.duration_ps = int(0.01e12)

    path = tmp_path / "t.xplane.pb"
    path.write_bytes(xs.SerializeToString())
    out = parse_xplane_memory_traffic(str(path))
    assert out["step_s"] == 0.05
    assert out["hbm_gb_per_step"] == 4.0   # 2 occurrences x 2 GB
    assert out["vmem_gb_per_step"] == 2.0
    assert out["hbm_gbps_measured"] == 80  # 4 GB / 50 ms
