"""bagua-lint gates: AST rule fixtures, suppressions, the shrink-only
baseline, and the jaxpr collective-consistency checker (seeded divergences +
overlap-vs-serialized equivalence on the real step builders)."""

import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import bagua_tpu
from bagua_tpu.analysis import Finding, run_ast_rules
from bagua_tpu.analysis.ast_rules import analyze_source
from bagua_tpu.analysis.findings import (
    load_baseline,
    save_baseline,
    split_by_baseline,
)
from bagua_tpu.analysis.jaxpr_check import (
    check_axis_binding,
    check_equivalence,
    collect,
    make_family_tracer,
    multiset,
)
from bagua_tpu.compat import shard_map

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.dirname(os.path.abspath(bagua_tpu.__file__))


def rules_of(source, path="fixture.py"):
    return [f.rule for f in analyze_source(path, textwrap.dedent(source))]


# ---- AST rule fixtures (positive + negative per rule) ---------------------


def test_host_sync_in_trace_positive():
    found = rules_of("""
        import jax
        import numpy as np

        def step(p, b):
            def per_shard(p, b):
                g = np.asarray(b)
                jax.device_get(p)
                v = float(g.sum())
                b.block_until_ready()
                return p
            return jax.jit(per_shard)(p, b)
    """)
    assert found.count("host-sync-in-trace") == 4


def test_host_sync_outside_trace_negative():
    # the same calls on the host side are fine
    found = rules_of("""
        import jax
        import numpy as np

        def host_eval(p, b):
            g = np.asarray(b)
            jax.device_get(p)
            return float(g.sum())
    """)
    assert "host-sync-in-trace" not in found


def test_host_sync_jnp_negative():
    found = rules_of("""
        import jax
        import jax.numpy as jnp

        def step(p):
            def traced(p):
                return jnp.asarray(p)[None]
            return jax.jit(traced)(p)
    """)
    assert "host-sync-in-trace" not in found


def test_raw_env_read_positive():
    found = rules_of("""
        import os
        a = os.environ.get("BAGUA_FIXTURE_X", "1")
        b = os.environ["BAGUA_FIXTURE_Y"]
        c = os.getenv("BAGUA_FIXTURE_Z")
    """)
    assert found.count("raw-env-read") == 3


def test_raw_env_read_negative():
    found = rules_of("""
        import os
        a = os.environ.get("HOME")
        b = os.environ.get("XLA_FLAGS", "")
    """)
    assert "raw-env-read" not in found


def test_raw_env_read_env_py_exempt():
    found = [
        f.rule
        for f in analyze_source(
            "bagua_tpu/env.py",
            'import os\nv = os.environ.get("BAGUA_ANYTHING")\n',
        )
    ]
    assert "raw-env-read" not in found


def test_tracer_leak_positive_and_negative():
    found = rules_of("""
        import jax

        class T:
            def go(self):
                def traced(x):
                    self.cache = x
                    return x * 2
                return jax.jit(traced)

            def host(self, x):
                self.cache = x  # host-side stash is fine
                return x
    """)
    assert found.count("tracer-leak") == 1


def test_py_rng_in_trace_positive_and_negative():
    found = rules_of("""
        import jax
        import random
        import numpy as np

        def step(p):
            def traced(p):
                a = random.random()
                b = np.random.randn(3)
                key = jax.random.PRNGKey(0)  # jax.random is fine
                return p + a + b.sum()
            return jax.jit(traced)(p)

        seed = random.random()  # host-side RNG is fine
    """)
    assert found.count("py-rng-in-trace") == 2


def test_dup_lambda_positive():
    found = rules_of("""
        import jax
        import jax.numpy as jnp
        f1 = lambda t: jax.tree.map(lambda x: jnp.asarray(x)[None], t)
        f2 = lambda t: jax.tree.map(lambda x: jnp.asarray(x)[None], t)
        f3 = lambda u: jax.tree.map(lambda x: jnp.asarray(x)[None], u)
    """)
    # arg-name normalization makes f3 a duplicate too; inner lambdas are
    # not double-reported
    assert found.count("dup-lambda") == 3


def test_dup_lambda_negative_two_copies_and_trivial():
    found = rules_of("""
        import jax
        import jax.numpy as jnp
        f1 = lambda t: jax.tree.map(lambda x: jnp.asarray(x)[None], t)
        f2 = lambda t: jax.tree.map(lambda x: jnp.asarray(x)[None], t)
        k1 = lambda p: p
        k2 = lambda p: p
        k3 = lambda p: p
    """)
    assert "dup-lambda" not in found


def test_torch_import_positive():
    found = rules_of("""
        import torch
        from torch.utils.data import DataLoader
    """)
    assert found.count("torch-import") == 2


def test_per_step_reflatten_positive_transform_fn():
    # the PRE-FIX contrib/fused_optimizer.update_fn pattern: per-dtype
    # concat of tree leaves inside an optax GradientTransformation (which
    # traces inside the jitted step by construction)
    found = rules_of("""
        import jax
        import jax.numpy as jnp
        import optax

        def fuse(inner):
            def update_fn(updates, state, params=None):
                leaves = jax.tree_util.tree_leaves(updates)
                flat = jnp.concatenate([jnp.ravel(l) for l in leaves])
                out, st = inner.update(flat, state, None)
                return out, st
            return optax.GradientTransformation(inner.init, update_fn)
    """)
    assert found.count("per-step-reflatten") == 1


def test_per_step_reflatten_positive_traced_step():
    found = rules_of("""
        import jax
        import jax.numpy as jnp

        def step(params, batch):
            leaves = jax.tree_util.tree_leaves(params)
            flat = jnp.concatenate([jnp.ravel(l) for l in leaves])
            return flat.sum()

        fn = jax.jit(step)
    """)
    assert found.count("per-step-reflatten") == 1


def test_per_step_reflatten_negative():
    # flatten without concat, concat without flatten, and an untraced
    # standalone helper are all idiom, not per-step repacking; the
    # flat-RESIDENT step consumes pre-flattened buffers and never flattens
    found = rules_of("""
        import jax
        import jax.numpy as jnp

        def helper(tree):
            return jnp.concatenate(
                [jnp.ravel(l) for l in jax.tree_util.tree_leaves(tree)]
            )

        def resident_step(flats, batch):
            return sum(f.sum() for f in flats)

        def flatten_only(params, batch):
            return sum(l.sum() for l in jax.tree_util.tree_leaves(params))

        f1 = jax.jit(resident_step)
        f2 = jax.jit(flatten_only)
    """)
    assert "per-step-reflatten" not in found


def test_per_step_reflatten_repo_is_clean():
    """The resident path (and the fixed fused optimizer) must lint clean."""
    findings = run_ast_rules([PKG], rel_to=REPO)
    assert not [f for f in findings if f.rule == "per-step-reflatten"], [
        (f.path, f.line) for f in findings if f.rule == "per-step-reflatten"
    ]


def test_unregistered_counter_positive_misspelled():
    # the canonical typo: a counter name one character off from a
    # registered one silently forks an unread metric
    found = rules_of("""
        from bagua_tpu.telemetry import counters

        def on_abort():
            counters.incr("comm/abortss")
            counters.set_gauge("async/staleness_maximum", 3)
    """)
    assert found.count("unregistered-counter") == 2


def test_unregistered_counter_incr_many_and_fstring():
    # literal dict keys in incr_many are checked too; f-string names pass
    # when SOME registered name fits the template, fail when none does
    found = rules_of("""
        from bagua_tpu.telemetry import counters

        def on_fire(point):
            counters.incr_many({"obs/flight_dumps": 1,
                                "obs/flite_dumps": 1})
            counters.incr(f"faults/{point}/fired")
            counters.incr(f"faults/{point}/exploded")
    """)
    assert found.count("unregistered-counter") == 2


def test_unregistered_counter_negative():
    # registered literals, matching f-string templates, and statically
    # unresolvable names (a variable) are all clean
    found = rules_of("""
        from bagua_tpu.telemetry import counters

        def ok(name):
            counters.incr("comm/aborts")
            counters.set_gauge("async/staleness_max", 2)
            counters.incr_many({"grad_guard/skipped_steps": 1})
            counters.incr(f"faults/{name}/recovered")
            counters.incr(name)
    """)
    assert "unregistered-counter" not in found


def test_unregistered_counter_repo_is_clean():
    """Every counter write site in the package names a registered metric."""
    findings = run_ast_rules([PKG], rel_to=REPO)
    assert not [f for f in findings if f.rule == "unregistered-counter"], [
        (f.path, f.line) for f in findings if f.rule == "unregistered-counter"
    ]


# ---- suppressions ---------------------------------------------------------


def test_suppression_trailing_and_standalone():
    src = """
        import os
        a = os.environ.get("BAGUA_FIXTURE_A")  # bagua: lint-ignore[raw-env-read] -- fixture
        # bagua: lint-ignore[raw-env-read] -- covers the next line
        b = os.environ.get("BAGUA_FIXTURE_B")
        c = os.environ.get("BAGUA_FIXTURE_C")
    """
    found = rules_of(src)
    assert found.count("raw-env-read") == 1  # only c survives


def test_suppression_wrong_rule_does_not_apply():
    found = rules_of("""
        import os
        a = os.environ.get("BAGUA_FIXTURE_A")  # bagua: lint-ignore[tracer-leak] -- wrong id
    """)
    assert "raw-env-read" in found


def test_suppression_without_reason_is_reported():
    found = rules_of("""
        import os
        a = os.environ.get("BAGUA_FIXTURE_A")  # bagua: lint-ignore[raw-env-read]
    """)
    assert "bad-suppression" in found
    assert "raw-env-read" in found  # the malformed suppression doesn't apply


# ---- baseline -------------------------------------------------------------


def test_baseline_round_trip_and_shrink_only(tmp_path):
    f1 = Finding("raw-env-read", "a.py", 3, "m", text="x = 1")
    f2 = Finding("raw-env-read", "b.py", 9, "m", text="y = 2")
    path = str(tmp_path / "baseline.json")
    save_baseline(path, [f1, f2])
    baseline = load_baseline(path)

    # same findings -> fully baselined, nothing new, nothing stale
    new, old, stale = split_by_baseline([f1, f2], baseline)
    assert not new and len(old) == 2 and not stale

    # line drift does not churn the baseline (fingerprint is rule+path+text)
    drifted = Finding("raw-env-read", "a.py", 30, "m", text="x = 1")
    new, old, stale = split_by_baseline([drifted, f2], baseline)
    assert not new and not stale

    # a fixed violation leaves a STALE entry (shrink-only: must prune)
    new, old, stale = split_by_baseline([f1], baseline)
    assert not new and len(stale) == 1

    # a new violation is NOT absorbed by the baseline
    f3 = Finding("tracer-leak", "c.py", 1, "m", text="self.x = t")
    new, old, stale = split_by_baseline([f1, f2, f3], baseline)
    assert new == [f3]


# ---- the repo itself is clean --------------------------------------------


def test_package_has_no_unsuppressed_findings():
    findings = run_ast_rules([PKG], rel_to=REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exits_zero_on_package():
    out = subprocess.run(
        [sys.executable, "-m", "bagua_tpu.analysis", "bagua_tpu/",
         "--no-jaxpr"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr


def test_cli_flags_violations_and_baseline_flow(tmp_path):
    bad = tmp_path / "bad_module.py"
    bad.write_text(
        'import os\nv = os.environ.get("BAGUA_FIXTURE_CLI")\n'
    )
    base = [sys.executable, "-m", "bagua_tpu.analysis", str(bad), "--no-jaxpr"]
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(base, capture_output=True, text=True, timeout=120,
                         cwd=str(tmp_path), env=env)
    assert out.returncode == 1 and "raw-env-read" in out.stdout

    # write a baseline, rerun: clean
    bl = str(tmp_path / "bl.json")
    subprocess.run(base + ["--write-baseline", "--baseline", bl],
                   capture_output=True, text=True, timeout=120,
                   cwd=str(tmp_path), env=env, check=True)
    out = subprocess.run(base + ["--baseline", bl], capture_output=True,
                         text=True, timeout=120, cwd=str(tmp_path), env=env)
    assert out.returncode == 0, out.stdout

    # fix the violation: the stale baseline entry now FAILS (shrink-only)
    bad.write_text("v = 1\n")
    out = subprocess.run(base + ["--baseline", bl], capture_output=True,
                         text=True, timeout=120, cwd=str(tmp_path), env=env)
    assert out.returncode == 1 and "STALE" in out.stdout


# ---- jaxpr checker --------------------------------------------------------


def _trace_shard_map(fn, n_args=1):
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    specs = (P("dp"),) * n_args
    g = shard_map(fn, mesh=mesh, in_specs=specs, out_specs=P("dp"),
                  check_vma=False)
    args = [jnp.ones((8, 4)) for _ in range(n_args)]
    jitted = jax.jit(g)
    if hasattr(jitted, "trace"):
        return jitted.trace(*args).jaxpr
    return jax.make_jaxpr(g)(*args)


def test_jaxpr_flags_mismatched_cond_collectives():
    def bad(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.lax.psum(v, "dp"),
            lambda v: v * 2.0,
            x,
        )

    _, findings = collect(_trace_shard_map(bad))
    assert [f.rule for f in findings] == ["cond-collective-divergence"]


def test_jaxpr_accepts_matched_cond_collectives():
    def good(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.lax.psum(v, "dp"),
            lambda v: jax.lax.psum(v * 2.0, "dp"),
            x,
        )

    seq, findings = collect(_trace_shard_map(good))
    assert findings == []
    # the shared branch collective is counted once, not per branch
    assert [c.prim for c in seq] == ["psum"]


def test_jaxpr_axis_binding():
    def f(x):
        return jax.lax.psum(x, "dp")

    seq, _ = collect(_trace_shard_map(f))
    assert check_axis_binding(seq, ("dp",)) == []
    bad = check_axis_binding(seq, ("inter", "intra"))
    assert [b.rule for b in bad] == ["unbound-mesh-axis"]


@pytest.mark.parametrize("family", ["gradient_allreduce", "zero", "bytegrad"])
@pytest.mark.parametrize("accum", [1, 4])
def test_overlap_vs_serialized_collective_equivalence(family, accum):
    """PR 2's 'paths cannot drift' claim as a checked invariant, on the REAL
    step builders."""
    findings, report = check_equivalence(
        family, accum, make_family_tracer(family, accum)
    )
    assert findings == [], "\n".join(f.render() for f in findings)
    assert report["equal"]
    # byte accounting covered every bucket with at least one collective
    for row in report["serialized"]["buckets"]:
        assert row["collectives"], row


def test_equivalence_catches_seeded_divergence():
    """A construction with one extra collective must be flagged."""
    tracer = make_family_tracer("gradient_allreduce", 1)
    trainer, jaxpr_off = tracer("off")

    def extra(x):
        return jax.lax.psum(jax.lax.psum(x, "dp"), "dp")

    divergent = _trace_shard_map(extra)

    def fake_tracer(mode):
        return trainer, (jaxpr_off if mode == "off" else divergent)

    findings, report = check_equivalence("gradient_allreduce", 1, fake_tracer)
    assert not report["equal"]
    assert "overlap-serialized-divergence" in [f.rule for f in findings]


def test_multiset_ignores_order_but_not_shape():
    a = _trace_shard_map(lambda x: jax.lax.psum(x, "dp"))
    b = _trace_shard_map(lambda x: jax.lax.psum(x * 2.0, "dp"))
    sa, _ = collect(a)
    sb, _ = collect(b)
    assert multiset(sa) == multiset(sb)  # same signature, different compute
    c = _trace_shard_map(lambda x: jax.lax.psum(x[:, :2], "dp"))
    sc, _ = collect(c)
    assert multiset(sa) != multiset(sc)  # shape is part of the signature


# ---- concurrency engine (bagua-lint v2) -----------------------------------


from bagua_tpu.analysis.concurrency import (  # noqa: E402
    build_program,
    run_concurrency_rules,
    static_lock_graph,
)
from bagua_tpu.analysis.trace_coherence import run_trace_coherence  # noqa: E402
from bagua_tpu.analysis import lockdep as lockdep_mod  # noqa: E402
from bagua_tpu.analysis.suppressions import KNOWN_RULE_IDS  # noqa: E402

import threading  # noqa: E402


def _fx(**files):
    """name -> dedented source; underscores in kwargs become path slashes."""
    return {k.replace("__", "/") + ".py": textwrap.dedent(v)
            for k, v in files.items()}


def conc_rules(sources):
    return [f.rule for f in run_concurrency_rules(sources=sources)]


def test_lock_order_inversion_positive():
    rules = conc_rules(_fx(fx__mod="""
        import threading
        A = threading.Lock()
        B = threading.Lock()

        def forward():
            with A:
                with B:
                    pass

        def backward():
            with B:
                with A:
                    pass

        def start():
            threading.Thread(target=backward).start()
    """))
    assert "lock-order-inversion" in rules


def test_lock_order_consistent_negative():
    rules = conc_rules(_fx(fx__mod="""
        import threading
        A = threading.Lock()
        B = threading.Lock()

        def forward():
            with A:
                with B:
                    pass

        def also_forward():
            with A:
                with B:
                    pass

        def start():
            threading.Thread(target=also_forward).start()
    """))
    assert "lock-order-inversion" not in rules


def test_unguarded_shared_write_positive():
    """The pre-fix obs/spans.py shape: init under the lock, the test
    override without it."""
    rules = conc_rules(_fx(fx__mod="""
        import threading
        _STATE = None
        _LOCK = threading.Lock()

        def init():
            global _STATE
            with _LOCK:
                _STATE = 1

        def override(v):
            global _STATE
            _STATE = v

        def bg():
            init()

        def start():
            threading.Thread(target=bg).start()
    """))
    assert "unguarded-shared-write" in rules


def test_unguarded_shared_write_common_lock_negative():
    rules = conc_rules(_fx(fx__mod="""
        import threading
        _STATE = None
        _LOCK = threading.Lock()

        def init():
            global _STATE
            with _LOCK:
                _STATE = 1

        def override(v):
            global _STATE
            with _LOCK:
                _STATE = v

        def bg():
            init()

        def start():
            threading.Thread(target=bg).start()
    """))
    assert "unguarded-shared-write" not in rules


def test_unguarded_shared_write_single_root_negative():
    """No second thread root: a module global mutated only from the main
    context is not a race."""
    rules = conc_rules(_fx(fx__mod="""
        _STATE = None

        def init():
            global _STATE
            _STATE = 1

        def override(v):
            global _STATE
            _STATE = v
    """))
    assert "unguarded-shared-write" not in rules


def test_lock_held_io_positive_and_negative():
    src = """
        import threading
        import time
        _L = threading.Lock()

        def slow():
            with _L:
                time.sleep(1.0)

        def fast():
            with _L:
                x = 1
                return x

        def start():
            threading.Thread(target=slow).start()
    """
    assert "lock-held-io" in conc_rules(_fx(fx__mod=src))
    # single-root: nobody contends, the IO hurts nobody
    single = src.replace("threading.Thread(target=slow).start()", "slow()")
    assert "lock-held-io" not in conc_rules(_fx(fx__mod=single))


def test_signal_unsafe_lock_positive_pre_fix_sigterm_dump():
    """The pre-fix flight-record shape: the SIGTERM handler called the
    dump path directly, acquiring the dump lock from handler context."""
    rules = conc_rules(_fx(fx__rec="""
        import signal
        import threading
        _DUMP_LOCK = threading.Lock()

        def dump_flight_record():
            with _DUMP_LOCK:
                pass

        def _on_term(signum, frame):
            dump_flight_record()

        def install():
            signal.signal(signal.SIGTERM, _on_term)
    """))
    assert "signal-unsafe-lock" in rules


def test_signal_flag_defer_negative_post_fix_shape():
    """The post-fix shape: the handler only sets a flag; the dump runs
    from a normal context later."""
    rules = conc_rules(_fx(fx__rec="""
        import signal
        import threading
        _DUMP_LOCK = threading.Lock()
        _PENDING = threading.Event()

        def dump_flight_record():
            with _DUMP_LOCK:
                pass

        def _on_term(signum, frame):
            _PENDING.set()

        def install():
            signal.signal(signal.SIGTERM, _on_term)

        def maybe_dump():
            if _PENDING.is_set():
                dump_flight_record()
    """))
    assert "signal-unsafe-lock" not in rules


def test_non_reentrant_reacquire_positive_and_rlock_negative():
    src = """
        import threading
        _L = threading.Lock()

        def outer():
            with _L:
                inner()

        def inner():
            with _L:
                pass
    """
    assert "non-reentrant-reacquire" in conc_rules(_fx(fx__mod=src))
    rlock = src.replace("threading.Lock()", "threading.RLock()")
    assert "non-reentrant-reacquire" not in conc_rules(_fx(fx__mod=rlock))


def test_concurrency_suppression_applies():
    rules = conc_rules(_fx(fx__mod="""
        import threading
        _STATE = None
        _LOCK = threading.Lock()

        def init():
            global _STATE
            with _LOCK:
                _STATE = 1  # bagua: lint-ignore[unguarded-shared-write] -- fixture

        def override(v):
            global _STATE
            _STATE = v

        def bg():
            init()

        def start():
            threading.Thread(target=bg).start()
    """))
    assert "unguarded-shared-write" not in rules


def test_package_is_concurrency_and_trace_clean():
    """The committed package has zero findings from both v2 engines (the
    baseline stays empty) — and the model is NOT vacuous: it sees the
    package's locks, thread roots, and the codec env read."""
    p = build_program([PKG], rel_to=REPO)
    conc = run_concurrency_rules(program=p)
    assert conc == [], "\n".join(f.render() for f in conc)
    trace = run_trace_coherence(program=p)
    assert trace == [], "\n".join(f.render() for f in trace)
    assert len(p.locks) >= 10
    assert len(p.thread_roots) >= 5
    g = static_lock_graph(p)
    assert "bagua_tpu/obs/spans.py::_ENABLED_LOCK" in set(g["locks"].values())
    # the trace prover actually followed construction into the codec
    from bagua_tpu.analysis import trace_coherence as tc
    closure = tc._construction_closure(
        p, "bagua_tpu/core/backend.py::BaguaTrainer._make_step_fn")
    assert ("bagua_tpu/compression/codecs.py::TopKCodec.__init__"
            in closure)


def test_spans_set_enabled_holds_the_lock():
    """Regression for the unguarded-shared-write finding on obs/spans:
    the test override must take the same lock as the double-checked
    init."""
    from bagua_tpu.obs import spans

    class Probe:
        def __init__(self):
            self.entered = 0
            self._l = threading.Lock()

        def __enter__(self):
            self.entered += 1
            return self._l.__enter__()

        def __exit__(self, *exc):
            return self._l.__exit__(*exc)

    probe = Probe()
    orig_lock, orig_state = spans._ENABLED_LOCK, spans._ENABLED
    try:
        spans._ENABLED_LOCK = probe
        spans.set_enabled(True)
        assert probe.entered == 1
        assert spans.enabled() is True
    finally:
        spans._ENABLED_LOCK = orig_lock
        spans._ENABLED = orig_state


# ---- trace-coherence engine -----------------------------------------------


_TRACE_ENV_FX = """
    import os

    def _raw(name, default):
        return os.environ.get(name, default)

    def get_ratio():
        return float(_raw("BAGUA_FX_RATIO", "0.01"))
"""

_TRACE_PRE_FIX = """
    from .env import get_ratio

    class Codec:
        def __init__(self):
            self.ratio = get_ratio()

    CODECS = {"topk": Codec()}

    def get_codec(name):
        return CODECS[name]

    class Trainer:
        def __init__(self):
            self.plan = "p"

        def _step_key(self):
            return (self.plan,)

        def _make_step_fn(self):
            return get_codec("topk")
"""


def trace_rules(sources):
    return [f.rule for f in run_trace_coherence(sources=sources)]


def test_trace_flags_import_time_env_freeze_pre_fix_shape():
    """The PR 17 BAGUA_TOPK_RATIO bug: the codec singleton reads the env
    var at import, the key never carries it — a flip reuses a stale
    compiled step."""
    found = trace_rules(_fx(fx__env=_TRACE_ENV_FX,
                            fx__trainer=_TRACE_PRE_FIX))
    assert "trace-knob-not-keyed" in found


def test_trace_accepts_keyed_knob_post_fix_shape():
    keyed = _TRACE_PRE_FIX.replace(
        "return (self.plan,)", "return (self.plan, get_ratio())")
    found = trace_rules(_fx(fx__env=_TRACE_ENV_FX, fx__trainer=keyed))
    assert found == []


def test_trace_invariant_annotation_suppresses():
    annotated = _TRACE_PRE_FIX.replace(
        'return get_codec("topk")',
        'return get_codec("topk")  '
        '# bagua: trace-invariant[BAGUA_FX_RATIO] -- fixture: host-side only',
    )
    found = trace_rules(_fx(fx__env=_TRACE_ENV_FX, fx__trainer=annotated))
    assert found == []


def test_malformed_trace_invariant_is_reported():
    found = trace_rules(_fx(fx__mod="""
        # bagua: trace-invariant[get_ratio]
        X = 1
    """))
    assert "bad-trace-invariant" in found


def test_trace_flags_autotune_mutable_attr_not_keyed():
    src = """
        class Trainer:
            def __init__(self):
                self.overlap = "on"

            def _apply_recommendation(self, rec):
                self.overlap = rec

            def _step_key(self):
                return (1,)

            def _make_step_fn(self):
                return self.overlap
    """
    assert "trace-knob-not-keyed" in trace_rules(_fx(fx__trainer=src))
    keyed = src.replace("return (1,)", "return (self.overlap,)")
    assert trace_rules(_fx(fx__trainer=keyed)) == []


def test_trace_flags_transitive_autotune_knob_not_keyed():
    """Autotune-v2 shape (the ``_flat_resident`` knob): the mutation sits
    in a HELPER the recommendation path calls, not in
    ``_apply_recommendation`` itself — the prover must chase the
    transitive call closure, flag the unkeyed knob, and accept it once
    it rides the step key."""
    src = """
        class Trainer:
            def __init__(self):
                self._flat_resident = False

            def _apply_flat_resident(self, want):
                self._flat_resident = want == "on"

            def _apply_recommendation(self, rec):
                if rec.flat_resident:
                    self._apply_flat_resident(rec.flat_resident)

            def _step_key(self):
                return (1,)

            def _make_step_fn(self):
                return self._flat_resident
    """
    assert "trace-knob-not-keyed" in trace_rules(_fx(fx__trainer=src))
    keyed = src.replace("return (1,)", "return (1, self._flat_resident)")
    assert trace_rules(_fx(fx__trainer=keyed)) == []


def test_constructor_frozen_attr_is_exempt():
    """An attr set only in __init__ and read by construction needs no key
    entry: the per-instance step cache cannot go stale on it."""
    found = trace_rules(_fx(fx__trainer="""
        class Trainer:
            def __init__(self, donate):
                self.donate = donate

            def _apply_recommendation(self, rec):
                pass

            def _step_key(self):
                return (1,)

            def _make_step_fn(self):
                return self.donate
    """))
    assert found == []


# ---- suppression rule-id validation ----------------------------------------


def test_unknown_rule_id_suppression_is_reported():
    found = rules_of("""
        import os
        a = os.environ.get("BAGUA_FIXTURE_A")  # bagua: lint-ignore[no-such-rule] -- typo
    """)
    assert "bad-suppression" in found
    assert "raw-env-read" in found  # the typo'd suppression covers nothing


def test_known_rule_ids_match_engine_catalogs():
    from bagua_tpu.analysis.ast_rules import RULES as AST_RULES
    from bagua_tpu.analysis.concurrency import CONCURRENCY_RULES
    from bagua_tpu.analysis.lockdep import LOCKDEP_RULES
    from bagua_tpu.analysis.trace_coherence import TRACE_RULES

    ids = {r.id for r in (list(AST_RULES) + list(CONCURRENCY_RULES)
                          + list(TRACE_RULES) + list(LOCKDEP_RULES))}
    ids |= {"cond-collective-divergence", "unbound-mesh-axis",
            "overlap-serialized-divergence", "bad-suppression", "*"}
    assert ids == set(KNOWN_RULE_IDS)


# ---- lockdep runtime witness -----------------------------------------------


def test_lockdep_state_records_edges_and_inversions(tmp_path):
    st = lockdep_mod._LockdepState(
        pkg_dir="/nonexistent", out_path=str(tmp_path / "w.json"))
    a, b = ("m.py", 1), ("m.py", 2)
    la = lockdep_mod._InstrumentedLock(threading.Lock(), a, st)
    lb = lockdep_mod._InstrumentedLock(threading.Lock(), b, st)
    with la:
        with lb:
            pass
    w = st.witness()
    assert {"from": list(a), "to": list(b), "count": 1} in w["edges"]
    assert w["inversions"] == []
    with lb:
        with la:
            pass
    w = st.witness()
    assert len(w["inversions"]) == 1
    st.dump()
    assert lockdep_mod.load_witness(str(tmp_path / "w.json"))["inversions"]


def test_lockdep_reentrant_reacquire_is_not_an_edge(tmp_path):
    st = lockdep_mod._LockdepState(
        pkg_dir="/nonexistent", out_path=str(tmp_path / "w.json"))
    a = ("m.py", 1)
    la = lockdep_mod._InstrumentedLock(threading.RLock(), a, st)
    with la:
        with la:
            pass
    w = st.witness()
    assert w["edges"] == [] and w["inversions"] == []


def test_lockdep_cross_check():
    graph = {
        "locks": {("m.py", 1): "m.py::A", ("m.py", 2): "m.py::B"},
        "edges": {("m.py::A", "m.py::B"): "m.py:10"},
    }
    clean = {"edges": [{"from": ["m.py", 1], "to": ["m.py", 2],
                        "count": 3}], "inversions": []}
    assert lockdep_mod.cross_check(clean, graph) == []

    inverted = {"edges": [], "inversions": [
        {"a": ["m.py", 1], "b": ["m.py", 2], "thread": "t"}]}
    assert [f.rule for f in lockdep_mod.cross_check(inverted, graph)] == \
        ["lockdep-runtime-inversion"]

    unmodeled = {"edges": [{"from": ["m.py", 2], "to": ["m.py", 1],
                            "count": 1}], "inversions": []}
    assert [f.rule for f in lockdep_mod.cross_check(unmodeled, graph)] == \
        ["lockdep-unmodeled-edge"]

    # locks the static model does not catalog are not a gate
    foreign = {"edges": [{"from": ["x.py", 9], "to": ["m.py", 1],
                          "count": 1}], "inversions": []}
    assert lockdep_mod.cross_check(foreign, graph) == []


def test_lockdep_not_installed_by_default():
    assert lockdep_mod.maybe_install() is (lockdep_mod._STATE is not None)
    # BAGUA_LOCKDEP defaults off, and nothing in the test suite turns it
    # on for this process
    assert lockdep_mod._STATE is None


def test_cli_witness_gates_runtime_inversion(tmp_path):
    import json

    wit = tmp_path / "wit.json"
    wit.write_text(json.dumps({
        "edges": [],
        "inversions": [{"a": ["bagua_tpu/telemetry.py", 63],
                        "b": ["bagua_tpu/obs/spans.py", 47],
                        "thread": "t"}],
    }))
    out = subprocess.run(
        [sys.executable, "-m", "bagua_tpu.analysis", "bagua_tpu/",
         "--engine", "concurrency", "--witness", str(wit)],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 1
    assert "lockdep-runtime-inversion" in out.stdout


def test_cli_engine_selection_runs_v2_clean():
    out = subprocess.run(
        [sys.executable, "-m", "bagua_tpu.analysis", "bagua_tpu/",
         "--engine", "concurrency,trace"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
