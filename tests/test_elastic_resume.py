"""Cross-topology elastic resume against the golden-gate harness: save at
world size 4, elastic-restore at 2 and at 8, and require loss-trajectory
continuity — the resumed run must land on the same final loss as the
uninterrupted run of ``bench.golden_task()`` (the exact-loss gate's task,
tests/test_loss_goldens.py).

"World size" here is the dp mesh extent inside the single 8-virtual-device
test process (conftest) — exactly the quantity the flat/plan layouts care
about — so the restore math is the multi-process one without subprocess
cost.  Tier-1 fast: pure CPU, no ports, no subprocesses.
"""

import jax
import numpy as np
import optax
import pytest

import bench
from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
from bagua_tpu.checkpoint import BaguaCheckpointManager
from bagua_tpu.core.backend import BaguaTrainer
from bagua_tpu.elastic.resize import elastic_restore
from bagua_tpu.parallel.mesh import build_mesh

# reduction orders differ between dp extents; continuity means "same
# trajectory up to collective reassociation", not bit-equality
ATOL = 5e-5
SAVE_AT, TOTAL = 15, 30


def _trainer(loss_fn, dp: int) -> BaguaTrainer:
    mesh = build_mesh({"dp": dp}, devices=jax.devices()[:dp])
    return BaguaTrainer(
        loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
        mesh=mesh, autotune=False,
    )


def _run(trainer, state, batch, steps: int):
    loss = None
    for _ in range(steps):
        state, loss = trainer.train_step(state, batch)
    return state, float(loss)


@pytest.fixture(scope="module")
def task():
    loss_fn, params, batch = bench.golden_task()
    # the uninterrupted 30-step trajectory this platform's golden gate
    # certifies (goldens are platform-specific; recompute, don't hardcode)
    trainer = _trainer(loss_fn, 4)
    state = trainer.init(params)
    _, final = _run(trainer, state, batch, TOTAL)
    return loss_fn, params, batch, final


@pytest.mark.parametrize("dp_restore", [2, 8])
def test_cross_topology_resume_matches_golden_trajectory(
    tmp_path, task, dp_restore
):
    loss_fn, params, batch, golden_final = task
    # ---- phase 1: train at world size 4, checkpoint at step SAVE_AT ----
    tr4 = _trainer(loss_fn, 4)
    state = tr4.init(params)
    state, _ = _run(tr4, state, batch, SAVE_AT)
    mgr = BaguaCheckpointManager(
        str(tmp_path / "ckpt"), max_to_keep=2, async_save=False,
    )
    assert mgr.save(SAVE_AT, state, metadata=tr4.checkpoint_layout_metadata())
    mgr.wait()

    # ---- phase 2: "restart" at a different world size and resume --------
    tr_new = _trainer(loss_fn, dp_restore)
    state_like = tr_new.init(params)
    mgr2 = BaguaCheckpointManager(str(tmp_path / "ckpt"))
    step, restored = elastic_restore(
        mgr2, state_like,
        expect_metadata=tr_new.checkpoint_layout_metadata(),
        mesh=tr_new.mesh,
    )
    assert step == SAVE_AT
    _, resumed_final = _run(tr_new, restored, batch, TOTAL - SAVE_AT)

    np.testing.assert_allclose(resumed_final, golden_final, rtol=0, atol=ATOL)


def test_elastic_restore_empty_dir_passes_through(tmp_path, task):
    loss_fn, params, _, _ = task
    tr = _trainer(loss_fn, 2)
    state = tr.init(params)
    mgr = BaguaCheckpointManager(str(tmp_path / "none"))
    step, out = elastic_restore(mgr, state)
    assert step is None and out is state


def test_plan_dependent_layout_still_blocked_across_topologies(
    tmp_path, task
):
    """elastic_restore relaxes ONLY the plan-independent case: a
    flat-resident ZeRO checkpoint saved at dp=4 must still refuse to
    restore at dp=2 with the actionable layout error."""
    from bagua_tpu.algorithms.zero import ZeroOptimizerAlgorithm

    loss_fn, params, batch, _ = task

    def zero_trainer(dp):
        mesh = build_mesh({"dp": dp}, devices=jax.devices()[:dp])
        return BaguaTrainer(
            loss_fn, None,
            ZeroOptimizerAlgorithm(optax.sgd(0.1, momentum=0.9)),
            mesh=mesh, autotune=False,
        )

    tr4 = zero_trainer(4)
    meta4 = None
    state = tr4.init(params)
    meta4 = tr4.checkpoint_layout_metadata()
    if not meta4.get("plan_dependent"):
        pytest.skip("zero layout is not flat-resident on this config")
    state, _ = _run(tr4, state, batch, 2)
    mgr = BaguaCheckpointManager(
        str(tmp_path / "zckpt"), async_save=False)
    mgr.save(2, state, metadata=meta4)
    mgr.wait()

    tr2 = zero_trainer(2)
    state_like = tr2.init(params)
    with pytest.raises(ValueError, match="layout mismatch"):
        elastic_restore(
            BaguaCheckpointManager(str(tmp_path / "zckpt")),
            state_like,
            expect_metadata=tr2.checkpoint_layout_metadata(),
        )
