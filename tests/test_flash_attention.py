"""Flash-attention kernel vs plain attention — golden-model equivalence
(SURVEY.md §4: every fused/native op is validated against a pure
reimplementation; same pattern as the codec goldens)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bagua_tpu.ops.flash_attention import (
    flash_attention,
    reference_attention,
)


def _qkv(key, b=2, s=256, h=2, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    want = reference_attention(q, k, v, jnp.float32, causal=causal)
    got = flash_attention(q, k, v, jnp.float32, causal=causal,
                          interpret=True, force=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_forward_rectangular_blocks():
    # seq 384 picks a single 384 block: one grid step, diagonal-only
    q, k, v = _qkv(jax.random.PRNGKey(3), s=384)
    want = reference_attention(q, k, v, jnp.float32)
    got = flash_attention(q, k, v, jnp.float32, interpret=True, force=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("block_q,block_k", [(128, 256), (256, 128)])
def test_mismatched_blocks_fwd_and_bwd(block_q, block_k):
    # block_q != block_k exercises the causal loop bounds (n_kb ceil-div) and
    # the dkv kernel's qb_start floor-div with multi-block diagonals
    q, k, v = _qkv(jax.random.PRNGKey(7), b=1, s=512, h=1, d=64)
    g = jax.random.normal(jax.random.PRNGKey(8), q.shape, jnp.float32)

    def loss(fn):
        return jax.grad(lambda q, k, v: (fn(q, k, v) * g).sum(),
                        argnums=(0, 1, 2))

    ref_fn = lambda q, k, v: reference_attention(q, k, v, jnp.float32)
    fl_fn = lambda q, k, v: flash_attention(
        q, k, v, jnp.float32, block_q=block_q, block_k=block_k,
        interpret=True, force=True,
    )
    np.testing.assert_allclose(fl_fn(q, k, v), ref_fn(q, k, v),
                               atol=2e-5, rtol=2e-5)
    for w, o, name in zip(loss(ref_fn)(q, k, v), loss(fl_fn)(q, k, v), "qkv"):
        np.testing.assert_allclose(o, w, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(1), b=1, s=256, h=2, d=64)
    g = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.float32)

    def loss(fn):
        def f(q, k, v):
            return (fn(q, k, v) * g).sum()

        return jax.grad(f, argnums=(0, 1, 2))

    want = loss(
        lambda q, k, v: reference_attention(q, k, v, jnp.float32,
                                            causal=causal)
    )(q, k, v)
    got = loss(
        lambda q, k, v: flash_attention(q, k, v, jnp.float32, causal=causal,
                                        interpret=True, force=True)
    )(q, k, v)
    for w, o, name in zip(want, got, "qkv"):
        np.testing.assert_allclose(o, w, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


def test_bf16_forward_close():
    q, k, v = _qkv(jax.random.PRNGKey(4), dtype=jnp.bfloat16)
    want = reference_attention(q, k, v, jnp.bfloat16)
    got = flash_attention(q, k, v, jnp.bfloat16, interpret=True, force=True)
    np.testing.assert_allclose(
        got.astype(jnp.float32), want.astype(jnp.float32), atol=2e-2,
        rtol=2e-2,
    )


def test_cpu_fallback_is_reference():
    # on CPU (no force) the dispatcher must return the plain path
    q, k, v = _qkv(jax.random.PRNGKey(5), s=96)
    want = reference_attention(q, k, v, jnp.float32)
    got = flash_attention(q, k, v, jnp.float32)
    np.testing.assert_allclose(got, want, atol=0, rtol=0)


def test_model_dispatch_unchanged_on_cpu():
    # causal_attention (the model hot path) must equal the old jnp math
    from bagua_tpu.models.transformer import causal_attention

    q, k, v = _qkv(jax.random.PRNGKey(6), s=128)
    want = reference_attention(q, k, v, jnp.float32)
    got = causal_attention(q, k, v, jnp.float32)
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)
