"""API-surface regression test: every name MIGRATION.md maps a reference
user to must exist and be importable.  Guards the migration guide against
silent drift (renames, moved modules)."""

import importlib

import pytest

#: (module, attribute) pairs straight from MIGRATION.md's API map
SURFACE = [
    ("bagua_tpu", "init_process_group"),
    ("bagua_tpu", "BaguaTrainer"),
    ("bagua_tpu", "get_rank"),
    ("bagua_tpu", "get_world_size"),
    ("bagua_tpu", "get_local_rank"),
    ("bagua_tpu", "get_local_size"),
    ("bagua_tpu", "ReduceOp"),
    # eager collectives
    ("bagua_tpu", "allreduce"),
    ("bagua_tpu", "allgather"),
    ("bagua_tpu", "reduce_scatter"),
    ("bagua_tpu", "alltoall"),
    ("bagua_tpu", "alltoall_v"),
    ("bagua_tpu", "gather"),
    ("bagua_tpu", "scatter"),
    ("bagua_tpu", "reduce"),
    ("bagua_tpu", "broadcast"),
    ("bagua_tpu", "send_recv"),
    ("bagua_tpu", "barrier"),
    # abort API (reference communicator abort/check_abort)
    ("bagua_tpu", "abort"),
    ("bagua_tpu", "check_abort"),
    ("bagua_tpu", "is_aborted"),
    ("bagua_tpu", "reset_abort"),
    ("bagua_tpu", "BaguaAborted"),
    # algorithms
    ("bagua_tpu.algorithms", "Algorithm"),
    ("bagua_tpu.algorithms", "GradientAllReduceAlgorithm"),
    ("bagua_tpu.algorithms", "ByteGradAlgorithm"),
    ("bagua_tpu.algorithms", "QAdamAlgorithm"),
    ("bagua_tpu.algorithms", "DecentralizedAlgorithm"),
    ("bagua_tpu.algorithms", "LowPrecisionDecentralizedAlgorithm"),
    ("bagua_tpu.algorithms", "AsyncModelAverageAlgorithm"),
    ("bagua_tpu.algorithms", "ZeroOptimizerAlgorithm"),
    # MoE
    ("bagua_tpu.model_parallel.moe", "MoEMLP"),
    # contrib
    ("bagua_tpu.contrib", "FusedOptimizer"),
    ("bagua_tpu.contrib", "LoadBalancingDistributedSampler"),
    ("bagua_tpu.contrib", "LoadBalancingDistributedBatchSampler"),
    ("bagua_tpu.contrib", "CacheLoader"),
    ("bagua_tpu.contrib", "CachedDataset"),
    ("bagua_tpu.contrib", "SyncBatchNorm"),
    ("bagua_tpu.contrib", "prefetch_to_device"),
    ("bagua_tpu.contrib.utils.store", "Store"),
    ("bagua_tpu.contrib.utils.store", "ClusterStore"),
    # services / checkpoint / launcher
    ("bagua_tpu.service.autotune_service", "AutotuneService"),
    ("bagua_tpu.checkpoint", "BaguaCheckpointManager"),
    ("bagua_tpu.distributed.run", "main"),
    ("bagua_tpu.script.baguarun", "main"),
    # inference / parallel
    ("bagua_tpu.models.generate", "generate"),
    ("bagua_tpu.models.generate", "generate_tp"),
    ("bagua_tpu.parallel.ring_attention", "make_ring_attention"),
    ("bagua_tpu.parallel.ulysses", "make_ulysses_attention"),
    ("bagua_tpu.parallel.tensor_parallel", "globalize_tp_params"),
    ("bagua_tpu.parallel.pipeline", "PipelinedTransformerLM"),
]


@pytest.mark.parametrize("module,attr", SURFACE,
                         ids=[f"{m}.{a}" for m, a in SURFACE])
def test_name_exists(module, attr):
    mod = importlib.import_module(module)
    assert hasattr(mod, attr), f"{module}.{attr} missing"
