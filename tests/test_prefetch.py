"""contrib.prefetch_to_device: lookahead device placement for input
pipelines (additive; the reference relies on torch DataLoader prefetch)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from bagua_tpu import BaguaTrainer
from bagua_tpu.algorithms import GradientAllReduceAlgorithm
from bagua_tpu.contrib import prefetch_to_device
from bagua_tpu.models import MLP

N = 8


def _batches(n, rows=16, dim=4):
    rng = np.random.default_rng(0)
    for _ in range(n):
        yield {
            "x": rng.normal(size=(rows, dim)).astype(np.float32),
            "y": rng.integers(0, 3, size=(rows,)).astype(np.int32),
        }


def test_prefetch_with_trainer_trains():
    model = MLP(features=(8, 3))
    loss_fn = lambda p, b: optax.softmax_cross_entropy_with_integer_labels(
        model.apply({"params": p}, b["x"]), b["y"]
    ).mean()
    trainer = BaguaTrainer(loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm())
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))["params"]
    state = trainer.init(params)

    seen = 0
    for batch in prefetch_to_device(_batches(5), trainer=trainer, size=2):
        # batches arrive already placed with the step's input sharding
        assert batch["x"].sharding.spec == P(("dp",))
        state, loss = trainer.train_step(state, batch)
        seen += 1
    assert seen == 5 and np.isfinite(float(loss))


def test_prefetch_explicit_mesh_and_order():
    from bagua_tpu.parallel.mesh import build_mesh

    mesh = build_mesh({"dp": N})
    out = list(prefetch_to_device(
        ({"x": np.full((8, 2), i, np.float32)} for i in range(4)),
        mesh=mesh, spec=P("dp"), size=3,
    ))
    assert len(out) == 4
    for i, b in enumerate(out):
        assert float(b["x"][0, 0]) == i  # order preserved


def test_prefetch_validation():
    with pytest.raises(ValueError, match="size"):
        list(prefetch_to_device([], trainer=object(), size=0))
    with pytest.raises(ValueError, match="trainer OR mesh"):
        list(prefetch_to_device([], trainer=object(), mesh=object(), spec=P()))
    with pytest.raises(ValueError, match="both mesh and spec"):
        list(prefetch_to_device([]))
