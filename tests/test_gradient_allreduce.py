"""Golden-model equivalence for GradientAllReduce.

Reference pattern (SURVEY.md §4): run the algorithm distributed, then a pure
single-worker reimplementation on the same data, and compare weights
elementwise.  DP with averaged grads over the full batch must equal
single-worker training on the concatenated batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from bagua_tpu import BaguaTrainer
from bagua_tpu.algorithms import GradientAllReduceAlgorithm
from bagua_tpu.models import MLP

N = 8
BATCH_PER_RANK = 4
DIM = 12
NCLASS = 10


def _data(steps=5, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(steps, N * BATCH_PER_RANK, DIM)).astype(np.float32)
    ys = rng.integers(0, NCLASS, size=(steps, N * BATCH_PER_RANK)).astype(np.int32)
    return xs, ys


def _loss_fn(model):
    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()

    return loss_fn


@pytest.mark.parametrize("hierarchical", [False, True])
def test_matches_single_worker_sgd(hierarchical):
    model = MLP(features=(16, NCLASS))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    opt = optax.sgd(0.1)
    loss_fn = _loss_fn(model)

    trainer = BaguaTrainer(
        loss_fn, opt, GradientAllReduceAlgorithm(hierarchical=hierarchical),
        bucket_bytes=256,
    )
    state = trainer.init(params)

    xs, ys = _data()
    for s in range(xs.shape[0]):
        state, loss = trainer.train_step(state, {"x": xs[s], "y": ys[s]})

    # golden: plain full-batch SGD (mean loss over the whole global batch ==
    # mean of per-rank means since shards are equal size)
    gp = params
    gopt = opt.init(gp)
    g_step = jax.jit(
        lambda p, o, b: (lambda g: (optax.apply_updates(p, opt.update(g, o, p)[0]), opt.update(g, o, p)[1]))(
            jax.grad(loss_fn)(p, b)
        )
    )
    for s in range(xs.shape[0]):
        gp, gopt = g_step(gp, gopt, {"x": xs[s], "y": ys[s]})

    # leaf view: flat-resident raw state holds bucket flats, not leaves
    flat_a = jax.tree.leaves(trainer.unstack_params(state))
    flat_b = jax.tree.leaves(gp)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_bf16_comm_dtype_close_to_full_precision():
    """comm_dtype=bfloat16 halves wire bytes; the result must track the
    full-precision allreduce within bf16 rounding (bf16 keeps f32's
    exponent range, so no scale factor is involved)."""
    model = MLP(features=(16, NCLASS))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    loss_fn = _loss_fn(model)
    xs, ys = _data(steps=3, seed=5)

    outs = {}
    for dtype in (None, jnp.bfloat16):
        trainer = BaguaTrainer(
            loss_fn, optax.sgd(0.1),
            GradientAllReduceAlgorithm(comm_dtype=dtype), bucket_bytes=256,
        )
        st = trainer.init(params)
        for s in range(xs.shape[0]):
            st, _ = trainer.train_step(st, {"x": xs[s], "y": ys[s]})
        outs[dtype] = st.params

    for a, b in zip(jax.tree.leaves(outs[jnp.bfloat16]), jax.tree.leaves(outs[None])):
        a, b = np.asarray(a), np.asarray(b)
        # bf16 has ~3 decimal digits; after 3 SGD steps the drift stays
        # within a few bf16 ulps of the weight scale
        np.testing.assert_allclose(a, b, rtol=0, atol=3e-2)


def test_bf16_comm_dtype_hierarchical():
    """comm_dtype composes with the hierarchical (intra -> inter) path:
    both allreduce stages run on the cast buffer, result tracks full
    precision within bf16 rounding."""

    from bagua_tpu.parallel.mesh import hierarchical_mesh

    model = MLP(features=(16, NCLASS))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    loss_fn = _loss_fn(model)
    xs, ys = _data(steps=3, seed=11)

    outs = {}
    for dtype in (None, jnp.bfloat16):
        trainer = BaguaTrainer(
            loss_fn, optax.sgd(0.1),
            GradientAllReduceAlgorithm(hierarchical=True, comm_dtype=dtype),
            mesh=hierarchical_mesh(intra_size=4), bucket_bytes=256,
        )
        st = trainer.init(params)
        for s in range(xs.shape[0]):
            st, _ = trainer.train_step(st, {"x": xs[s], "y": ys[s]})
        outs[dtype] = st.params

    # anchor the nontrivial 2x4 hierarchical topology to a flat-mesh golden
    # (avg-of-avg over equal groups == global avg); the bf16 run is then
    # compared against the anchored full-precision run
    flat = BaguaTrainer(
        loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
        bucket_bytes=256,
    )
    st = flat.init(params)
    for s in range(xs.shape[0]):
        st, _ = flat.train_step(st, {"x": xs[s], "y": ys[s]})
    for a, b in zip(jax.tree.leaves(outs[None]), jax.tree.leaves(st.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)

    for a, b in zip(jax.tree.leaves(outs[jnp.bfloat16]), jax.tree.leaves(outs[None])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0, atol=3e-2)


def test_sum_vs_avg_scales_update():
    model = MLP(features=(8, NCLASS))
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, DIM)))["params"]
    loss_fn = _loss_fn(model)
    xs, ys = _data(steps=1, seed=3)
    batch = {"x": xs[0], "y": ys[0]}

    outs = {}
    for avg in (True, False):
        trainer = BaguaTrainer(
            loss_fn, optax.sgd(0.05), GradientAllReduceAlgorithm(average=avg)
        )
        st = trainer.init(params)
        st, _ = trainer.train_step(st, batch)
        outs[avg] = trainer.unstack_params(st)

    # delta with SUM should be N times delta with AVG
    d_avg = jax.tree.map(lambda a, b: np.asarray(a - b), outs[True], params)
    d_sum = jax.tree.map(lambda a, b: np.asarray(a - b), outs[False], params)
    for a, b in zip(jax.tree.leaves(d_avg), jax.tree.leaves(d_sum)):
        np.testing.assert_allclose(b, N * a, rtol=1e-4, atol=1e-5)
