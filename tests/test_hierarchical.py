"""DCN-aware hierarchical bucket collectives (ISSUE 11).

Pinned contracts:

* the two-level decomposition (slice-local reduce-scatter -> cross-slice
  allreduce on the 1/intra shard -> slice-local allgather) matches the flat
  fused allreduce numerically on the (2-slice x 4-chip) cpu-sim mesh — the
  only difference is sum association order, so the comparison is
  tight-tolerance, while END-TO-END loss trajectories are BIT-equal for the
  sgd-family (allreduce, zero) on this pinned workload/horizon (the
  last-ulp gradient drift stays below f32 loss resolution for these 5
  steps — deterministic here, but heavier workloads accumulate an ulp:
  the drive script pins <=1e-5 relative over 40 steps) and within
  quantization tolerance for bytegrad;
* the DCN tier carries ~1/intra_size of the flat path's bytes (jaxpr byte
  accounting — exact on any platform);
* per-tier ring chunking is layout-symmetric with the fused primitives and
  with itself across the scatter/gather pair;
* overlap-vs-serialized stays bit-identical under the hierarchical path;
* ``overlap="off"`` + non-hierarchical construction contains no tiered
  collectives (HLO pin);
* the per-tier chunk knobs ride the env registry, the autotune
  recommendation path, and the step-cache key;
* ``get_backend`` invalidates its cache when the global mesh changes
  (elastic resize / ``set_global_mesh``);
* ``ring_chunks_for`` handles prime/pathological per-rank blocks in
  O(sqrt(m)) via the direct largest-divisor computation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from bagua_tpu import BaguaTrainer
from bagua_tpu.algorithms import (
    ByteGradAlgorithm,
    GradientAllReduceAlgorithm,
    ZeroOptimizerAlgorithm,
)
from bagua_tpu.algorithms.base import AlgorithmContext
from bagua_tpu.communication import (
    LINK_DCN,
    LINK_ICI,
    MAX_RING_CHUNKS,
    BaguaCommunicator,
    ReduceOp,
    collapse_trivial_axes,
    largest_divisor_leq,
    ring_chunks_for,
)
from bagua_tpu.compat import shard_map
from bagua_tpu.models import MLP
from bagua_tpu.parallel.mesh import build_mesh

N = 8
INTRA = 4
INTER = 2
DIM = 12
NCLASS = 10
MODEL = MLP(features=(16, NCLASS))


def _loss_fn(params, batch):
    logits = MODEL.apply({"params": params}, batch["x"])
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["y"]
    ).mean()


def _hier_mesh():
    return build_mesh({"inter": INTER, "intra": INTRA})


def _ctx(mesh, **kw):
    class _EmptyPlan:
        buckets = []

    comm = BaguaCommunicator(
        collapse_trivial_axes(mesh, ("inter", "intra")), mesh
    )
    return AlgorithmContext(
        comm=comm,
        internode=BaguaCommunicator("inter", mesh),
        intranode=BaguaCommunicator("intra", mesh),
        plan=kw.pop("plan", _EmptyPlan()),
        world_size=N,
        **kw,
    )


def _run(mesh, fn, x):
    spec = P(("inter", "intra"))
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=(spec,), out_specs=spec,
                  check_vma=False)
    )(x)


# ---- divisor search (satellite: O(sqrt(m)) largest divisor) ------------


def test_largest_divisor_leq():
    assert largest_divisor_leq(12, 12) == 12
    assert largest_divisor_leq(12, 100) == 12
    assert largest_divisor_leq(12, 5) == 4
    assert largest_divisor_leq(128, 10) == 8
    # primes: the only divisor <= k < m is 1
    assert largest_divisor_leq(127, 126) == 1
    assert largest_divisor_leq(104729, 104728) == 1
    assert largest_divisor_leq(1, 5) == 1
    # perfect square (the i*i == m edge of the enumeration)
    assert largest_divisor_leq(49, 7) == 7
    assert largest_divisor_leq(49, 6) == 1
    # semiprime with a large factor
    assert largest_divisor_leq(2 * 104729, 104729) == 104729
    assert largest_divisor_leq(2 * 104729, 104728) == 2


def test_ring_chunks_for_prime_and_pathological_sizes():
    # prime per-rank block: the old O(m) `k -= 1` scan walked every
    # candidate; the divisor computation answers directly (and the answer
    # for any k < m is 1 — a prime block cannot be split evenly)
    assert ring_chunks_for(8 * 104729, 4, 8, 4) == 1
    assert ring_chunks_for(1016, 4, 8, 4) == 1          # m = 127, prime
    # highly composite block still sizes normally
    assert ring_chunks_for(1024, 4, 8, 128) == 4
    assert ring_chunks_for(1024, 4, 8, 512) == 1
    # indivisible buffers size against the ring's internal zero-padding
    assert ring_chunks_for(1023, 4, 8, 64) == 8
    # the compile-size cap still binds
    assert ring_chunks_for(800_000, 4, 8, 16) <= MAX_RING_CHUNKS
    # every answer divides the (padded) per-rank block
    for numel in (1016, 1023, 997 * 8, 123456):
        for chunk in (4, 64, 1000):
            k = ring_chunks_for(numel, 4, 8, chunk)
            m = -(-numel // 8)
            assert m % k == 0


def test_ring_chunks_for_link_class_mapping():
    # a mapping chunk target resolves per link class; ints apply anywhere
    targets = {LINK_ICI: 128, LINK_DCN: 512}
    assert ring_chunks_for(1024, 4, 8, targets, LINK_ICI) == 4
    assert ring_chunks_for(1024, 4, 8, targets, LINK_DCN) == 1
    assert ring_chunks_for(1024, 4, 8, targets, "unknown") == 1
    assert ring_chunks_for(1024, 4, 8, 128, LINK_DCN) == 4


def test_ctx_chunk_bytes_per_tier_fallback():
    mesh = _hier_mesh()
    ctx = _ctx(mesh, overlap=True, overlap_chunk_bytes=64,
               intra_chunk_bytes=32, inter_chunk_bytes=256)
    assert ctx.chunk_bytes_for(LINK_ICI) == 32
    assert ctx.chunk_bytes_for(LINK_DCN) == 256
    # unset tier knobs fall back to the link-agnostic target
    ctx2 = _ctx(mesh, overlap=True, overlap_chunk_bytes=64)
    assert ctx2.chunk_bytes_for(LINK_ICI) == 64
    assert ctx2.chunk_bytes_for(LINK_DCN) == 64


# ---- two-level decomposition vs the flat fused allreduce ---------------


@pytest.mark.parametrize("size", [64, 50, 7])
@pytest.mark.parametrize("op", [ReduceOp.AVG, ReduceOp.SUM])
def test_two_level_allreduce_matches_flat(op, size):
    """The decomposition computes the same reduction as the flat psum —
    tight tolerance: the tiers change only the sum association order
    (indivisible sizes exercise the internal zero-padding)."""
    mesh = _hier_mesh()
    ctx = _ctx(mesh)
    assert ctx.two_tier()
    x = np.random.default_rng(0).normal(size=(N, size)).astype(np.float32)
    flat = _run(mesh, lambda v: ctx.comm.allreduce(v[0], op)[None], x)
    two = _run(
        mesh, lambda v: ctx.hierarchical_allreduce(v[0], op, True)[None], x
    )
    assert np.asarray(two).shape == np.asarray(flat).shape
    np.testing.assert_allclose(
        np.asarray(two), np.asarray(flat), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("intra_chunk,inter_chunk",
                         [(32, 0), (0, 16), (32, 16)])
def test_two_level_per_tier_ring_matches_fused(intra_chunk, inter_chunk):
    """Per-tier ring chunking (either tier, or both) reproduces the fused
    two-level result — the ring-vs-psum layout symmetry per tier."""
    mesh = _hier_mesh()
    fused = _ctx(mesh)
    ringed = _ctx(mesh, overlap=True,
                  intra_chunk_bytes=intra_chunk or None,
                  inter_chunk_bytes=inter_chunk or None)
    x = np.random.default_rng(1).normal(size=(N, 64)).astype(np.float32)
    a = _run(mesh, lambda v: fused.hierarchical_allreduce(
        v[0], ReduceOp.AVG, True)[None], x)
    b = _run(mesh, lambda v: ringed.hierarchical_allreduce(
        v[0], ReduceOp.AVG, True)[None], x)
    np.testing.assert_allclose(
        np.asarray(b), np.asarray(a), rtol=1e-6, atol=1e-6
    )


def test_tier_scatter_gather_pair_is_layout_symmetric():
    """tier_reduce_scatter -> tier_allgather round-trips to the intra
    psum average under ring chunking, and the chunked tier_allgather is
    EXACTLY the fused all_gather (pure data movement)."""
    mesh = _hier_mesh()
    ctx = _ctx(mesh, overlap=True, intra_chunk_bytes=32)
    fused = _ctx(mesh)
    x = np.random.default_rng(2).normal(size=(N, 64)).astype(np.float32)

    def pair(v):
        chunk = ctx.tier_reduce_scatter(v[0], ReduceOp.AVG)
        return ctx.tier_allgather(chunk)[None]

    out = _run(mesh, pair, x)
    # each slice row averages ITS slice's 4 rows (intra average)
    want = x.reshape(INTER, INTRA, 64).mean(axis=1, keepdims=True)
    want = np.broadcast_to(want, (INTER, INTRA, 64)).reshape(N, 64)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6, atol=1e-6)

    # gather stage alone: chunked ring == fused all_gather, bit-exact
    y = np.random.default_rng(3).normal(size=(N, 16)).astype(np.float32)
    ringed = _run(mesh, lambda v: ctx.tier_allgather(v[0])[None], y)
    plain = _run(mesh, lambda v: fused.tier_allgather(v[0])[None], y)
    np.testing.assert_array_equal(np.asarray(ringed), np.asarray(plain))


# ---- end-to-end: two-tier vs flat training equivalence -----------------


def _train(algo_factory, optimizer, accum, hierarchical, overlap="off",
           steps=5, **kw):
    trainer = BaguaTrainer(
        _loss_fn, optimizer, algo_factory(hierarchical), mesh=_hier_mesh(),
        bucket_bytes=256, accum_steps=accum, overlap=overlap,
        autotune=False, **kw,
    )
    params = MODEL.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    state = trainer.init(params)
    rng = np.random.default_rng(7)
    losses = []
    for _ in range(steps):
        batch = {
            "x": rng.normal(size=(N * 2 * accum, DIM)).astype(np.float32),
            "y": rng.integers(0, NCLASS, size=(N * 2 * accum,)).astype(
                np.int32
            ),
        }
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    return np.array(losses), state, trainer


@pytest.mark.parametrize("accum", [1, 4])
@pytest.mark.parametrize(
    "algo_factory,optimizer,exact",
    [
        (lambda h: GradientAllReduceAlgorithm(hierarchical=h),
         optax.sgd(0.1), True),
        (lambda h: ZeroOptimizerAlgorithm(optax.adam(1e-2), hierarchical=h),
         None, True),
        # the DCN-stage codec quantizes the 1/intra shard instead of the
        # whole bucket, so the 8-bit levels differ from the flat path's
        (lambda h: ByteGradAlgorithm(hierarchical=h), optax.sgd(0.1), False),
    ],
    ids=["gradient_allreduce", "zero", "bytegrad"],
)
def test_two_tier_matches_flat_trajectory(algo_factory, optimizer, exact,
                                          accum):
    l_flat, st_flat, tr_flat = _train(algo_factory, optimizer, accum, False)
    l_two, st_two, tr_two = _train(algo_factory, optimizer, accum, True)
    if exact:
        # sgd-family loss trajectories are BIT-equal on this pinned
        # workload (params drift only in the last ulp from sum
        # association; over these 5 steps the scalar losses coincide
        # bitwise — deterministic for fixed seeds on this platform)
        np.testing.assert_array_equal(l_two, l_flat)
        for a, b in zip(jax.tree.leaves(tr_two.unstack_params(st_two)),
                        jax.tree.leaves(tr_flat.unstack_params(st_flat))):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    else:
        np.testing.assert_allclose(l_two, l_flat, rtol=0.05, atol=0.02)


@pytest.mark.parametrize(
    "algo_factory,optimizer",
    [
        (lambda h: GradientAllReduceAlgorithm(hierarchical=h),
         optax.sgd(0.1)),
        (lambda h: ZeroOptimizerAlgorithm(optax.adam(1e-2), hierarchical=h),
         None),
    ],
    ids=["gradient_allreduce", "zero"],
)
def test_hierarchical_overlap_matches_serialized(algo_factory, optimizer):
    """Overlap-vs-serialized stays BIT-identical under the hierarchical
    path (one reduce_bucket_grad implementation, launch reordering never
    changes the per-bucket math)."""
    l_off, _, _ = _train(algo_factory, optimizer, 4, True, overlap="off")
    l_on, _, tr_on = _train(algo_factory, optimizer, 4, True, overlap="on")
    assert tr_on._overlap_active()
    np.testing.assert_array_equal(l_on, l_off)


def test_hierarchical_per_tier_chunked_end_to_end():
    """Per-tier ring chunking trains the fused two-level trajectory within
    float tolerance (ring reduction order differs per tier)."""
    l_fused, _, _ = _train(
        lambda h: GradientAllReduceAlgorithm(hierarchical=h),
        optax.sgd(0.1), 4, True, overlap="on",
    )
    l_ring, _, tr = _train(
        lambda h: GradientAllReduceAlgorithm(hierarchical=h),
        optax.sgd(0.1), 4, True, overlap="on",
        overlap_chunk_bytes_intra=64, overlap_chunk_bytes_inter=32,
    )
    assert tr._overlap_active()
    np.testing.assert_allclose(l_ring, l_fused, rtol=1e-5, atol=1e-6)


# ---- DCN byte accounting (the decomposition's reason to exist) ---------


def _tier_wire_bytes(trainer, state, batch):
    """(dcn_bytes, ici_bytes) of one traced step: jaxpr collective
    operands classified by axis — anything spanning ``inter`` crosses the
    slice boundary."""
    from bagua_tpu.analysis.jaxpr_check import iter_collectives

    jaxpr = trainer.trace_step(state, batch)
    dcn = ici = 0
    for c in iter_collectives(jaxpr):
        if "inter" in c.axes:
            dcn += c.nbytes
        else:
            ici += c.nbytes
    return dcn, ici


def test_dcn_bytes_reduced_to_shard():
    """The flat path moves every bucket's FULL bytes across the slice
    boundary; the two-level path moves the 1/intra_size shard (+ the
    4-byte loss reduction) — the acceptance ratio of ISSUE 11."""
    def build(hierarchical):
        trainer = BaguaTrainer(
            _loss_fn, optax.sgd(0.1),
            GradientAllReduceAlgorithm(hierarchical=hierarchical),
            mesh=_hier_mesh(), bucket_bytes=256, autotune=False,
            overlap="off",
        )
        params = MODEL.init(
            jax.random.PRNGKey(0), jnp.zeros((1, DIM))
        )["params"]
        state = trainer.init(params)
        rng = np.random.default_rng(0)
        batch = trainer.shard_batch({
            "x": rng.normal(size=(N * 2, DIM)).astype(np.float32),
            "y": rng.integers(0, NCLASS, size=(N * 2,)).astype(np.int32),
        })
        return trainer, state, batch

    dcn_flat, _ = _tier_wire_bytes(*build(False))
    dcn_two, ici_two = _tier_wire_bytes(*build(True))
    loss_scalar_bytes = 4
    assert dcn_two - loss_scalar_bytes <= (
        (dcn_flat - loss_scalar_bytes) / INTRA
    ) * 1.01 + 8  # +8: per-bucket intra-padding slack
    # and the ICI tiers took over the heavy lifting
    assert ici_two > dcn_two


def test_non_hierarchical_off_construction_has_no_tiered_collectives():
    """HLO pin: the non-hierarchical ``overlap="off"`` construction is
    untouched by the tier machinery — no reduce-scatter/all-gather stages
    appear (one fused all-reduce per bucket), and setting the per-tier
    knobs without overlap changes nothing (they are nulled outside the
    overlap scheduler, same as the link-agnostic knob)."""
    def hlo(**kw):
        trainer = BaguaTrainer(
            _loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
            mesh=_hier_mesh(), bucket_bytes=256, overlap="off",
            autotune=False, **kw,
        )
        params = MODEL.init(
            jax.random.PRNGKey(0), jnp.zeros((1, DIM))
        )["params"]
        state = trainer.init(params)
        rng = np.random.default_rng(0)
        batch = trainer.shard_batch({
            "x": rng.normal(size=(N * 2, DIM)).astype(np.float32),
            "y": rng.integers(0, NCLASS, size=(N * 2,)).astype(np.int32),
        })
        return trainer._get_step_fn().lower(state, batch).as_text()

    plain = hlo()
    assert "reduce-scatter" not in plain
    assert "collective-permute" not in plain
    knobbed = hlo(overlap_chunk_bytes_intra=64, overlap_chunk_bytes_inter=32)
    assert knobbed == plain


# ---- bandwidth-tier-aware overlap scheduling ---------------------------


def test_bucket_launch_order_streams_dcn_dominant_first():
    from bagua_tpu.bucket import BucketPlan
    from bagua_tpu.tensor import build_params

    params = {
        "a": jnp.zeros((8,), jnp.float32),
        "b": jnp.zeros((256,), jnp.float32),
        "c": jnp.zeros((64,), jnp.float32),
    }
    named = build_params(params)
    plan = BucketPlan.from_declaration_buckets(
        [[p.declaration()] for p in named], named, alignment=1
    )
    mesh = _hier_mesh()
    ctx = _ctx(mesh, plan=plan, overlap=True)
    sizes = [b.padded_numel for b in plan.buckets]
    want = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    assert ctx.bucket_launch_order(True) == want
    # plan (readiness) order everywhere else: serialized, non-hierarchical
    assert ctx.bucket_launch_order(False) == list(range(len(sizes)))
    serialized = _ctx(mesh, plan=plan, overlap=False)
    assert serialized.bucket_launch_order(True) == list(range(len(sizes)))
    # tier byte estimates: the DCN stage carries the 1/intra shard
    tiers = ctx.bucket_tier_bytes(want[0], True)
    assert tiers["tier"] == "two_level"
    assert tiers["dcn_bytes"] <= tiers["bytes"] // INTRA
    flat_tiers = ctx.bucket_tier_bytes(want[0], False)
    assert flat_tiers["tier"] == "flat"
    assert flat_tiers["dcn_bytes"] > tiers["dcn_bytes"]


def test_two_level_launch_spans_record_tier():
    """The streamed schedule's spans carry tier + per-tier bytes so
    obs/attribution can split device comm seconds into ICI vs DCN."""
    from bagua_tpu.obs import spans as obs_spans
    from bagua_tpu.obs.attribution import bucket_launches_from_ring

    obs_spans.recorder.clear()
    _train(lambda h: GradientAllReduceAlgorithm(hierarchical=h),
           optax.sgd(0.1), 4, True, overlap="on", steps=1)
    launches = bucket_launches_from_ring()
    assert launches, "overlap scheduler recorded no bucket launches"
    assert all(l["tier"] == "two_level" for l in launches)
    assert all(l["dcn_bytes"] <= l["bytes"] // INTRA for l in launches)
    # DCN-dominant-first: the recorded launch order is descending DCN bytes
    dcn = [l["dcn_bytes"] for l in launches]
    assert dcn == sorted(dcn, reverse=True)
    obs_spans.recorder.clear()


# ---- knobs: env/step-cache/autotune plumbing ---------------------------


def test_step_key_includes_tier_knobs_only_under_overlap():
    _, _, tr = _train(lambda h: GradientAllReduceAlgorithm(hierarchical=h),
                      optax.sgd(0.1), 4, True, overlap="on", steps=1)
    key_before = tr._step_key()
    tr.overlap_chunk_bytes_inter = 12345
    assert tr._step_key() != key_before
    _, _, tr_off = _train(
        lambda h: GradientAllReduceAlgorithm(hierarchical=h),
        optax.sgd(0.1), 1, True, overlap="off", steps=1,
    )
    key_off = tr_off._step_key()
    tr_off.overlap_chunk_bytes_inter = 12345
    assert tr_off._step_key() == key_off


def test_recommendation_path_carries_tier_knobs():
    from bagua_tpu.define import BaguaHyperparameter
    from bagua_tpu.service.autotune_task_manager import AutotuneTaskManager

    trainer = BaguaTrainer(
        _loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
        mesh=_hier_mesh(), bucket_bytes=256, overlap="off", autotune=False,
    )
    params = MODEL.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    trainer.init(params)
    trainer._apply_recommendation(BaguaHyperparameter(
        overlap="on", overlap_chunk_bytes_intra=4096,
        overlap_chunk_bytes_inter=1 << 20, is_hierarchical_reduce=True,
    ))
    assert trainer.overlap_chunk_bytes_intra == 4096
    assert trainer.overlap_chunk_bytes_inter == 1 << 20
    assert trainer.algorithm.hierarchical is True
    # 0 keeps the current values
    trainer._apply_recommendation(
        BaguaHyperparameter(is_hierarchical_reduce=True)
    )
    assert trainer.overlap_chunk_bytes_intra == 4096
    assert trainer.overlap_chunk_bytes_inter == 1 << 20
    hp = trainer._current_hyperparameters()
    assert hp.overlap_chunk_bytes_intra == 4096
    assert hp.overlap_chunk_bytes_inter == 1 << 20
    assert hp.is_hierarchical_reduce is True
    # the service's next materialized recommendation carries them through
    mgr = AutotuneTaskManager("t", is_output_autotune_log=False)
    decls = [t.declaration() for b in trainer._plan.buckets
             for t in b.tensors]
    nxt = mgr.ask_hyperparameters(100, decls, hp, 1.0)
    assert nxt.overlap_chunk_bytes_intra == 4096
    assert nxt.overlap_chunk_bytes_inter == 1 << 20


def test_tier_knobs_opt_into_overlap_and_env_registry():
    from bagua_tpu import env as env_mod

    for var in ("BAGUA_OVERLAP_CHUNK_BYTES_INTRA",
                "BAGUA_OVERLAP_CHUNK_BYTES_INTER"):
        assert var in env_mod.ENV_REGISTRY
    # a per-tier knob is an explicit opt-in to the ring path at accum==1,
    # like the link-agnostic knob
    trainer = BaguaTrainer(
        _loss_fn, optax.sgd(0.1),
        GradientAllReduceAlgorithm(hierarchical=True), mesh=_hier_mesh(),
        bucket_bytes=256, overlap_chunk_bytes_inter=4096, autotune=False,
    )
    params = MODEL.init(jax.random.PRNGKey(0), jnp.zeros((1, DIM)))["params"]
    trainer.init(params)
    assert trainer._overlap_active()


# ---- get_backend cache invalidation (satellite) ------------------------


def test_get_backend_invalidated_on_mesh_change():
    from bagua_tpu import communication
    from bagua_tpu.parallel.mesh import set_global_mesh

    mesh_a = _hier_mesh()
    set_global_mesh(mesh_a)
    be_a = communication.get_backend("m")
    assert be_a.mesh is mesh_a
    # same registered mesh: the cache holds (no rebuild per call)
    assert communication.get_backend("m") is be_a
    # an elastic resize / set_global_mesh re-registers a NEW mesh object:
    # the cached backend spans the dead topology and must be rebuilt
    mesh_b = build_mesh({"dp": N})
    set_global_mesh(mesh_b)
    be_b = communication.get_backend("m")
    assert be_b is not be_a
    assert be_b.mesh is mesh_b
    assert be_b.global_communicator.mesh is mesh_b


# ---- device-time attribution: per-tier split ---------------------------


def _two_level_xplane(tmp_path, n_steps=2, buckets=((4096, 1024),
                                                    (2048, 512)),
                      phase_split=False):
    """Synthetic TPU plane for a two-level schedule.  Default: per step
    and bucket, three comm occurrences in issue order — ICI
    reduce-scatter, DCN allreduce, ICI allgather (rs/ag sized by the
    bucket, the DCN stage by its shard).  ``phase_split=True`` emits the
    ZeRO-hierarchical shape instead: all (rs, ar) pairs in the backward
    window, then all allgathers in the optimizer phase."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    em = plane.event_metadata
    em[1].id = 1
    em[1].name = "reduce-scatter-start.1"
    em[2].id = 2
    em[2].name = "all-reduce-start.2"
    em[3].id = 3
    em[3].name = "all-gather-start.3"
    steps = plane.lines.add(name="Steps")
    for _ in range(n_steps):
        ev = steps.events.add()
        ev.duration_ps = int(0.010e12)
    ops = plane.lines.add(name="XLA Ops")
    t = 0

    def _emit(mid, nbytes):
        nonlocal t
        ev = ops.events.add()
        ev.metadata_id = mid
        ev.offset_ps = t
        ev.duration_ps = int(nbytes * 1e5)
        t += ev.duration_ps

    for _ in range(n_steps):
        if phase_split:
            for full, shard in buckets:
                _emit(1, full)
                _emit(2, shard)
            for full, _ in buckets:
                _emit(3, full)
        else:
            for full, shard in buckets:
                for mid, nbytes in ((1, full), (2, shard), (3, full)):
                    _emit(mid, nbytes)
    (tmp_path / "hier.xplane.pb").write_bytes(xs.SerializeToString())


def test_attribution_splits_two_level_schedule_per_tier(tmp_path):
    from bagua_tpu.obs import export as obs_export
    from bagua_tpu.obs.attribution import attribute_device_comm

    _two_level_xplane(tmp_path)
    launches = [
        {"bucket": 0, "bytes": 4096, "tier": "two_level",
         "ici_bytes": 2 * 4096, "dcn_bytes": 1024},
        {"bucket": 1, "bytes": 2048, "tier": "two_level",
         "ici_bytes": 2 * 2048, "dcn_bytes": 512},
    ]
    out = attribute_device_comm(str(tmp_path), bucket_launches=launches)
    assert out["available"] is True
    per = {b["bucket"]: b for b in out["per_bucket"]}
    # stage durations were synthesized proportional to bytes: rs+ag = 2x
    # the full bucket, the DCN allreduce = the shard
    assert per[0]["device_ici_s"] == pytest.approx(2 * 4096 * 1e5 / 1e12)
    assert per[0]["device_dcn_s"] == pytest.approx(1024 * 1e5 / 1e12)
    assert per[0]["device_comm_s"] == pytest.approx(
        per[0]["device_ici_s"] + per[0]["device_dcn_s"])
    assert out["comm_dcn_s_per_step"] == pytest.approx(
        (1024 + 512) * 1e5 / 1e12)
    assert out["comm_ici_s_per_step"] == pytest.approx(
        2 * (4096 + 2048) * 1e5 / 1e12)
    # the gauges + obs summary carry the split
    obs_export.reset_local_summary()
    try:
        obs_export.note_step(5, 0.01)
        obs_export.note_device_attribution(out)
        summary = obs_export.local_obs_summary()
        assert summary["device_comm_dcn_s_per_step"] == pytest.approx(
            out["comm_dcn_s_per_step"])
        assert summary["device_comm_ici_s_per_step"] == pytest.approx(
            out["comm_ici_s_per_step"])
        from bagua_tpu.telemetry import counters

        snap = counters.snapshot()
        assert snap["obs/device_comm_dcn_s_per_step"] == pytest.approx(
            out["comm_dcn_s_per_step"])
    finally:
        obs_export.reset_local_summary()


def test_attribution_phase_split_schedule_degrades_per_bucket_only(tmp_path):
    """ZeRO-hierarchical issues all (rs, ar) pairs in the backward window
    and the allgathers later in the optimizer phase — NOT contiguous
    per-bucket triples.  The per-bucket positional split must degrade
    (rationale, never a mis-attribution), while the per-tier totals still
    report correctly: they classify by op NAME, not position."""
    from bagua_tpu.obs.attribution import attribute_device_comm

    _two_level_xplane(tmp_path, phase_split=True)
    launches = [
        {"bucket": 0, "bytes": 4096, "tier": "two_level",
         "ici_bytes": 2 * 4096, "dcn_bytes": 1024},
        {"bucket": 1, "bytes": 2048, "tier": "two_level",
         "ici_bytes": 2 * 2048, "dcn_bytes": 512},
    ]
    out = attribute_device_comm(str(tmp_path), bucket_launches=launches)
    assert out["available"] is True
    assert out["per_bucket"] is None
    assert "contiguous" in out["per_bucket_rationale"]
    # name-classified tier totals are order-independent and stay exact
    assert out["comm_dcn_s_per_step"] == pytest.approx(
        (1024 + 512) * 1e5 / 1e12)
    assert out["comm_ici_s_per_step"] == pytest.approx(
        2 * (4096 + 2048) * 1e5 / 1e12)


def test_attribution_two_level_mismatch_degrades_with_rationale(tmp_path):
    from bagua_tpu.obs.attribution import attribute_device_comm

    _two_level_xplane(tmp_path)
    # three launches cannot positionally absorb 2 buckets x 3 stages
    launches = [
        {"bucket": i, "bytes": 64, "tier": "two_level",
         "ici_bytes": 128, "dcn_bytes": 16}
        for i in range(3)
    ]
    out = attribute_device_comm(str(tmp_path), bucket_launches=launches)
    assert out["available"] is True and out["per_bucket"] is None
    assert "do not map" in out["per_bucket_rationale"]
