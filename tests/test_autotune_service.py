"""Autotune service tests — cluster-free, driven over real HTTP with mock
clients and a synthetic score function (reference
tests/service/test_autotune_service.py:29-95)."""

import math
import threading
import time

import pytest

from bagua_tpu.define import BaguaHyperparameter, TensorDeclaration, TensorDtype
from bagua_tpu.service.autotune_service import (
    AutotuneClient,
    AutotuneService,
    make_server,
)
from bagua_tpu.service.bayesian_optimizer import (
    BayesianOptimizer,
    BoolParam,
    IntParam,
)


def synthetic_score(bucket_size: int, is_hierarchical: bool) -> float:
    """Concave in log2(bucket_size), peaked at 20 MB, small hierarchical
    penalty — same shape as the reference's mock."""
    peak = math.log2(20 * 1024 ** 2)
    x = math.log2(max(bucket_size, 1))
    return 1000.0 - 10.0 * (x - peak) ** 2 - (50.0 if is_hierarchical else 0.0)


def tensor_list(n=20, numel=250_000):
    return [
        TensorDeclaration(name=f"p{i}", num_elements=numel, dtype=TensorDtype.F32)
        for i in range(n)
    ]


def test_bayesian_optimizer_converges():
    opt = BayesianOptimizer(
        [IntParam("x", 10, 31), BoolParam("h")], n_initial_points=8
    )
    f = lambda p: -((p["x"] - 24) ** 2) - (5 if p["h"] else 0)
    for _ in range(50):
        p = opt.ask()
        opt.tell(p, f(p))
    best, _ = opt.best()
    assert abs(best["x"] - 24) <= 2
    assert best["h"] is False


@pytest.fixture()
def service_client():
    service = AutotuneService(
        world_size=2,
        autotune_level=1,
        max_samples=40,
        sampling_confidence_time_s=0.0,
        warmup_time_s=0.0,
        default_bucket_size=10 * 1024 ** 2,
    )
    server = make_server(0, service)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    client = AutotuneClient("127.0.0.1", port)
    client.wait_until_ready(10)
    yield service, client
    server.shutdown()


def test_autotune_http_end_to_end(service_client):
    service, client = service_client
    decls = [t.model_dump() for t in tensor_list()]
    rsp = client.register_tensors("m", decls)
    hp = BaguaHyperparameter(**rsp["recommended_hyperparameters"])
    assert hp.buckets, "initial bucketing should partition registered tensors"
    names = [t.name for b in hp.buckets for t in b]
    assert sorted(names) == sorted(d["name"] for d in decls)

    train_iter = 0
    completed = False
    # the all-ranks confidence gate admits a sample at most every other
    # round, so allow 2x max_samples rounds plus slack
    for sample in range(120):
        train_iter += 1
        score = synthetic_score(hp.bucket_size, hp.is_hierarchical_reduce)
        for rank in range(2):
            client.report_metrics("m", rank, train_iter, hp.model_dump(), score / 2)
        for rank in range(2):
            rsp = client.ask_hyperparameters("m", rank, train_iter)
        hp = BaguaHyperparameter(**rsp["recommended_hyperparameters"])
        if rsp["is_autotune_completed"]:
            completed = True
            break
    assert completed
    # converged near the synthetic peak (20 MB = 2^~24.3; accept 2^22..2^27)
    assert 2 ** 22 <= hp.bucket_size <= 2 ** 27, hp.bucket_size
    assert hp.is_hierarchical_reduce is False


def test_execution_order_reorders_buckets(service_client):
    service, client = service_client
    decls = [t.model_dump() for t in tensor_list(n=6, numel=100)]
    client.register_tensors("m2", decls)
    order = ["p5", "p3", "p1", "p0", "p2", "p4"]
    spans = [
        {"trace_id": i, "action": "tensor_ready", "tensor_name": n,
         "start_time": i, "end_time": i + 1}
        for i, n in enumerate(order)
    ]
    client.report_tensor_execution_order(spans, model_name="m2")
    task = service._task("m2")
    hp = task.manager.ask_hyperparameters(
        1, task.tensor_list, task.recommended, None
    )
    names = [t.name for b in hp.buckets for t in b]
    assert names == order


def test_same_round_same_recommendation(service_client):
    """All ranks asking at the same train_iter MUST get identical replies,
    else their compiled SPMD programs diverge and collectives deadlock."""
    service, client = service_client
    decls = [t.model_dump() for t in tensor_list(n=8, numel=1000)]
    client.register_tensors("mr", decls)
    for it in range(1, 12):
        for rank in range(2):
            client.report_metrics("mr", rank, it, {}, 100.0)
        replies = [
            client.ask_hyperparameters("mr", rank, it) for rank in range(2)
        ]
        assert replies[0] == replies[1], f"divergent replies at iter {it}"


def test_algorithm_family_tuning():
    """With tune_algorithm on, the optimizer searches over families and the
    best one wins (bytegrad scores higher in this synthetic)."""
    service = AutotuneService(
        world_size=1, autotune_level=1, max_samples=30,
        sampling_confidence_time_s=0.0, warmup_time_s=0.0,
        tune_algorithm=True,
    )
    server = make_server(0, service)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = AutotuneClient("127.0.0.1", port)
    client.wait_until_ready(10)
    decls = [t.model_dump() for t in tensor_list(n=8, numel=1000)]
    rsp = client.register_tensors("ma", decls)
    hp = BaguaHyperparameter(**rsp["recommended_hyperparameters"])
    for it in range(1, 40):
        score = 100.0 + (50.0 if hp.algorithm == "bytegrad" else 0.0)
        client.report_metrics("ma", 0, it, hp.model_dump(), score)
        rsp = client.ask_hyperparameters("ma", 0, it)
        hp = BaguaHyperparameter(**rsp["recommended_hyperparameters"])
        if rsp["is_autotune_completed"]:
            break
    assert rsp["is_autotune_completed"]
    assert hp.algorithm == "bytegrad"
    server.shutdown()


def test_trainer_switches_algorithm():
    """Trainer swaps gradient_allreduce -> bytegrad on recommendation and
    keeps training (state layout unchanged)."""
    import jax
    import jax.numpy as jnp
    import optax

    from bagua_tpu.algorithms.gradient_allreduce import GradientAllReduceAlgorithm
    from bagua_tpu.core.backend import BaguaTrainer
    from bagua_tpu.models.mlp import MLP
    from bagua_tpu.parallel.mesh import build_mesh

    model = MLP(features=(16, 8))
    mesh = build_mesh({"dp": 8})
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    y = jnp.argmax(x @ jax.random.normal(jax.random.PRNGKey(1), (4, 8)), -1)
    params = model.init(jax.random.PRNGKey(2), x[:2])["params"]

    def loss_fn(p, batch):
        import optax as _o
        logits = model.apply({"params": p}, batch["x"])
        return _o.softmax_cross_entropy_with_integer_labels(logits, batch["y"]).mean()

    trainer = BaguaTrainer(loss_fn, optax.sgd(0.1), GradientAllReduceAlgorithm(),
                           mesh=mesh, autotune=False)
    state = trainer.init(params)
    state, l0 = trainer.train_step(state, {"x": x, "y": y})
    trainer._apply_recommendation(BaguaHyperparameter(algorithm="bytegrad"))
    assert trainer.algorithm.name == "bytegrad"
    losses = []
    for _ in range(10):
        state, loss = trainer.train_step(state, {"x": x, "y": y})
        losses.append(float(loss))
    assert losses[-1] < float(l0)


def test_autotune_level_zero_is_passthrough(service_client):
    service, client = service_client
    service.autotune_level = 0
    decls = [t.model_dump() for t in tensor_list(n=4, numel=100)]
    rsp = client.register_tensors("m3", decls)
    first = rsp["recommended_hyperparameters"]
    for it in range(3):
        for rank in range(2):
            rsp = client.ask_hyperparameters("m3", rank, it + 1)
        assert rsp["recommended_hyperparameters"] == first
        assert rsp["is_autotune_completed"] is False
